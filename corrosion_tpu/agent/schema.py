"""Schema management: constrained CREATE TABLE application + migration.

Parity: ``crates/corro-types/src/schema.rs`` — the reference parses the
user's schema SQL, **constrains** it (``schema.rs:115-172``: no foreign
keys, no unique indexes, every NOT NULL column needs a DEFAULT, primary
keys must be plain columns), then diffs against the live schema and
migrates (``apply_schema``, ``schema.rs:276-530``: new tables become CRRs,
new columns are added in place, destructive changes are rejected).

Design: instead of a SQL AST parser we apply the candidate schema to a
scratch in-memory database and introspect it with PRAGMAs — the database
itself is the parser.  The same introspection drives the diff.
"""

from __future__ import annotations

import re
import sqlite3
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


class SchemaError(Exception):
    pass


@dataclass(frozen=True)
class Column:
    name: str
    type: str
    notnull: bool
    default: Optional[str]
    pk_index: int  # 0 = not part of pk


@dataclass(frozen=True)
class TableSchema:
    name: str
    columns: Tuple[Column, ...]
    sql: str

    @property
    def pk_cols(self) -> Tuple[str, ...]:
        return tuple(
            c.name for c in sorted(
                (c for c in self.columns if c.pk_index), key=lambda c: c.pk_index
            )
        )


@dataclass(frozen=True)
class Schema:
    tables: Dict[str, TableSchema]
    # non-unique secondary indexes: name -> CREATE INDEX sql
    # (schema.rs applies these alongside tables; unique ones are
    # rejected by constrain())
    indexes: Dict[str, str] = None  # type: ignore[assignment]


def parse_schema(sql: str) -> Schema:
    """Apply the schema SQL to a scratch db and introspect the result."""
    scratch = sqlite3.connect(":memory:")
    try:
        try:
            scratch.executescript(sql)
        except sqlite3.Error as e:
            raise SchemaError(f"schema SQL failed: {e}") from e
        return _introspect(scratch)
    finally:
        scratch.close()


# the CRR machinery interpolates table/column names into bookkeeping
# DDL and cached hot-path SQL as plain quoted identifiers — word
# identifiers only, enforced HERE so a hostile schema (user input via
# config or the schema API) is rejected cleanly at apply time instead
# of surfacing as a SQL syntax error mid-introspection (or worse,
# splicing into trigger bodies)
_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _introspect(conn: sqlite3.Connection) -> Schema:
    tables: Dict[str, TableSchema] = {}
    for name, create_sql in conn.execute(
        "SELECT name, sql FROM sqlite_master WHERE type='table' "
        "AND name NOT LIKE 'sqlite_%' AND name NOT LIKE '\\_\\_corro\\_%' ESCAPE '\\'"
    ).fetchall():
        if not _IDENT_RE.match(name):
            raise SchemaError(
                f"table name {name!r} is not a plain identifier "
                "([A-Za-z_][A-Za-z0-9_]*): quoted/special names cannot "
                "be CRRs"
            )
        cols = []
        for cid, cname, ctype, notnull, dflt, pk in conn.execute(
            f'PRAGMA table_info("{name}")'
        ):
            if not _IDENT_RE.match(cname):
                raise SchemaError(
                    f"table {name}: column name {cname!r} is not a "
                    "plain identifier"
                )
            cols.append(
                Column(
                    name=cname,
                    type=(ctype or "").upper(),
                    notnull=bool(notnull),
                    default=dflt,
                    pk_index=pk,
                )
            )
        tables[name] = TableSchema(name=name, columns=tuple(cols), sql=create_sql)
    # CRR bookkeeping lives in "<table>__corro_*" tables/indexes —
    # substring match, or re-applying a schema would drop them
    indexes = dict(
        conn.execute(
            "SELECT name, sql FROM sqlite_master WHERE type='index' "
            "AND sql IS NOT NULL AND name NOT LIKE 'sqlite_%' "
            "AND name NOT LIKE '%\\_\\_corro\\_%' ESCAPE '\\' "
            "AND tbl_name NOT LIKE '%\\_\\_corro\\_%' ESCAPE '\\'"
        ).fetchall()
    )
    return Schema(tables=tables, indexes=indexes)


def constrain(schema: Schema, scratch_sql: str) -> None:
    """Reject schema constructs that can't replicate conflict-free."""
    scratch = sqlite3.connect(":memory:")
    try:
        scratch.executescript(scratch_sql)
        for name, ts in schema.tables.items():
            if not ts.pk_cols:
                raise SchemaError(f"table {name}: a primary key is required")
            fks = scratch.execute(f'PRAGMA foreign_key_list("{name}")').fetchall()
            if fks:
                raise SchemaError(
                    f"table {name}: foreign keys are not supported in CRR tables"
                )
            for idx_name, unique, origin in (
                (r[1], r[2], r[3])
                for r in scratch.execute(f'PRAGMA index_list("{name}")')
            ):
                # origin 'pk' is the implicit primary-key index; explicit
                # UNIQUE constraints/indexes can't merge deterministically
                if unique and origin != "pk":
                    raise SchemaError(
                        f"table {name}: unique index {idx_name} is not "
                        "supported in CRR tables"
                    )
            for col in ts.columns:
                if col.pk_index:
                    if not col.notnull:
                        raise SchemaError(
                            f"table {name}: primary key column {col.name} "
                            "must be NOT NULL"
                        )
                    continue
                if col.notnull and col.default is None:
                    raise SchemaError(
                        f"table {name}: NOT NULL column {col.name} needs a "
                        "DEFAULT for conflict-free replication"
                    )
    finally:
        scratch.close()


def apply_schema(cr_conn, sql: str) -> List[str]:
    """Create/migrate CRR tables from a schema file's SQL.

    Returns the list of touched table names.  New tables are created and
    marked CRR; existing tables gain missing columns via ALTER TABLE ADD
    COLUMN; column removals/type changes are rejected.
    """
    target = parse_schema(sql)
    constrain(target, sql)
    live = _introspect(cr_conn.conn)
    touched: List[str] = []
    for name, ts in target.tables.items():
        if name not in live.tables:
            cr_conn.conn.execute(ts.sql)
            cr_conn.as_crr(name)
            touched.append(name)
            continue
        have = {c.name: c for c in live.tables[name].columns}
        want = {c.name: c for c in ts.columns}
        removed = set(have) - set(want)
        if removed:
            raise SchemaError(
                f"table {name}: dropping columns is not supported "
                f"({', '.join(sorted(removed))})"
            )
        added = [c for cn, c in want.items() if cn not in have]
        for c in added:
            if c.pk_index:
                raise SchemaError(
                    f"table {name}: cannot add primary key column {c.name}"
                )
            decl = f'"{c.name}" {c.type}'
            if c.notnull:
                if c.default is None:
                    raise SchemaError(
                        f"table {name}: new NOT NULL column {c.name} needs "
                        "a DEFAULT"
                    )
                decl += f" NOT NULL DEFAULT {c.default}"
            elif c.default is not None:
                decl += f" DEFAULT {c.default}"
            cr_conn.conn.execute(f'ALTER TABLE "{name}" ADD COLUMN {decl}')
            touched.append(name)
        if added:
            # refresh triggers to cover the new columns
            cr_conn.as_crr(name)
    # secondary (non-unique) indexes follow the schema file like
    # tables do (schema.rs:276-530): new ones are created, removed or
    # redefined ones are dropped (+ recreated)
    for iname, isql in sorted((target.indexes or {}).items()):
        if live.indexes.get(iname) == isql:
            continue
        if iname in (live.indexes or {}):
            cr_conn.conn.execute(f'DROP INDEX IF EXISTS "{iname}"')
        cr_conn.conn.execute(isql)
    for iname in sorted(set(live.indexes or {}) - set(target.indexes or {})):
        cr_conn.conn.execute(f'DROP INDEX IF EXISTS "{iname}"')
    return touched
