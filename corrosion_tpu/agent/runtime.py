"""Agent runtime: the full node assembly.

Parity map (SURVEY.md §1):

* layer 5 (SWIM membership): asyncio UDP datagrams — announce, probe/ack
  with nonce matching, ping-req indirect probes, piggybacked member
  gossip, suspicion timeout → down, incarnation refutation
  (reference: foca runtime loop, ``broadcast/mod.rs:122-381``).
* layer 6 (dissemination): changesets gossiped over UDP to a ring0-first
  member sample with retransmit decay and rebroadcast-on-learn
  (``broadcast/mod.rs:405-1028``).
* layer 7 (anti-entropy): TCP sync sessions — handshake states, needs
  algebra, chunked changeset streaming, inbound session semaphore
  (``api/peer.rs:344-1719``).
* layer 8 (ingestion): dedupe cache, complete-version apply, partial
  buffering + promotion, emptyset clearing — all committed atomically
  with bookkeeping (``agent/util.rs:761-1380``).

The transport is length-prefixed JSON (see ``wire.py``) over plain
UDP/TCP — the codec/transport are deliberately isolated behind small
functions so QUIC/mTLS or a native codec can replace them.
"""

from __future__ import annotations

import logging

import asyncio
import hashlib
import os
import random
import sqlite3
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from corrosion_tpu.agent import tracing, wire
from corrosion_tpu.agent.metrics import percentile_sorted
from corrosion_tpu.agent.locks import PRIO_HIGH, PRIO_LOW
from corrosion_tpu.agent.bookkeeping import Bookie
from corrosion_tpu.agent.members import Member, Members, MemberState
from corrosion_tpu.agent.schema import apply_schema
from corrosion_tpu.agent.storage import CrConn, unpack_stmt
from corrosion_tpu.types import (
    ActorId,
    ChangeV1,
    Changeset,
    ChangeSource,
    HLClock,
    SyncNeedV1,
    SyncStateV1,
    Timestamp,
    Version,
)
from corrosion_tpu.types.change import ChunkedChanges, MAX_CHANGES_BYTE_SIZE
from corrosion_tpu.types.actor import ClusterId
from corrosion_tpu.types.payload import BiPayload, BroadcastV1, UniPayload
from corrosion_tpu.agent.transport import MAX_UDP_PAYLOAD
from corrosion_tpu.bridge import speedy
from corrosion_tpu.utils.ranges import RangeSet

# TCP stream preludes: one byte standing in for QUIC's uni/bi stream
# types; every byte after it is exactly the reference's stream content
# (u32-BE LengthDelimited speedy frames).
logger = logging.getLogger("corrosion_tpu.agent")

STREAM_UNI = b"U"
STREAM_BI = b"B"
STREAM_MUX = b"M"  # multiplexed uni+bi channels (agent/mux.py)

# precomputed corro_change_lag_seconds label keys (provenance runs per
# ingested changeset — kwargs packing + sort per call is measurable)
_PROV_KEY_BROADCAST = (("path", "broadcast"),)
_PROV_KEY_REBROADCAST = (("path", "rebroadcast"),)
_PROV_KEY_SYNC = (("path", "sync"),)

# widest seq span a full changeset may legally claim: one local
# transaction's change count is bounded by what sqlite can hold in one
# tx; anything wider is a structurally-impossible (hostile) claim that
# would wedge partial buffering (see _screen_changeset)
_MAX_SEQ_SPAN = 1 << 32


def _changes_digest(changes) -> bytes:
    """Canonical content digest of a changeset's changes — the
    equivocation detector's identity for 'what this (actor, version)
    actually said'.  Sorted by (db_version, seq, table, pk, cid) so
    chunk-reassembly order cannot alias two identical contents apart."""
    h = hashlib.blake2b(digest_size=16)
    for ch in sorted(
        changes,
        key=lambda c: (int(c.db_version), int(c.seq), c.table, c.pk,
                       c.cid),
    ):
        h.update(repr((
            ch.table, ch.pk, ch.cid, ch.val, int(ch.col_version),
            int(ch.db_version), int(ch.seq), ch.site_id, int(ch.cl),
        )).encode())
    return h.digest()


def _sig_message_raw(actor: bytes, version: int, seq0: int, seq1: int,
                     last_seq: int, ts, digest: bytes) -> bytes:
    """The one place the signing-message wire layout lives: every
    signer and every verifier — including the evidence-time rebuild of
    a STORED half (``_stored_sig_message``) — must produce the same
    bytes, or provable signed equivocations silently downgrade to
    bounded-window verdicts."""
    return (
        b"corro-sig-v1"
        + actor
        + struct.pack(
            "<QQQQQ",
            int(version), int(seq0), int(seq1), int(last_seq),
            int(ts) if ts is not None else 0,
        )
        + digest
    )


def sig_message(actor: bytes, cs, digest: bytes = None) -> bytes:
    """The canonical byte string a changeset signature covers: the
    content digest BOUND to (actor, version, seq span, ts).  Binding
    the metadata matters — a signature over the digest alone could be
    replayed under re-written seq claims to wedge partial buffering
    with origin-attributed garbage.  Only FULL changesets are ever
    signed (equivocation is a full-changeset attack; empty/empty-set
    variants carry no content to conflict over).  ``digest`` lets hot
    callers that already computed ``_changes_digest`` skip the
    recompute (the sort+hash is the expensive part of this message)."""
    return _sig_message_raw(
        actor, cs.version, cs.seqs[0], cs.seqs[1], cs.last_seq, cs.ts,
        digest if digest is not None else _changes_digest(cs.changes),
    )


class _SlowPeer(Exception):
    """Sync serving aborted: the peer cannot keep up (peer.rs:796-811)."""


@dataclass
class AgentConfig:
    db_path: str
    gossip_host: str = "127.0.0.1"
    gossip_port: int = 0
    api_host: str = "127.0.0.1"
    api_port: int = 0
    bootstrap: List[str] = field(default_factory=list)
    schema_sql: Optional[str] = None
    cluster_id: int = 0
    # perf knobs (reference defaults in config.rs / broadcast mod)
    probe_interval: float = 0.4
    probe_timeout: float = 0.35
    # periodic membership gossip cadence (foca periodic_gossip; the
    # WAN preset gossips faster than it probes). 0 disables.
    gossip_interval: float = 0.2
    gossip_fanout: int = 3  # targets per gossip round (foca num_members)
    suspect_timeout: float = 2.0  # floor; scaled up with cluster size
    suspicion_mult: int = 4  # suspicion deadline growth multiplier
    num_indirect_probes: int = 3
    fanout: int = 3
    max_transmissions: int = 5
    rebroadcast_delay: float = 0.15
    sync_interval_min: float = 0.5
    sync_interval_max: float = 2.0
    sync_peers: int = 3
    max_sync_sessions: int = 3
    # batched serve pipeline (docs/sync.md): full-range needs resolve
    # versions -> db_versions in one bookkeeping pass, collect whole
    # spans off the event loop on RO-pool connections, and coalesce
    # changeset frames into buffered writes with one drain per budget.
    # False = the per-version parity oracle (bench baseline / tests)
    sync_batched_serve: bool = True
    # group-commit write combining (docs/writes.md): concurrent
    # execute_transaction callers coalesce into one storage-lock hold /
    # one outer transaction (per-client SAVEPOINTs), with ONE change
    # collection per group on a read-only pool connection off the event
    # loop.  False = the per-transaction parity oracle.
    write_group_commit: bool = True
    write_group_max: int = 64  # client batches per combined group
    seen_cache_size: int = 65536
    # ingest pipeline (handlers.rs:742-956 / config.rs:10-45 defaults)
    processing_queue_len: int = 20_000  # bounded, drop-oldest
    apply_queue_len: int = 50           # cost-based batch target
    apply_queue_timeout: float = 0.01   # batching tick
    max_concurrent_applies: int = 5     # apply worker threads
    # columnar CRDT merge kernel (docs/crdts.md "Columnar merge
    # kernel"): batched applies encode to flat arrays and resolve
    # causal/LWW winners via ops/merge.py segment reductions, sharing
    # ONE winner-selection core with the simulator's representation-
    # independence check.  Below the threshold (changes per table
    # batch), or when a hostile batch cannot encode, the per-change
    # dict replay — the parity oracle — runs instead.
    columnar_merge: bool = True
    columnar_merge_min: int = 256
    # device-resident apply (docs/crdts.md "Device-resident apply"):
    # keep hot (pk, cid) clock state in cross-batch device arrays and
    # flush SQLite through the write-behind journal.  None = auto —
    # enabled only when JAX is loaded with a non-CPU backend (default
    # OFF on CPU-only hosts); True forces it on (NumPy store when no
    # accelerator), False forces the classic prefetch path.
    device_cache: Optional[bool] = None
    device_cache_slots: int = 262144
    # broadcast buffering + governor (broadcast/mod.rs:399-458,745-801)
    bcast_buffer_cutoff: int = 64 * 1024
    bcast_flush_interval: float = 0.5
    bcast_rate_limit: float = 10 * 1024 * 1024  # bytes/s
    bcast_max_pending: int = 500        # drop-oldest-most-sent beyond this
    api_authz: Optional[str] = None
    subs_enabled: bool = True
    subs_path: Optional[str] = None
    subs_shards: int = 4                # matcher worker shards (by sub_id)
    subs_columnar: bool = True          # columnar wave matching fast path
    subs_shard_max_pending: int = 50_000  # per-shard depth before overflow
    admin_path: Optional[str] = None
    # append finished spans as OTLP-flavored JSON lines ([telemetry.traces]);
    # bounded: one rotation at max_bytes, drops counted after that
    trace_export_path: Optional[str] = None
    trace_export_max_bytes: int = 64 * 1024 * 1024
    # -- convergence observability plane (docs/telemetry.md) -----------
    # change provenance: on a version's FIRST arrival, record
    # origin-commit -> apply lag (corro_change_lag_seconds{path=
    # broadcast|rebroadcast|sync}) and per-origin-actor staleness —
    # the agent measures its own convergence
    provenance: bool = True
    # evict an origin actor's staleness entry once nothing has been
    # applied from it for this long AND it is no longer an alive
    # member: a departed (or identity-renewed, e.g. `cluster rejoin`)
    # actor must not leave a permanently rising
    # corro_change_staleness_seconds{actor_id=} series — and unbounded
    # label cardinality — on every node that ever applied its writes,
    # while a live-but-unconverged actor keeps alerting.  0 disables.
    staleness_evict_s: float = 600.0
    # carry hop + traceparent on uni broadcasts via the versioned
    # envelope (bridge/speedy.py encode_traced_uni): the write-group /
    # collect / remote-apply spans share one trace id, and receivers
    # can label lag broadcast vs rebroadcast.  Old-format payloads
    # always decode; turn this OFF for reference-byte-exact wire output
    bcast_trace_propagation: bool = True
    # always-on event-loop stall probe (agent/health.py, the bench
    # stall gates made continuous): corro_loop_stall_ms histogram +
    # max gauge + slow-callback attribution.  0 disables.
    stall_probe_interval: float = 0.05
    stall_probe_slow_ms: float = 50.0
    # flight recorder (agent/recorder.py, docs/telemetry.md): periodic
    # HLC-stamped metric snapshots + the typed event journal in a
    # bounded in-memory ring, merged cluster-wide by
    # ClusterObserver.flight_timeline.  0 disables the whole recorder
    # (snapshots AND journal).  The optional jsonl export reuses the
    # spans-export rotation/drop discipline ([telemetry.flight] path).
    flight_interval_s: float = 1.0
    flight_ring_max: int = 512
    flight_export_path: Optional[str] = None
    flight_export_max_bytes: int = 64 * 1024 * 1024
    # HLC clock skew (the scenario matrix's clock-skew fault family,
    # types/hlc.py skewed_now_ns): constant offset + linear drift
    # applied to THIS node's HLClock physical source.  Zero in
    # production — set per node by devcluster from the FaultPlan.
    clock_skew_ns: int = 0
    clock_drift: float = 0.0
    # equivocation defense (docs/faults.md): screen structurally-
    # impossible seq spans, detect conflicting contents re-claiming an
    # accepted (actor, version) via bounded content digests, and
    # quarantine the hostile actor (Members path) — dropping its
    # further changesets so it cannot poison CRDT state
    equivocation_detection: bool = True
    # how long an UNSIGNED equivocation quarantine holds before the
    # actor's traffic is admitted again (re-offense re-quarantines:
    # the digests survive).  The bounded window applies ONLY to
    # conflicts whose attribution could not be cryptographically
    # proven — a hostile relay can forge an unsigned actor id, so an
    # unbounded drop-all would let one forged message inflict
    # permanent divergence.  A VERIFIED signed conflicting pair (both
    # contents signed by the origin's key, types/crypto.py) is an
    # unframeable proof: that verdict is PERMANENT
    # (quarantine_reason="signed_equivocation", persisted across
    # restarts) and ignores this window.  0 = forever even unsigned
    # (only for harnesses that control every message source).
    equiv_quarantine_s: float = 300.0
    # -- signed changeset attribution (docs/faults.md) ------------------
    # 32-byte Ed25519 seed (types/crypto.py) signing this node's OWN
    # full changesets on the broadcast path; None (and no key file) =
    # unsigned, wire byte-exact vs the pre-signing envelope
    sig_secret: Optional[bytes] = None
    # production path: hex-encoded 32-byte seed on disk (chmod 600)
    sig_key_file: Optional[str] = None
    # trust directory: origin actor id -> 32-byte Ed25519 public key.
    # Verification only ever runs for actors present here; the agent
    # keeps a live REFERENCE (not a copy) so a harness can extend the
    # shared directory after boot
    sig_pubkeys: Optional[Dict[bytes, bytes]] = None
    # verify-on-evidence posture: signatures are verified when the
    # digest screen fires, when the span screen trips, and on a
    # bounded random spot check — hash-sampled per (node, actor,
    # version) at this rate (deterministic, no rng stream), and
    # additionally spaced at least sig_spot_check_min_interval_s apart
    # so pure-Python verification (~ms each) stays a tripwire, never
    # an ingest tax (the APPLY_BENCH sig A/B holds the ≥0.95 gate at
    # these defaults).  0 disables spot checks (evidence-driven
    # verification stays on)
    sig_spot_check_rate: float = 0.0
    sig_spot_check_min_interval_s: float = 0.5
    # evidence-triggered verification budget: conflicting duplicates
    # and span-screen trips admit at most this many verifications per
    # second (token bucket, burst 2x) — without it an attacker who
    # mutates one byte per replayed copy manufactures a ~ms verify per
    # message inside the apply workers.  Over budget the conflicting
    # duplicate is DROPPED with no verdict (it was never going to
    # apply; counted result=skipped) rather than falling back to the
    # unsigned bounded-window path, which would let the flood frame
    # the origin.  0 disables the budget (every evidence fires a
    # verify)
    sig_evidence_verify_rate: float = 64.0
    # -- Byzantine sync-serve client hardening (docs/faults.md) ---------
    # total wall/virtual budget for one outbound sync session: a
    # hostile server trickling one byte per read-timeout window would
    # otherwise hold a session (and its needs) hostage forever.
    # 0 disables the deadline
    sync_session_deadline_s: float = 60.0
    # -- snapshot bootstrap (docs/sync.md, agent/snapshot.py) -----------
    # serve side: answer snap_request sessions with a consistent
    # VACUUM-INTO copy (scrubbed via the shared snapshot registry) and
    # advertise per-actor snapshot floors in the sync handshake
    snapshot_serve: bool = True
    # client side: dispatch to snapshot install when a server's floors
    # cover needs it can no longer serve change-by-change; off = this
    # node only ever bootstraps change-by-change
    snapshot_install: bool = True
    # snap_chunk payload size on the serve stream
    snapshot_chunk_bytes: int = 256 * 1024
    # client-side offer screen: an advertised snapshot larger than this
    # is rejected before a byte is staged (reason=snap_offer)
    snapshot_max_bytes: int = 1 << 30
    # serve-side build cache: a restart storm re-serves ONE snapshot
    # file for this long instead of re-vacuuming per reborn client
    snapshot_cache_s: float = 5.0
    # history compaction: the snapshot floor advances to the contained
    # prefix minus this retain window — the newest `retain` versions
    # stay servable change-by-change (cheap incremental catch-up);
    # everything below the floor compacts its per-version bookkeeping
    # and is only obtainable via snapshot.  Negative disables floor
    # advancement entirely
    snapshot_retain_versions: int = 2000
    # maintenance-driven compaction cadence (docs/sync.md): the sweep
    # that finds overwritten versions AND advances snapshot floors runs
    # on its own loop at this interval, so an idle-but-serving node's
    # cleared spans and floor keep moving without a local write to
    # trigger the post-commit sweep.  0 disables the dedicated loop
    # (the slower maintenance_interval pass still runs it)
    compaction_interval: float = 30.0
    pg_port: Optional[int] = None  # PostgreSQL wire protocol (None = off)
    pg_host: Optional[str] = None  # PG bind host (None = api_host)
    # PG TLS client-cert verification is its OWN knob (corro-pg
    # verify_client): gossip mTLS must not lock psql-style clients out
    # of the SQL port
    pg_tls_verify_client: bool = False
    maintenance_interval: float = 60.0
    wal_truncate_pages: int = 250_000  # ~1 GB at 4 KiB pages
    vacuum_free_pages: int = 10_000
    # test-only instrumentation: prefix every uni frame with a 1-byte
    # hop count so a harness can measure real dissemination depth.
    # MUST stay off for reference-byte-exact wire compatibility.
    debug_hops: bool = False
    # ring0-first fanout for local changes (broadcast/mod.rs:586-643).
    # Calibration harnesses disable it so agents match the simulator's
    # uniform-sampling model (on loopback EVERY peer is ring0).
    ring0_enabled: bool = True
    # one multiplexed TCP connection per peer for uni + bi channels
    # (transport.rs single-QUIC-connection parity); off = one
    # connection per channel class (the round-4 wiring)
    transport_mux: bool = True
    # LRU cap on cached outbound uni connections (fd budget)
    uni_cache_size: int = 512
    # degraded-mode hardening knobs: bounded redials of dead cached
    # connections (utils.backoff decorrelated jitter), and the per-peer
    # circuit breaker that quarantines persistently-failing addresses
    # so one dead node cannot stall a broadcast flush round
    connect_timeout: float = 2.0
    redial_retries: int = 2
    redial_base: float = 0.05
    redial_cap: float = 0.5
    breaker_threshold: int = 5
    breaker_cooldown: float = 3.0
    # SWIM datagram format: "foca" = binary foca messages, the wire the
    # reference relays verbatim (broadcast/mod.rs:185-324, via
    # bridge/foca.py); "json" = the legacy debuggable envelope.
    # Receivers accept both (sniffed by first byte) regardless.
    swim_wire: str = "foca"
    # TLS over the gossip/sync TCP streams (main.rs:707-760 tooling,
    # peer.rs:128-318 rustls config). Off unless tls_cert_file is set;
    # SWIM datagrams stay plaintext UDP (see agent/tls.py).
    tls_cert_file: Optional[str] = None
    tls_key_file: Optional[str] = None
    tls_ca_file: Optional[str] = None
    tls_insecure: bool = False  # skip server-cert verification
    tls_client_required: bool = False  # mTLS: peers must present certs
    tls_client_cert_file: Optional[str] = None
    tls_client_key_file: Optional[str] = None
    # the one injectable time source (corrosion_tpu/clock.py) behind
    # every agent timer: sleeps, monotonic state stamps, wall clocks
    # and the HLC physical source.  None = SYSTEM_CLOCK — real time,
    # behavior- and wire-byte-identical to the pre-clock agent
    clock: Optional[object] = None
    # fixed site (actor) id for a FRESH database; None = random uuid4
    # as before.  The virtual-time cluster derives ids from its seed so
    # two runs of one campaign are byte-identical; a restart from an
    # existing directory keeps the persisted id either way
    site_id: Optional[bytes] = None


async def _cancel_tasks(tasks, rounds: int = 5, timeout: float = 2.0):
    """Cancel ``tasks`` and wait until every one actually exits.

    A single cancel + gather is not enough on Python < 3.11:
    ``asyncio.wait_for`` can swallow a cancellation that races the
    inner future's completion (bpo-37658) — e.g. a probe ack landing
    in the same loop cycle as ``stop()``'s cancel — leaving a periodic
    loop task alive and the gather pending FOREVER.  Re-cancel each
    round until the set drains (bounded, so a truly stuck task can't
    hold shutdown hostage either)."""
    pending = list(tasks)
    for t in pending:
        t.cancel()
    for _ in range(rounds):
        if not pending:
            break
        done, pend = await asyncio.wait(pending, timeout=timeout)
        for t in done:
            if not t.cancelled():
                t.exception()  # retrieve, never raise at shutdown
        pending = list(pend)
        for t in pending:
            t.cancel()
    return pending


class Agent:
    """A full node: storage + bookkeeping + gossip + sync (+ HTTP API)."""

    def __init__(self, config: AgentConfig):
        self.config = config
        from corrosion_tpu.agent.locks import LockRegistry
        from corrosion_tpu.clock import SYSTEM_CLOCK

        # the injectable time source (docs/sim.md, virtual time): every
        # timer/stamp below reads THIS, so a virtual-time campaign can
        # drive hundreds of agents off one event heap
        self._clock = config.clock or SYSTEM_CLOCK
        # lock tracking costs a few ops per acquisition on the hottest
        # lock; only pay for it when the admin surface can read it
        self.lock_registry = LockRegistry()
        # crash-safe snapshot install (agent/snapshot.py): a node
        # killed at ANY install point classifies here BEFORE storage
        # opens — either the swap completed (boot into the installed
        # snapshot + tail sync) or the sidecar/journal are discarded
        # (boot into the untouched previous database + clean retry)
        from corrosion_tpu.agent import snapshot as snaplib

        self._snap_recovered = snaplib.recover_pending_install(
            config.db_path
        )
        self.storage = CrConn(
            config.db_path,
            site_id=config.site_id,
            lock_registry=self.lock_registry if config.admin_path else None,
        )
        self.bookie = Bookie(self.storage.conn, lock=self.storage._lock)
        # restart = resume: an older DB may predate __corro_sync_state
        self.bookie.backfill_own_sync_state(self.storage.site_id)
        if config.clock_skew_ns or config.clock_drift:
            from corrosion_tpu.types.hlc import skewed_now_ns

            self.clock = HLClock(now_ns=skewed_now_ns(
                config.clock_skew_ns, config.clock_drift,
                base=self._clock.wall_ns,
            ))
        else:
            self.clock = HLClock(now_ns=self._clock.wall_ns)
        self.actor_id = self.storage.site_id
        self.members = Members(self.actor_id, clock=self._clock)
        from corrosion_tpu.agent.metrics import Metrics

        self.metrics = Metrics()
        # columnar merge dispatch + merge-phase timing sink
        # (corro_apply_merge_seconds{kernel=}) for the storage layer
        self.storage.metrics = self.metrics
        self.storage.columnar_merge = config.columnar_merge
        self.storage.columnar_merge_min = config.columnar_merge_min
        dev_on = config.device_cache
        if dev_on is None:
            from corrosion_tpu.ops.devcache import default_enabled

            dev_on = default_enabled()
        if dev_on:
            self.storage.enable_device_cache(
                slots=config.device_cache_slots
            )
        if self.storage.flush_journal_recovered:
            # boot classified the crash window between a committed
            # device-merge and its async flush (storage replayed the
            # journal before we got here)
            self.metrics.counter(
                "corro_apply_flush_recoveries_total",
                float(self.storage.flush_journal_recovered),
            )
        if self._snap_recovered is not None:
            self.metrics.counter(
                "corro_snapshot_recoveries_total",
                stage=self._snap_recovered,
            )
        # snapshot serve cache + build serialization (one VACUUM at a
        # time; a restart storm's clients share the cached file)
        self._snap_cache: Optional[tuple] = None
        self._snap_build_lock = threading.Lock()
        self._members_table()
        # incarnation survives restarts one-higher: a gracefully-left
        # node re-announces ALIVE above the DOWN record peers hold for
        # its previous life, so rejoin is immediate (foca renew())
        self.incarnation = self._load_incarnation() + 1
        self._persist_incarnation()
        # foca identity generation: our Actor.ts; a renewed (rejoined)
        # identity carries a fresh ts (actor.rs renew())
        self._identity_ts = int(self.clock.new_timestamp())
        # per-peer identity ts + per-update transmission counts (foca's
        # freshness-prioritized update backlog)
        self._swim_ts: Dict[bytes, int] = {}
        self._swim_update_tx: Dict[bytes, int] = {}
        self._probe_seq = 0  # wrapping u16 ProbeNumber counter
        self._seen: Dict[tuple, None] = {}
        # apply workers call handle_change concurrently; the seen cache's
        # check/insert/evict must be atomic across them
        self._seen_lock = threading.Lock()
        # debug_hops: seen-key -> hop depth at first receipt (harness
        # reads this to measure real dissemination depth)
        self._recv_hops: Dict[tuple, int] = {}
        # change provenance (first-seen dedupe): (actor, version) pairs
        # whose first-arrival lag was already recorded, FIFO-bounded
        # like the broadcast dedup cache; plus the freshest origin
        # wall-clock ts seen per actor (the staleness gauge's base)
        self._prov_seen: Dict[tuple, None] = {}
        self._prov_lock = threading.Lock()
        self._origin_ts_wall: Dict[bytes, float] = {}
        # LOCAL wall time of the most recent applied write per origin
        # actor — the eviction clock (idle time), deliberately separate
        # from the origin HLC ts above: evicting on origin-ts age would
        # delete a rising staleness series at exactly the moment its
        # "stopped converging" alert should fire
        self._origin_seen_wall: Dict[bytes, float] = {}
        # equivocation defense state: accepted-content digest per
        # (actor, version) — bounded FIFO like the dedup caches — and
        # the actors quarantined for hostile traffic (their further
        # changesets drop at _pre_change until the verdict's deadline;
        # actor -> monotonic expiry, inf when equiv_quarantine_s=0)
        self._equiv_digests: Dict[tuple, bytes] = {}
        self._equiv_lock = threading.Lock()
        self._equiv_quarantined: Dict[bytes, float] = {}
        # actors under a SIGNED (proof-backed) verdict: drives the
        # one-time escalation relabel in _note_equivocation
        self._equiv_proofed: set = set()
        # signed changeset attribution (docs/faults.md): this node's
        # Ed25519 identity (None = unsigned, wire byte-exact), the
        # trust directory (a live REFERENCE — harnesses extend it
        # after boot and respawns see the additions), accepted-content
        # signatures remembered next to the digests (the raw material
        # of a signed-equivocation proof), an own-signature cache (one
        # sign per local version, not per retransmission), and the
        # spot-check interval bound
        self._sig_secret: Optional[bytes] = None
        self._sig_pub: Optional[bytes] = None
        secret = config.sig_secret
        if secret is None and config.sig_key_file:
            with open(config.sig_key_file) as f:
                secret = bytes.fromhex(f.read().strip())
        if secret is not None:
            from corrosion_tpu.types import crypto

            self._sig_secret = bytes(secret)
            self._sig_pub = crypto.public_key(self._sig_secret)
        self._sig_pubkeys: Dict[bytes, bytes] = (
            config.sig_pubkeys if config.sig_pubkeys is not None else {}
        )
        self._equiv_sigs: Dict[tuple, bytes] = {}  # (actor,v) -> 64-byte sig
        self._sig_own_cache: Dict[int, bytes] = {}
        self._sig_last_spot = float("-inf")
        # evidence-verification token bucket (sig_evidence_verify_rate)
        self._sig_ev_tokens = 2.0 * config.sig_evidence_verify_rate
        self._sig_ev_stamp = self._clock.monotonic()
        # guards the bucket and the spot-check stamp: apply workers
        # race their read-modify-writes, and an unsynchronized bucket
        # admits more ~ms verifies than the rate it exists to enforce
        self._sig_lock = threading.Lock()
        # digests survive restarts (__corro_equiv_digests): an
        # equivocator must not be able to wait out a reboot of its
        # victim — the conflicting re-send after a restart compares
        # against the RELOADED digest and re-quarantines immediately.
        # Gated: with detection off nothing ever reads or writes them
        if config.equivocation_detection:
            self._load_equiv_digests()
        # loop health probe (agent/health.py), created on start()
        self.health = None
        # flight recorder (agent/recorder.py): created NOW — event
        # seams fire before start() (e.g. a bootstrap breaker open) and
        # the journal must hold them; the snapshot loop starts with the
        # other tasks.  flight_interval_s = 0 disables the plane.
        if config.flight_interval_s > 0:
            from corrosion_tpu.agent.recorder import FlightRecorder

            self.flight = FlightRecorder(
                self.metrics, self.clock,
                timebase=self._clock,
                interval=config.flight_interval_s,
                ring_max=config.flight_ring_max,
                export_path=config.flight_export_path,
                export_max_bytes=config.flight_export_max_bytes,
                crash_path=os.path.join(
                    os.path.dirname(config.db_path) or ".",
                    "flight_crash.jsonl",
                ),
            )
        else:
            self.flight = None
        # live sync sessions (client + server), admin `sync_sessions`:
        # id -> {role, peer, started (monotonic), needs_total,
        # needs_done, bytes}
        self._sync_live: Dict[int, dict] = {}
        self._sync_sess_seq = 0
        self._trace_token = None  # export ownership (set in start())
        self._trace_dropped_seen = 0  # last synced export-drop total
        self._acks: Dict[int, asyncio.Future] = {}
        self._suspects: Dict[bytes, float] = {}
        self._bcast_queue: asyncio.Queue = asyncio.Queue()
        # guards the _loop-is-set check vs start()'s flush of deferred
        # broadcasts (writes can come from any HTTP thread)
        self._bcast_gate = threading.Lock()
        self._pre_start_broadcasts: List[tuple] = []
        self._pre_start_cvs: List[ChangeV1] = []
        # bounded ingest queue (processing_queue_len, drop-oldest) drained
        # by the change loop in cost-based batches off the event loop
        from collections import deque

        self._ingest: "deque" = deque()
        self._ingest_event: Optional[asyncio.Event] = None
        self._apply_pool = None  # ThreadPoolExecutor, created on start
        self._apply_inflight: set = set()  # up to max_concurrent_applies
        self._apply_gauge_lock = threading.Lock()
        self._apply_active = 0  # batches currently executing (threads)
        self._apply_max_overlap = 0  # high-water mark, for tests/metrics
        self._bcast_wakeups = 0  # broadcast-loop iterations (idle = 0/s)
        self.transport = None  # Transport, created on start
        self._conn_tasks: set = set()  # live inbound connection handlers
        self._tasks: List[asyncio.Task] = []
        self._udp: Optional[asyncio.DatagramTransport] = None
        self._tcp: Optional[asyncio.AbstractServer] = None
        self._sync_sem: Optional[asyncio.Semaphore] = None
        # generate_sync snapshot cache keyed on the bookie generation
        # (dirty flag): (gen, SyncStateV1) — see generate_sync()
        self._sync_gen_cache: Optional[tuple] = None
        # serve-side collection workers (lazy: tests drive _serve_need
        # without start()); distinct from the apply pool so a long
        # backfill serve can't starve change application
        self._serve_pool = None
        # group-commit write combiner (agent/writes.py): callers of
        # execute_transaction coalesce into shared commits; the leader
        # is always a caller thread, so this works without the loop
        from corrosion_tpu.agent.writes import WriteCombiner

        self._write_combiner = WriteCombiner(
            self, max_group=config.write_group_max
        )
        # single-thread local-broadcast collection worker (lazy): keeps
        # collect_changes + chunk encoding for local commits OFF the
        # event loop while preserving version order of on_change fanout
        self._wbcast_pool = None
        self._wbcast_lock = threading.Lock()
        self._wbcast_closed = False  # stop(): no lazy pool rebirth
        self._sync_server_sessions = 0  # in-flight inbound sessions
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        if config.schema_sql:
            apply_schema(self.storage, config.schema_sql)
        # register any pending backfill versions — from this boot's schema
        # apply OR left over from a crash before registration completed
        self._register_backfills()
        self._rng = random.Random(int.from_bytes(self.actor_id[:4], "big"))
        self._http = None
        self.gossip_addr: Tuple[str, int] = (config.gossip_host, config.gossip_port)
        self.api_addr: Tuple[str, int] = (config.api_host, config.api_port)
        self.on_change = None  # hook(ChangeV1) for subscriptions layer
        # fault injection (corrosion_tpu.faults): the controller and the
        # per-agent hook, installed by devcluster/chaos harnesses before
        # start(); None in production
        self.faults = None  # FaultController (introspection/admin)
        self.fault_filter = None  # hook(channel, addr) -> FaultAction
        self.subs = None  # SubsManager, attached by setup when enabled
        self._admin = None
        self._pg = None
        self.pg_addr: Optional[Tuple[str, int]] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def _flight_event(self, kind: str, /, **attrs) -> None:
        """Journal one typed event into the flight ring (no-op when the
        recorder is disabled).  Emission sites are the protocol seams —
        see recorder.EVENT_KINDS for the registry.  ``kind`` is
        positional-only: several events legitimately carry a ``kind``
        ATTRIBUTE (e.g. an equivocation verdict's detection kind)."""
        f = self.flight
        if f is None:
            return
        try:
            f.event(kind, **attrs)
        except Exception:
            # telemetry must never break the seam it observes: the
            # emission sites sit inside quarantine/fallback/serve paths
            # whose correctness outranks the journal.  Counted loudly —
            # a silent journaling bug would hollow out the flight ring
            self.metrics.counter("corro_flight_journal_errors_total")
            logger.exception("flight event %r failed", kind)

    def _spawn_task(self, coro, name: str) -> asyncio.Task:
        """Create one long-lived agent task under the crash-dump
        supervisor: an UNHANDLED exception (not cancellation — the
        agent owns those) flushes the flight ring to disk before the
        task dies, so the history leading up to a dead loop survives
        it instead of evaporating with the process state."""
        async def supervised():
            try:
                await coro
            except asyncio.CancelledError:
                raise
            except BaseException as e:
                logger.exception("agent task %r died", name)
                if self.flight is not None:
                    self.flight.crash_dump(
                        f"task {name}: {type(e).__name__}: {e}"
                    )
                raise

        return asyncio.create_task(supervised())

    async def start(self) -> None:
        if self.config.trace_export_path:
            self._trace_token = tracing.configure_export(
                self.config.trace_export_path,
                max_bytes=self.config.trace_export_max_bytes,
            )
            # baseline against the process-lifetime drop total: drops
            # from a PREVIOUS owner's sink are not this agent's to claim
            self._trace_dropped_seen = tracing.export_dropped_total()
        # publish the loop and drain deferred broadcasts atomically, so a
        # concurrent writer either defers (and is flushed below) or sees
        # the live loop — never a stranded append
        with self._bcast_gate:
            self._loop = asyncio.get_running_loop()
            pending = self._pre_start_broadcasts
            self._pre_start_broadcasts = []
            pending_cvs = self._pre_start_cvs
            self._pre_start_cvs = []
        if pending:
            # deferred pre-start commits: collection runs on the
            # write-bcast worker, never on the event loop starting up
            # (start() precedes stop(), so the pool can't be closed)
            self._wbcast_executor().submit(
                self._broadcast_local_commits, pending
            )
        for cv, tp in pending_cvs:
            self.metrics.counter(
                "corro_channel_sends_total", channel="bcast")
            self._bcast_queue.put_nowait(
                (cv, self.config.max_transmissions, 0, tp, None)
            )
        self._sync_sem = asyncio.Semaphore(self.config.max_sync_sessions)
        self._ingest_event = asyncio.Event()
        from concurrent.futures import ThreadPoolExecutor

        from corrosion_tpu.agent.transport import Transport

        self._apply_pool = ThreadPoolExecutor(
            max_workers=self.config.max_concurrent_applies,
            thread_name_prefix="corro-apply",
        )
        from corrosion_tpu.agent.tls import contexts_from_config

        tls_server_ctx, tls_client_ctx = contexts_from_config(self.config)
        self.transport = Transport(
            metrics=self.metrics, on_rtt=self._record_rtt,
            max_cached=self.config.uni_cache_size,
            ssl_context=tls_client_ctx,
            mux=self.config.transport_mux,
            connect_timeout=self.config.connect_timeout,
            redial_retries=self.config.redial_retries,
            redial_base=self.config.redial_base,
            redial_cap=self.config.redial_cap,
            breaker_threshold=self.config.breaker_threshold,
            breaker_cooldown=self.config.breaker_cooldown,
            on_breaker=self._on_breaker,
            # seeded off the actor id: det-mode replays draw the same
            # redial backoff schedule (utils/backoff.retry)
            rng=random.Random(
                int.from_bytes(self.actor_id[4:8], "big") ^ 0x5EED
            ),
            clock=self._clock,
        )
        if self.fault_filter is not None:
            self.transport.fault_filter = self.fault_filter
        # one gossip port for both datagrams (SWIM) and streams, like the
        # reference's single QUIC/UDP endpoint; with an ephemeral port the
        # TCP side of the pair may be taken by someone else — rebind
        for attempt in range(16):
            self._udp, _ = await self._loop.create_datagram_endpoint(
                lambda: _UdpProtocol(self),
                local_addr=(self.config.gossip_host, self.config.gossip_port),
            )
            self.gossip_addr = self._udp.get_extra_info("sockname")[:2]
            try:
                self._tcp = await asyncio.start_server(
                    self._serve_tcp, self.config.gossip_host,
                    self.gossip_addr[1],
                    ssl=tls_server_ctx,
                )
                break
            except OSError:
                self._udp.close()
                self._udp = None
                if self.config.gossip_port != 0 or attempt == 15:
                    raise
        self._load_members()
        # persisted members loaded AFTER the proof reload in __init__:
        # re-assert the permanent signed verdicts on the records that
        # just appeared (the boot-time set_quarantined no-op'd on them).
        # Keyed on the explicit proof set, NOT deadline == inf: with
        # equiv_quarantine_s=0 UNSIGNED verdicts park at inf too, and
        # a pre-run() unsigned verdict must never boot-relabel a
        # possibly-framed actor as a proven signed equivocator
        with self._equiv_lock:
            proven = [
                actor for actor in self._equiv_quarantined
                if actor in self._equiv_proofed
            ]
        for actor in proven:
            self.members.set_quarantined(
                actor, True, reason="signed_equivocation"
            )
        if self.config.subs_enabled:
            from corrosion_tpu.agent.pubsub import SubsManager

            self.subs = SubsManager(self, self.config.subs_path)
        self._tasks = [
            self._spawn_task(self._announce_loop(), "announce"),
            self._spawn_task(self._probe_loop(), "probe"),
            self._spawn_task(self._suspect_reaper(), "suspect"),
            self._spawn_task(self._gossip_loop(), "gossip"),
            self._spawn_task(self._broadcast_loop(), "broadcast"),
            self._spawn_task(self._change_loop(), "change"),
            self._spawn_task(self._sync_loop(), "sync"),
            self._spawn_task(self._maintenance_loop(), "maintenance"),
        ]
        if self.config.compaction_interval > 0:
            self._tasks.append(
                self._spawn_task(self._compaction_loop(), "compaction")
            )
        if self.config.stall_probe_interval > 0:
            from corrosion_tpu.agent.health import LoopHealthProbe

            self.health = LoopHealthProbe(
                self.metrics,
                interval=self.config.stall_probe_interval,
                slow_ms=self.config.stall_probe_slow_ms,
                clock=self._clock,
            )
            self._tasks.append(
                self._spawn_task(self.health.run(), "health")
            )
        if self.flight is not None:
            self._tasks.append(
                self._spawn_task(self.flight.run(), "flight")
            )
        if self.config.api_port is not None:
            from corrosion_tpu.agent.http import start_http_api

            self._http = start_http_api(self)
            self.api_addr = self._http.server_address[:2]
        if self.config.admin_path:
            from corrosion_tpu.agent.admin import start_admin

            self._admin = await start_admin(self, self.config.admin_path)
        if self.config.pg_port is not None:
            from corrosion_tpu.agent.pg import serve_pg

            self._pg = await serve_pg(
                self,
                self.config.pg_host or self.config.api_host,
                self.config.pg_port,
            )
            self.pg_addr = self._pg.sockets[0].getsockname()[:2]

    async def stop(self, graceful: bool = True) -> None:
        # graceful leave (broadcast/mod.rs:327-366 leave_cluster): tell
        # alive peers we are going down so they drop us immediately
        # instead of burning a probe->suspect->down cycle on us.
        # graceful=False simulates a crash (tests of the suspicion path)
        if graceful and self._udp is not None:
            self._swim_leave()
        await _cancel_tasks(self._tasks)
        self._tasks = []
        # drain in-flight apply batches before tearing down connections /
        # storage — a worker must never touch a closed resource
        if self._apply_inflight:
            await asyncio.gather(
                *self._apply_inflight, return_exceptions=True
            )
            self._apply_inflight.clear()
        if self._apply_pool is not None:
            self._apply_pool.shutdown(wait=True)
        if self.transport is not None:
            await self.transport.aclose()
        await _cancel_tasks(list(self._conn_tasks))
        # after the connection handlers: a live sync session must not
        # race a shut-down collection pool
        if self._serve_pool is not None:
            self._serve_pool.shutdown(wait=True)
            self._serve_pool = None
        # drain queued local-broadcast collections before storage goes
        # away (their RO reads must not race close).  The closed flag
        # flips under the lock BEFORE shutdown so a write completing
        # concurrently with stop() can't lazily rebirth a pool that
        # would read closing storage and leak its thread — late
        # dispatches drop instead (the versions are durable;
        # anti-entropy serves them after restart)
        with self._wbcast_lock:
            self._wbcast_closed = True
            pool, self._wbcast_pool = self._wbcast_pool, None
        if pool is not None:
            pool.shutdown(wait=True)
        if self._udp:
            self._udp.close()
            self._udp = None  # liveness marker: stopped agents don't send
        if self._tcp:
            self._tcp.close()
            try:
                # wait_closed waits for every handler's transport to
                # flush; a peer that stopped reading would hold shutdown
                # hostage
                await asyncio.wait_for(self._tcp.wait_closed(), timeout=2.0)
            except asyncio.TimeoutError:
                pass
        if self._http:
            self._http.shutdown()
            self._http.server_close()
        if self._admin is not None:
            self._admin.close()
            await self._admin.wait_closed()
        if self._pg is not None:
            self._pg.close()
            # abort live sessions: wait_closed() waits for every
            # handler, so an idle client would hold stop() forever.
            # abort (not close): close() flushes first, and a peer
            # that stopped reading would outlive the grace period and
            # touch storage after it closes.  Two abort passes: a
            # connection accepted just before close() can register in
            # corro_conns after the first snapshot
            for timeout in (1.0, 1.0):
                for w in list(getattr(self._pg, "corro_conns", ())):
                    try:
                        w.transport.abort()
                    except Exception:
                        pass
                try:
                    await asyncio.wait_for(
                        self._pg.wait_closed(), timeout=timeout
                    )
                    break
                except asyncio.TimeoutError:
                    continue
        if self.subs is not None:
            self.subs.close()
        if self.config.trace_export_path:
            # symmetric with start(), but only if OUR sink is still the
            # active one — another agent in this process may own it now
            tracing.disable_export_if(getattr(self, "_trace_token", None))
        if self.flight is not None:
            self.flight.close()
        self._persist_members()
        self.storage.close()

    # ------------------------------------------------------------------
    # member persistence (__corro_members parity)
    # ------------------------------------------------------------------

    def metric_gauges(self) -> List[tuple]:
        """Scrape-time gauges matching the reference's metrics loop
        (``agent/metrics.rs:18-108`` collect_metrics + pool/transport
        emit_metrics): per-table row counts, per-actor buffered-change
        rows and bookkeeping-gap sums, db/WAL sizes and freelist, queue
        depths, and aggregate transport ConnStats."""
        extra: List[tuple] = []
        # committed-state reads ride the READ pool: a scrape must not
        # hold the write lock across full-table COUNT(*) scans and
        # stall PRIO_HIGH client writes (the reference's metrics loop
        # reads through its read pool too)
        for t in self.storage.tables:
            # identifier-quote the table name: a schema may legally
            # declare adversarial names (embedded quotes) and a scrape
            # must not turn them into SQL — exposition escaping keeps
            # the label value scrape-safe regardless
            q = t.replace('"', '""')
            _, rows = self.storage.read_query(
                f'SELECT COUNT(*) FROM "{q}"'
            )
            extra.append(
                ("corro_table_rows", float(rows[0][0]), {"table": t})
            )
        _, rows = self.storage.read_query(
            "SELECT actor_id, COUNT(*) FROM __corro_buffered_changes"
            " GROUP BY actor_id"
        )
        for actor, n in rows:
            extra.append((
                "corro_db_buffered_changes_rows", float(n),
                {"actor_id": bytes(actor).hex()},
            ))
        _, rows = self.storage.read_query("PRAGMA freelist_count")
        extra.append(
            ("corro_db_freelist_pages", float(rows[0][0]), {})
        )
        _, rows = self.storage.read_query(
            "SELECT value FROM __corro_state WHERE key='db_version'"
        )
        extra.append(("corro_db_version", float(rows[0][0]), {}))
        # version-gap sums per actor (corro.db.gaps.sum parity): the
        # bookie's RangeSets mutate under the storage lock.  Best
        # effort — a scrape must not queue behind a long write, so if
        # the lock isn't free quickly the gap series is simply omitted
        # this round (the next scrape catches up)
        if self.storage._lock.acquire(PRIO_LOW, timeout=0.25):
            try:
                for actor, booked in self.bookie.actors().items():
                    gap_sum = sum(
                        e - s + 1 for s, e in booked.needed.spans()
                    )
                    if gap_sum:
                        extra.append((
                            "corro_db_gaps_sum", float(gap_sum),
                            {"actor_id": actor.hex()},
                        ))
            finally:
                self.storage._lock.release()
        for name, path in (
            ("corro_db_size_bytes", self.config.db_path),
            ("corro_db_wal_size_bytes", self.config.db_path + "-wal"),
        ):
            try:
                extra.append((name, float(os.stat(path).st_size), {}))
            except OSError:
                pass
        extra.extend([
            ("corro_members_alive", float(len(self.members.alive())), {}),
            ("corro_members_suspect", float(sum(
                1 for m in self.members.all()
                if m.state is MemberState.SUSPECT)), {}),
            ("corro_members_down", float(sum(
                1 for m in self.members.all()
                if m.state is MemberState.DOWN)), {}),
            ("corro_members_ring0", float(len(self.members.ring0())), {}),
        ])
        # channel/queue depths (channel.rs metered-channel parity)
        extra.append(
            ("corro_change_queue_depth", float(len(self._ingest)), {})
        )
        extra.append((
            "corro_bcast_queue_depth",
            float(self._bcast_queue.qsize()), {},
        ))
        extra.append((
            "corro_sync_server_sessions",
            float(self._sync_server_sessions), {},
        ))
        extra.append((
            "corro_write_queue_depth",
            float(self._write_combiner.depth()), {},
        ))
        if self.subs is not None:
            # subscription-plane gauges (pubsub.py): pending/matcher
            # queue depths + per-subscription staleness
            extra.extend(self.subs.metric_gauges())
        # transport ConnStats aggregates (transport.rs:235-419 export)
        if self.transport is not None:
            stats = list(self.transport.stats.values())
            extra.append(
                ("corro_transport_peers", float(len(stats)), {})
            )
            for field in ("connects", "bytes_sent", "frames_sent",
                          "failures", "faults_dropped", "redials",
                          "breaker_opens"):
                extra.append((
                    f"corro_transport_{field}",
                    float(sum(getattr(s, field) for s in stats)), {},
                ))
            extra.append((
                "corro_transport_breakers_open",
                # list() snapshot: apply workers insert convictions
                # concurrently and a plain generator over .values()
                # races the resize
                float(sum(
                    1 for b in list(self.transport.breakers.values())
                    if b.is_open
                )), {},
            ))
            rtts = [s.rtt_min_ms for s in stats if s.rtt_min_ms is not None]
            if rtts:
                extra.append(
                    ("corro_transport_rtt_min_ms", float(min(rtts)), {})
                )
        # per-origin-actor staleness (provenance plane): wall-now minus
        # the freshest origin-commit ts applied from that actor — a
        # rising series means we stopped converging on its writes
        now_wall = self._clock.wall()
        for actor, ts_wall in self._staleness_entries(now_wall):
            extra.append((
                "corro_change_staleness_seconds",
                max(0.0, now_wall - ts_wall),
                {"actor_id": actor.hex()},
            ))
        # bounded trace export drops: the sink is process-wide, so ONLY
        # the agent whose token opened the CURRENTLY active sink syncs
        # the global total into its counter — if every past owner of an
        # in-process cluster claimed the delta, summing the family
        # across nodes would overcount drops n_owners-fold
        if tracing.export_token_active(self._trace_token):
            dropped = tracing.export_dropped_total()
            if dropped > self._trace_dropped_seen:
                self.metrics.counter(
                    "corro_trace_spans_dropped_total",
                    dropped - self._trace_dropped_seen,
                )
                self._trace_dropped_seen = dropped
        return extra

    def _staleness_entries(self, now_wall: float):
        """Snapshot ``(actor, freshest-origin-ts)`` pairs, evicting on
        the way out the entries of actors that are BOTH idle past
        ``staleness_evict_s`` (no write applied from them locally — the
        idle clock, not the origin-ts age, which a partition or a slow
        remote clock legitimately grows) AND not an alive cluster
        member — a departed or identity-renewed actor must not leave a
        permanently rising staleness series (and ever-growing label
        cardinality) behind, while a live-but-unconverged actor keeps
        alerting.  A later write from the actor re-creates its entry on
        first arrival."""
        evict = self.config.staleness_evict_s
        with self._prov_lock:
            if evict > 0:
                dead = []
                for a, seen in self._origin_seen_wall.items():
                    if now_wall - seen <= evict:
                        continue
                    m = self.members.get(a)
                    if m is not None and m.state is not MemberState.DOWN:
                        continue
                    dead.append(a)
                for a in dead:
                    self._origin_seen_wall.pop(a, None)
                    self._origin_ts_wall.pop(a, None)
            return list(self._origin_ts_wall.items())

    def health_snapshot(self) -> dict:
        """Runtime health for the admin ``health`` command: the loop
        stall probe's state, queue depths, apply concurrency, per-path
        convergence lag (windowed quantiles from the agent's own
        provenance measurement), and per-origin staleness — the
        always-on form of the gates the benches enforce."""
        now_wall = self._clock.wall()
        staleness = {
            actor.hex(): round(max(0.0, now_wall - ts), 3)
            for actor, ts in self._staleness_entries(now_wall)
        }
        lag: Dict[str, dict] = {}
        for key, samples in self.metrics.histogram_samples(
            "corro_change_lag_seconds"
        ).items():
            if not samples:
                continue
            path = dict(key).get("path", "?")
            s = sorted(samples)
            count, total = self.metrics.histogram_stats(
                "corro_change_lag_seconds", path=path
            )
            lag[path] = {
                "count": count,
                "p50_s": round(percentile_sorted(s, 0.5), 4),
                "p99_s": round(percentile_sorted(s, 0.99), 4),
                "max_s": round(s[-1], 4),
                "mean_s": round(total / max(count, 1), 4),
            }
        return {
            "actor": self.actor_id.hex(),
            "loop": self.health.snapshot() if self.health else None,
            "flight": self.flight.snapshot() if self.flight else None,
            "queues": {
                "changes": len(self._ingest),
                "bcast": self._bcast_queue.qsize() if self._loop else 0,
                "write": self._write_combiner.depth(),
            },
            "apply_in_flight": self._apply_active,
            "members_alive": len(self.members.alive()),
            "convergence_lag": lag,
            "origin_staleness_s": staleness,
        }

    def provenance_first_seen(self) -> Dict[tuple, tuple]:
        """Snapshot of the provenance first-seen stamps:
        ``(actor_bytes, version) -> (wall_seconds, hlc_int)`` for every
        remote version whose first arrival this node recorded (bounded
        by ``seen_cache_size``).  The timeline plane's per-node raw
        material (``ClusterObserver.coverage_curve``)."""
        with self._prov_lock:
            return {
                k: v for k, v in self._prov_seen.items()
                if v is not None
            }

    def _members_table(self) -> None:
        self.storage.conn.execute(
            "CREATE TABLE IF NOT EXISTS __corro_members ("
            " actor_id BLOB PRIMARY KEY, host TEXT, port INTEGER,"
            " state TEXT, incarnation INTEGER)"
        )

    def _persist_members(self) -> None:
        with self.storage._lock:
            self.storage.conn.execute("DELETE FROM __corro_members")
            self.storage.conn.executemany(
                "INSERT OR REPLACE INTO __corro_members VALUES (?, ?, ?, ?, ?)",
                [
                    (m.actor_id, m.addr[0], m.addr[1], m.state.value, m.incarnation)
                    for m in self.members.all()
                ],
            )

    def _load_members(self) -> None:
        for actor, host, port, state, inc in self.storage.conn.execute(
            "SELECT actor_id, host, port, state, incarnation FROM __corro_members"
        ):
            self.members.upsert(
                bytes(actor), (host, port), MemberState(state), inc
            )

    # ------------------------------------------------------------------
    # SWIM: announce / probe / suspicion
    # ------------------------------------------------------------------

    def _self_entry(self) -> list:
        return [
            wire._b64(self.actor_id),
            self.gossip_addr[0],
            self.gossip_addr[1],
            MemberState.ALIVE.value,
            self.incarnation,
            self._identity_ts,
        ]

    def _piggyback(self, k: int = 5) -> list:
        entries = [self._self_entry()]
        members = self.members.all()
        for m in self._rng.sample(members, min(k, len(members))):
            entries.append(
                [
                    wire._b64(m.actor_id),
                    m.addr[0],
                    m.addr[1],
                    m.state.value,
                    m.incarnation,
                    # identity ts rides the JSON wire too, so a member
                    # learned here is advertised with its real identity
                    # generation on the foca wire (mixed-wire clusters)
                    self._swim_ts.get(m.actor_id, 0),
                ]
            )
        return entries

    def _ingest_piggyback(self, entries: list) -> None:
        for entry in entries:
            actor_b64, host, port, state, inc = entry[:5]
            ts = entry[5] if len(entry) > 5 else 0
            actor = wire._unb64(actor_b64)
            if actor == self.actor_id:
                # refute anything non-alive said about us
                if state != MemberState.ALIVE.value and inc >= self.incarnation:
                    self.incarnation = inc + 1
                    self._persist_incarnation()
                continue
            known_ts = self._swim_ts.get(actor, 0)
            if 0 < ts < known_ts:
                # a real but STALE identity generation: discard, or an
                # old DOWN record would override the renewed member by
                # incarnation (swim_foca._ingest_update's guard).
                # ts == 0 means "generation unknown" (legacy peer) and
                # falls through to plain incarnation rules
                continue
            if ts > known_ts:
                # renewed identity generation: the fresh incarnation
                # space must override a stale DOWN record, so drop the
                # old member — and its suspicion timer: the new
                # generation must not inherit the old one's deadline
                self._swim_ts[actor] = ts
                if self.members.get(actor) is not None:
                    self.members.remove(actor)
                self._suspects.pop(actor, None)
            if self.members.upsert(
                actor, (host, port), MemberState(state), inc
            ):
                self._swim_update_tx[actor] = 0  # fresh news
                self.note_member_state(actor, MemberState(state))

    def _send_udp(self, addr: Tuple[str, int], msg: dict) -> None:
        if self._udp:
            if self.fault_filter is not None:
                act = self.fault_filter("udp", tuple(addr))
                if act is not None and act.drop:
                    # SWIM datagrams are unreliable by design: an
                    # injected drop is indistinguishable from the
                    # network eating the packet
                    self.metrics.counter(
                        "corro_transport_faults_injected_total",
                        kind="udp",
                    )
                    return
                if act is not None and act.delay and self._loop:
                    data_msg = dict(msg)
                    self._loop.call_later(
                        act.delay, self._send_udp_now, addr, data_msg
                    )
                    return
            self._send_udp_now(addr, msg)

    def _send_udp_now(self, addr: Tuple[str, int], msg: dict) -> None:
        if self._udp:
            if self.config.cluster_id:
                # SWIM is cluster-scoped like the foca identity's
                # cluster_id (actor.rs:222): receivers in other
                # clusters drop the datagram, so membership — not just
                # the data plane — partitions on cluster id
                msg.setdefault("c", self.config.cluster_id)
            data = wire.encode_datagram(msg)
            if len(data) > MAX_UDP_PAYLOAD:
                # foca caps SWIM packets at 1178 B (broadcast/mod.rs:943);
                # anything bigger belongs on a uni-stream
                self.metrics.counter("corro_udp_oversize_dropped_total")
                return
            self.metrics.counter(
                "corro_gossip_datagrams_sent_total",
                kind=(msg.get("k") if msg.get("k") in _SWIM_KINDS
                      else "other"),
            )
            self._udp.sendto(data, tuple(addr))

    def _next_probe_number(self) -> int:
        """Wrapping u16 probe counter (foca's ProbeNumber space): a
        sequential counter cannot collide across the ≤2 concurrent
        probes the loop runs, where random 16-bit draws eventually
        would — a collision overwrites one probe's ack future and the
        loser reads as a failed probe."""
        self._probe_seq = (self._probe_seq + 1) & 0xFFFF
        return self._probe_seq

    def _swim_announce(self, addr: Tuple[str, int]) -> None:
        if self.config.swim_wire == "foca":
            from corrosion_tpu.agent import swim_foca

            swim_foca.announce(self, addr)
        else:
            self._send_udp(addr, {"k": "announce", "pb": self._piggyback()})

    def _swim_probe(self, m: Member, nonce: int) -> None:
        if self.config.swim_wire == "foca":
            from corrosion_tpu.agent import swim_foca

            swim_foca.probe(self, m, nonce)
        else:
            self._send_udp(
                m.addr, {"k": "probe", "n": nonce, "pb": self._piggyback()}
            )

    def _swim_ping_req(self, helper: Member, target: Member,
                       nonce: int) -> None:
        if self.config.swim_wire == "foca":
            from corrosion_tpu.agent import swim_foca

            swim_foca.ping_req(self, helper, target, nonce)
        else:
            self._send_udp(
                helper.addr,
                {
                    "k": "ping_req",
                    "n": nonce,
                    "target": [target.addr[0], target.addr[1]],
                    "reply_to": [self.gossip_addr[0], self.gossip_addr[1]],
                },
            )

    def _swim_leave(self) -> None:
        if self.config.swim_wire == "foca":
            from corrosion_tpu.agent import swim_foca

            swim_foca.leave(self)
        else:
            for m in self.members.alive():
                self._send_udp(
                    m.addr,
                    {"k": "leave", "a": wire._b64(self.actor_id),
                     "i": self.incarnation},
                )

    async def _announce_loop(self) -> None:
        delay = 0.1
        while True:
            known = {m.addr for m in self.members.alive()}
            targets = [
                _parse_addr(b) for b in self.config.bootstrap
            ]
            for addr in targets:
                if addr != self.gossip_addr and addr not in known:
                    try:
                        self._swim_announce(addr)
                    except Exception:
                        # a bad bootstrap entry must not kill the loop
                        self.metrics.counter(
                            "corro_swim_announce_errors_total"
                        )
            if known or not targets:
                delay = min(delay * 2, 30.0)
            await self._clock.sleep(delay)

    def _load_incarnation(self) -> int:
        row = self.storage.conn.execute(
            "SELECT value FROM __corro_state WHERE key='incarnation'"
        ).fetchone()
        return int(row[0]) if row else 0

    def _persist_incarnation(self) -> None:
        with self.storage._lock:
            self.storage.conn.execute(
                "INSERT INTO __corro_state (key, value) "
                "VALUES ('incarnation', ?) "
                "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
                (self.incarnation,),
            )

    def rejoin(self) -> int:
        """Renew our identity and re-announce (foca ``Identity::renew``
        + the admin Rejoin command, ``actor.rs:199-210``): bump our
        incarnation (and, on the foca wire, our identity ts — a renewed
        identity is a fresh generation that replaces any stale DOWN
        record wholesale) so peers holding a stale/suspect view refresh
        it, then announce to every known member and configured
        bootstrap."""
        self.incarnation += 1
        self._persist_incarnation()
        self._identity_ts = max(
            self._identity_ts + 1, int(self.clock.new_timestamp())
        )
        targets = {tuple(m.addr) for m in self.members.alive()}
        targets.update(_parse_addr(b) for b in self.config.bootstrap)
        targets.discard(tuple(self.gossip_addr))
        for addr in targets:
            try:
                self._swim_announce(addr)
            except Exception:
                # one bad (e.g. unresolvable) target must not abort the
                # whole rejoin fan-out
                self.metrics.counter("corro_swim_announce_errors_total")
        return len(targets)

    def apply_schema_sql(self, sql: str) -> List[str]:
        """Apply schema additions (new tables/columns) to the live
        agent; returns touched table names.  The one shared entry point
        for /v1/migrations, SIGHUP reload, and tests — blocking, so
        call it off the event loop."""
        from corrosion_tpu.agent.schema import apply_schema

        with self.storage._lock:
            touched = apply_schema(self.storage, sql)
            self._register_backfills()
        return touched

    def set_cluster_id(self, cluster_id: int) -> int:
        """Move this node to another cluster (admin ``cluster set-id``,
        ``corro-admin/src/lib.rs`` Cluster SetId → FocaCmd change
        identity): SWIM datagrams and data-plane payloads to/from peers
        with a different cluster_id are rejected, so switching ids
        detaches us from the old cluster on both planes; old members
        are forgotten here, and the old cluster's view of us decays to
        down once our refutations stop (its probes are dropped).  The
        renewed announce lets same-id peers adopt us."""
        ClusterId(cluster_id)  # range-check (u16)
        old_members = self.members.all()
        self.config.cluster_id = int(cluster_id)
        announced = self.rejoin()
        for m in old_members:
            self.members.remove(m.actor_id)
        # fresh cluster, fresh SWIM bookkeeping (and the only unbounded
        # growth path for these per-identity dicts)
        self._swim_ts.clear()
        self._swim_update_tx.clear()
        return announced

    async def _gossip_loop(self) -> None:
        """Periodic membership gossip (foca periodic_gossip, enabled by
        the reference's WAN preset): a pure update-carrier round on a
        cadence faster than probing, skipped entirely once the backlog
        has decayed — the quiet-cluster cost is zero datagrams."""
        interval = self.config.gossip_interval
        if interval <= 0 or self.config.swim_wire != "foca":
            return
        from corrosion_tpu.agent import swim_foca

        while True:
            await self._clock.sleep(interval)
            try:
                sent = swim_foca.gossip_round(
                    self, self.config.gossip_fanout
                )
                if sent:
                    self.metrics.counter(
                        "corro_gossip_rounds_total"
                    )
            except Exception:
                self.metrics.counter("corro_gossip_round_errors_total")

    async def _probe_loop(self) -> None:
        while True:
            await self._clock.sleep(self.config.probe_interval)
            alive = self.members.alive()
            if not alive:
                continue
            target = self._rng.choice(alive)
            ok = await self._probe(target)
            if not ok:
                ok = await self._indirect_probe(target)
            if not ok:
                self._mark_suspect(target)

    async def _probe(self, m: Member, timeout: Optional[float] = None) -> bool:
        nonce = self._next_probe_number()
        fut = self._loop.create_future()
        self._acks[nonce] = fut
        t0 = self._clock.monotonic()
        self._swim_probe(m, nonce)
        try:
            await self._clock.wait_for(
                fut, timeout or self.config.probe_timeout
            )
            self.members.record_rtt(
                m.actor_id, (self._clock.monotonic() - t0) * 1e3
            )
            self._suspects.pop(m.actor_id, None)
            self.members.revive(m.actor_id)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            self._acks.pop(nonce, None)

    async def _indirect_probe(self, target: Member) -> bool:
        helpers = [
            m for m in self.members.alive() if m.actor_id != target.actor_id
        ]
        if not helpers:
            return False
        helpers = self._rng.sample(
            helpers, min(self.config.num_indirect_probes, len(helpers))
        )
        nonce = self._next_probe_number()
        fut = self._loop.create_future()
        self._acks[nonce] = fut
        for h in helpers:
            self._swim_ping_req(h, target, nonce)
        try:
            await self._clock.wait_for(fut, self.config.probe_timeout * 2)
            self._suspects.pop(target.actor_id, None)
            self.members.revive(target.actor_id)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            self._acks.pop(nonce, None)

    def _mark_suspect(self, m: Member) -> None:
        if self.members.upsert(
            m.actor_id, m.addr, MemberState.SUSPECT, m.incarnation
        ):
            self._suspects[m.actor_id] = self._clock.monotonic()
            self._swim_update_tx[m.actor_id] = 0  # fresh news

    def _suspect_deadline(self) -> float:
        """Cluster-size-scaled suspicion timeout (make_foca_config →
        Config::new_wan, broadcast/mod.rs:937-946): configured value as
        the floor, growing log10 with membership so big clusters don't
        declare slow-but-alive members down."""
        from corrosion_tpu.utils.swimscale import scaled_suspect_timeout

        return scaled_suspect_timeout(
            self.config.suspect_timeout,
            self.config.probe_interval,
            len(self.members.alive()) + 1,
            self.config.suspicion_mult,
        )

    def note_member_state(self, actor: bytes, state: MemberState) -> None:
        """Arm/clear the local suspicion timer for a member-record
        change (SWIM deadlines are PER NODE — foca starts one on every
        member that hears a suspicion).  Shared by both wire ingest
        paths so they cannot diverge."""
        if state is MemberState.SUSPECT:
            self._suspects.setdefault(actor, self._clock.monotonic())
        else:
            self._suspects.pop(actor, None)

    def _reap_suspects(self) -> None:
        """One suspicion-deadline pass (extracted so tests can drive
        it without the loop's cadence)."""
        now = self._clock.monotonic()
        deadline = self._suspect_deadline()
        for actor, since in list(self._suspects.items()):
            if now - since >= deadline:
                m = self.members.get(actor)
                if m and m.state is MemberState.SUSPECT:
                    self.members.upsert(
                        actor, m.addr, MemberState.DOWN, m.incarnation
                    )
                    self._swim_update_tx[actor] = 0  # fresh news
                self._suspects.pop(actor, None)

    async def _suspect_reaper(self) -> None:
        while True:
            await self._clock.sleep(self.config.probe_interval)
            self._reap_suspects()

    # ------------------------------------------------------------------
    # local writes + broadcast
    # ------------------------------------------------------------------

    def _register_backfills(self) -> None:
        """Record as_crr-backfill versions in bookkeeping so pre-existing
        rows replicate (sync serves them; see CrConn._backfill).

        Transactional: bookkeeping rows persist atomically with deleting
        the durable __corro_backfills records, all under the storage lock
        — a crash at any point either leaves the records for the next
        boot or has them fully registered.
        """
        with self.storage._lock:
            pending = self.storage.peek_backfills()
            if not pending:
                return
            booked = self.bookie.for_actor(self.actor_id)
            regs = []
            self.storage.conn.execute("BEGIN IMMEDIATE")
            try:
                last = booked.last()
                for dbv, last_seq in pending:
                    last += 1
                    ts = self.clock.new_timestamp()
                    self.bookie.persist_version(
                        self.actor_id, last, dbv, last_seq, int(ts)
                    )
                    regs.append((last, dbv, last_seq, ts))
                self.storage.clear_backfills()
            except BaseException:
                self.storage.conn.execute("ROLLBACK")
                raise
            self.storage.conn.execute("COMMIT")
            for version, dbv, last_seq, ts in regs:
                booked.apply_version(version, dbv, last_seq, ts)
                self._queue_or_defer_broadcast(version, dbv, last_seq, ts)

    def execute_transaction(self, statements: Sequence,
                            on_conn=None) -> dict:
        """Run write statements in one client transaction; version +
        bookkeeping + queue the broadcast (``make_broadcastable_changes``
        parity).

        With ``AgentConfig.write_group_commit`` (default on) the call
        routes through the write combiner (``agent/writes.py``,
        docs/writes.md): concurrent callers share one storage-lock hold
        and one outer commit, each batch isolated under its own
        SAVEPOINT — same results, versions, broadcasts, and subscription
        events as the per-transaction path, which stays below as the
        parity oracle.  Batches opening with transaction-control SQL
        (BEGIN/COMMIT/PRAGMA/…) always take the oracle path.

        ``on_conn`` (called with the RW connection once the storage lock
        is held, then with None before release) lets a caller interrupt
        the in-flight write — the PG front-end's CancelRequest path,
        mirroring ``CrConn.read_query``'s contract."""
        if self.config.write_group_commit:
            from corrosion_tpu.agent.writes import has_tx_control

            if not has_tx_control(statements):
                return self._write_combiner.submit(statements, on_conn)
            self.metrics.counter(
                "corro_write_group_fallbacks_total", reason="stmt"
            )
            self._flight_event("write_group_fallback", reason="stmt")
        return self._execute_transaction_single(statements, on_conn)

    def _execute_transaction_single(self, statements: Sequence,
                                    on_conn=None) -> dict:
        """The per-transaction write path: one storage-lock hold, one
        BEGIN..COMMIT, one broadcast collection — the parity oracle the
        write combiner is pinned against (tests/test_write_combiner.py)."""
        results = []
        booked = self.bookie.for_actor(self.actor_id)
        # hold the storage lock across COMMIT *and* the in-memory bookie
        # update: the version counter (booked.last()+1) must not be read
        # by a second writer between our COMMIT and apply_version, and
        # apply_version must not race generate_sync's locked snapshot.
        # HIGH tier: client writes ride write_priority() in the
        # reference (api/public/mod.rs:59)
        with self.metrics.timed("corro_write_tx_seconds"), \
                self.storage._lock.prio(PRIO_HIGH, "write", kind="write"):
            # tracked only while the lock is held, so a cancel cannot
            # interrupt another session's statement on the shared conn
            if on_conn is not None:
                on_conn(self.storage.conn)
            try:
                committed = self._execute_transaction_locked(
                    statements, results, booked
                )
            finally:
                if on_conn is not None:
                    on_conn(None)
        if committed is not None:
            version, db_version, n_changes, ts = committed
            self._queue_or_defer_broadcast(
                version, db_version, n_changes - 1, ts
            )
            self._compact_best_effort()
            return {"results": results, "version": version}
        return {"results": results, "version": None}

    def _execute_statements(self, conn, statements, results) -> None:
        """Run one client batch's statements on ``conn``, appending a
        result dict per statement.  Shared verbatim by the per-
        transaction oracle and the group-commit combiner so the two can
        never diverge on result shapes."""
        for stmt in statements:
            sql, params = unpack_stmt(stmt)
            cur = conn.execute(sql, params)
            head = sql.lstrip().split(None, 1)
            is_dml = bool(head) and head[0].upper() in (
                "INSERT", "UPDATE", "DELETE", "REPLACE", "WITH",
            )
            if cur.rowcount < 0 and cur.description is None \
                    and is_dml:
                # sqlite3 reports -1 for INSERT..SELECT and
                # friends; changes() has the statement's true
                # direct count (triggers excluded).  DML-gated:
                # for DDL, changes() still holds the PREVIOUS
                # statement's count
                cur = conn.execute("SELECT changes()")
                n = cur.fetchone()[0]
                results.append({"rows_affected": n})
                continue
            if cur.description is not None:
                # RETURNING clause (ORM-style writes): surface
                # the produced rows alongside the write result,
                # JSON-safe (a BLOB column must not 500 the
                # HTTP response after the write committed).
                # fetchall() FIRST — sqlite3 only counts
                # affected rows as RETURNING rows are stepped,
                # so rowcount is 0 until the fetch completes
                from corrosion_tpu.agent.pack import jsonable_row

                fetched = cur.fetchall()
                res = {
                    "rows_affected": cur.rowcount,
                    "columns": [d[0] for d in cur.description],
                    "rows": [jsonable_row(r) for r in fetched],
                }
            else:
                res = {"rows_affected": cur.rowcount}
            results.append(res)

    def _execute_transaction_locked(self, statements, results,
                                    booked) -> Optional[tuple]:
        """Body of :meth:`_execute_transaction_single` under the storage
        lock; returns ``(version, db_version, n_changes, ts)`` for a
        committed versioned write, None for a changeless one."""
        with self.storage.write_tx() as conn:
            self._execute_statements(conn, statements, results)
            n_changes = self.storage._state("seq")
            if n_changes > 0:
                version = booked.last() + 1
                db_version = self.storage._state("pending_db_version")
                ts = self.clock.new_timestamp()
                # persist inside the tx (atomic with the data); the
                # in-memory bookie commits only after COMMIT succeeds,
                # so a failed commit can't leave memory advertising a
                # version the DB never stored
                self.bookie.persist_version(
                    self.actor_id, version, db_version,
                    n_changes - 1, int(ts),
                )
            else:
                version = None
        if version is None:
            return None
        booked.apply_version(version, db_version, n_changes - 1, ts)
        return (version, db_version, n_changes, ts)

    # -- group-commit write combining (docs/writes.md) ------------------
    #
    # Concurrent execute_transaction callers coalesce (agent/writes.py):
    # one storage-lock hold + one outer BEGIN..COMMIT per group, each
    # client batch under its own SAVEPOINT, versions/db_versions/seq
    # spans assigned gaplessly in submission order, bookkeeping flushed
    # via Bookie.persist_versions, then ONE change collection for the
    # group's whole db_version span on a read-only pool connection off
    # the event loop — with on_change fired per changeset and one
    # compaction sweep per group.  The per-transaction path above is
    # the parity oracle (tests/test_write_combiner.py).

    def _execute_write_group(self, reqs) -> None:
        """Drain one combined group: resolve every request's result or
        error and set its ``done`` event.  Never raises — a dead leader
        would strand every parked caller."""
        from corrosion_tpu.agent.writes import GroupAborted

        booked = self.bookie.for_actor(self.actor_id)
        self.metrics.counter("corro_write_groups_total")
        self.metrics.histogram("corro_write_group_size", len(reqs))
        aborted: Optional[GroupAborted] = None
        entries = None
        # the group span roots the broadcast trace: its context flows
        # through the collect worker onto the wire (traced uni
        # envelope) so every remote's first-arrival apply span shares
        # this trace id — one write, one cross-cluster trace
        with tracing.span("write.group", batches=len(reqs)) as wsp:
            self.metrics.counter("corro_trace_spans_total")
            group_tp = wsp.traceparent
            try:
                with self.metrics.timed("corro_write_group_seconds"), \
                        self.storage._lock.prio(PRIO_HIGH, "write-group",
                                                kind="write"):
                    entries = self._run_write_group_locked(reqs, booked)
            except GroupAborted as ga:
                aborted = ga
            except BaseException as e:  # lock/commit-level failure
                aborted = GroupAborted(None, e)
            wsp.set(
                committed=len(entries or ()),
                aborted=aborted is not None,
            )
        if aborted is not None:
            # replay every batch that didn't fail in its own savepoint
            # and didn't commit durably (a hostile mid-group COMMIT
            # finishes its prefix in _recover_committed_group — those
            # requests carry a result and must NOT be replayed, that
            # would double-apply) through the per-transaction oracle
            # (the mirror of _handle_change_group's merged-tx
            # fallback); the batch that surfaced the abort keeps its
            # original error
            self.metrics.counter(
                "corro_write_group_fallbacks_total", reason="abort"
            )
            self._flight_event(
                "write_group_fallback", reason="abort", batches=len(reqs)
            )
            if aborted.recovered:
                try:
                    self._dispatch_local_broadcast(
                        list(aborted.recovered), traceparent=group_tp
                    )
                except Exception:
                    self.metrics.counter(
                        "corro_local_broadcast_errors_total")
            for i, req in enumerate(reqs):
                if i == aborted.index:
                    req.error = aborted.error
                elif req.error is None and req.result is None:
                    try:
                        req.result = self._execute_transaction_single(
                            req.statements, req.on_conn
                        )
                    except BaseException as e:
                        req.error = e
                req.done.set()
            return
        # committed: ONE coalesced broadcast collection for the span
        # (off the event loop), then unblock the callers — their write
        # is durable — and sweep compaction once for the whole group
        if entries:
            try:
                self._dispatch_local_broadcast(
                    entries, traceparent=group_tp
                )
            except Exception:
                self.metrics.counter("corro_local_broadcast_errors_total")
        for req in reqs:
            req.done.set()
        if entries:
            self._compact_best_effort()

    def _run_write_group_locked(self, reqs, booked) -> List[tuple]:
        """Group body under the storage lock: one outer transaction,
        per-batch savepoints.  Returns the committed ``(version,
        db_version, last_seq, ts)`` entries in submission order; sets
        ``result``/``error`` on every request (without firing ``done``).

        Raises ``GroupAborted`` when the OUTER transaction is lost
        (interrupt, disk error, a statement that terminated it):
        usually a rollback — nothing committed, no request state
        trusted — but a statement that COMMITTED the outer transaction
        mid-group is detected via the committed db_version cursor and
        its durable prefix finished in place
        (:meth:`_recover_committed_group`)."""
        import sqlite3

        from corrosion_tpu.agent.writes import GroupAborted

        conn = self.storage.conn
        conn.execute("BEGIN IMMEDIATE")
        # committed db_version cursor at group start: if the outer tx
        # terminates and this has ADVANCED durably, a statement
        # committed mid-group rather than rolling back
        dbv0 = self.storage._state("db_version")
        entries: List[tuple] = []  # (version, db_version, last_seq, ts)
        req_state: List[Optional[tuple]] = []  # (results, version|None)
        rows: List[tuple] = []  # bookkeeping executemany rows
        version = booked.last()
        try:
            for i, req in enumerate(reqs):
                # per-batch version state, exactly like write_tx: the
                # CRR triggers stamp this batch's rows with its OWN
                # (pending db_version, seq 0..n-1) span
                pending = self.storage.begin_write_batch()
                conn.execute("SAVEPOINT corro_wg")
                if req.on_conn is not None:
                    req.on_conn(conn)
                results: List[dict] = []
                try:
                    self._execute_statements(conn, req.statements, results)
                    if not conn.in_transaction:
                        # a statement ended the outer tx underneath us
                        # (screened tx-control should prevent this, but
                        # a hostile/odd statement must fail loud, not
                        # half-commit a group)
                        raise sqlite3.OperationalError(
                            "statement terminated the group transaction"
                        )
                except BaseException as e:
                    if not conn.in_transaction:
                        raise GroupAborted(i, e)
                    # savepoint-scoped failure: only this caller fails
                    conn.execute("ROLLBACK TO corro_wg")
                    conn.execute("RELEASE corro_wg")
                    req.error = e
                    req_state.append(None)
                    continue
                finally:
                    if req.on_conn is not None:
                        req.on_conn(None)
                conn.execute("RELEASE corro_wg")
                n_changes = self.storage._state("seq")
                if n_changes > 0:
                    self.storage._set_state("db_version", pending)
                    version += 1
                    ts = self.clock.new_timestamp()
                    rows.append((version, pending, n_changes - 1, int(ts)))
                    entries.append((version, pending, n_changes - 1, ts))
                    req_state.append((results, version))
                else:
                    # changeless batch: no version/db_version consumed
                    req_state.append((results, None))
            if rows:
                # one executemany write-through for the whole group
                # (persist INSIDE the tx, atomic with the data —
                # persist_version contract)
                self.bookie.persist_versions(self.actor_id, rows)
            conn.execute("COMMIT")
        except GroupAborted as ga:
            if conn.in_transaction:
                conn.execute("ROLLBACK")
            else:
                self._recover_committed_group(
                    ga, dbv0, entries, rows, reqs, req_state, booked
                )
            raise
        except BaseException as e:
            ga = GroupAborted(None, e)
            if conn.in_transaction:
                conn.execute("ROLLBACK")
            else:
                self._recover_committed_group(
                    ga, dbv0, entries, rows, reqs, req_state, booked
                )
            raise ga
        # in-memory bookie only AFTER the commit succeeded (the oracle's
        # ordering): a failed commit must never leave memory advertising
        # versions the DB never stored.  Still under the storage lock,
        # so generate_sync's locked snapshot can't see a half-applied
        # group
        for v, dbv, last_seq, ts in entries:
            booked.apply_version(v, dbv, last_seq, ts)
        for req, st in zip(reqs, req_state):
            if st is None:
                continue
            results, v = st
            req.result = {"results": results, "version": v}
        return entries

    def _recover_committed_group(self, ga, dbv0, entries, rows, reqs,
                                 req_state, booked) -> None:
        """The group's outer transaction is GONE (still under the
        storage lock).  Usually that is a rollback and nothing
        committed — detected here by the committed db_version cursor
        still reading ``dbv0`` — and the abort fallback may safely
        replay every batch.  But a statement that slipped past
        tx-control screening and COMMITTED mid-group leaves every batch
        processed so far durable WITHOUT bookkeeping; replaying those
        would double-apply.  Finish the committed prefix in place
        instead: persist its bookkeeping rows in a recovery
        transaction, apply the in-memory versions, attach the callers'
        results (so the fallback skips them), and hand the entries to
        the abort path via ``ga.recovered`` for broadcast.  Best
        effort by design — the one invariant that must hold even when
        recovery itself fails is that durable batches are never
        replayed, so results attach regardless."""
        try:
            committed = self.storage._state("db_version")
        except Exception:
            return  # storage unreadable: nothing more we can do
        if committed == dbv0:
            return  # clean rollback: replay is safe
        self.metrics.counter("corro_write_group_hostile_commits_total")
        logger.warning(
            "a group write statement committed mid-group "
            "(db_version %d -> %d); recovering %d durable batches",
            dbv0, committed, len(req_state),
        )
        conn = self.storage.conn
        persisted = True
        if rows:
            try:
                conn.execute("BEGIN IMMEDIATE")
                self.bookie.persist_versions(self.actor_id, rows)
                conn.execute("COMMIT")
            except BaseException:
                persisted = False
                try:
                    if conn.in_transaction:
                        conn.execute("ROLLBACK")
                except Exception:
                    pass
        if persisted:
            for v, dbv, last_seq, ts in entries:
                booked.apply_version(v, dbv, last_seq, ts)
            ga.recovered = list(entries)
        for req, st in zip(reqs, req_state):
            if st is None:
                continue  # savepoint-failed batch keeps its error
            results, v = st
            req.result = {"results": results, "version": v}

    def _find_and_clear_overwritten(self) -> List[Tuple[int, int]]:
        """Local compaction: versions whose change rows were all
        overwritten become cleared ranges and gossip as empty changesets.

        Parity: ``find_overwritten_versions`` + ``store_empty_changeset``
        (agent.rs:1753-1812, change.rs:314-436) — runs after every local
        write and remote apply; only the originating node clears its own
        versions (impact triggers watch site_ordinal=1 rows only).
        Returns the cleared (start, end) ranges.
        """
        cleared: List[Tuple[int, int]] = []
        # LOW tier: compaction is maintenance — the reference clears
        # overwritten/empty ranges on write_low (handlers.rs:635-691)
        with self.storage._lock.prio(PRIO_LOW, "compaction"):
            any_impacted, gone = self.storage.overwritten_local_db_versions()
            if not any_impacted:
                return []
            booked = self.bookie.for_actor(self.actor_id)
            gone_set = set(gone)
            rs = RangeSet()
            for v, (dbv, _seq) in booked.versions.items():
                if dbv in gone_set:
                    rs.insert(v, v)
            ranges = rs.spans()
            ts = self.clock.new_timestamp()
            self.storage.conn.execute("BEGIN IMMEDIATE")
            try:
                self.storage.conn.execute(
                    "DELETE FROM __corro_versions_impacted"
                )
                for s, e in ranges:
                    self.bookie.persist_cleared(self.actor_id, s, e, int(ts))
                if ranges:
                    # our own compaction is complete information: advance
                    # our advertised cleared watermark
                    self.bookie.persist_sync_state(self.actor_id, int(ts))
            except BaseException:
                self.storage.conn.execute("ROLLBACK")
                raise
            self.storage.conn.execute("COMMIT")
            for s, e in ranges:
                booked.mark_cleared(s, e)
                cleared.append((s, e))
            if ranges:
                booked.update_cleared_ts(ts)
        for s, e in cleared:
            cv = ChangeV1(
                actor_id=ActorId(self.actor_id),
                changeset=Changeset.empty((Version(s), Version(e)), ts),
            )
            self._queue_or_defer_cv(cv)
        if cleared:
            self.metrics.counter(
                "corro_compaction_cleared_versions_total",
                sum(e - s + 1 for s, e in cleared),
            )
        return cleared

    def _compact_best_effort(self) -> None:
        """Post-commit compaction sweep on hot paths: the user's write is
        already durable, so a sweep failure (e.g. busy DB) must not turn
        a successful write into an error — maintenance retries it."""
        try:
            self._find_and_clear_overwritten()
        except Exception:
            self.metrics.counter("corro_compaction_sweep_errors_total")

    async def _compaction_loop(self) -> None:
        """Maintenance-driven compaction on its own cadence
        (``AgentConfig.compaction_interval``): an idle-but-serving node
        has no post-commit sweep to piggyback on, so without this loop
        its cleared spans and snapshot floor would only move on the
        (much slower) maintenance tick.  The SQL body runs on the apply
        pool like the maintenance pass."""
        while True:
            await self._clock.sleep(self.config.compaction_interval)
            try:
                await self._loop.run_in_executor(
                    self._apply_pool, self._compaction_pass
                )
            except Exception:
                pass

    def _compaction_pass(self) -> int:
        """One maintenance-driven compaction sweep (worker thread):
        clear overwritten versions, then advance snapshot floors over
        the freshly-extended contained prefixes.  Returns the versions
        cleared + ledger rows compacted, counted under
        ``corro_compaction_maintenance_clears_total``."""
        work = 0
        # device-resident apply: compaction reads + rewrites clock
        # bookkeeping, so unflushed winners must land first, and the
        # cache view is invalid once floors advance
        self.storage.flush_pending()
        try:
            cleared = self._find_and_clear_overwritten()
            work += sum(e - s + 1 for s, e in cleared)
        except Exception:
            self.metrics.counter("corro_compaction_sweep_errors_total")
        try:
            work += self._advance_snapshot_floors()
        except Exception:
            self.metrics.counter("corro_compaction_sweep_errors_total")
        if work:
            self.storage.device_cache_invalidate("compaction")
        if work:
            self.metrics.counter(
                "corro_compaction_maintenance_clears_total", work
            )
        return work

    def _advance_snapshot_floors(self) -> int:
        """Background history compaction (docs/sync.md): per actor,
        advance the snapshot floor to the contained prefix minus the
        retain window, deleting the per-version bookkeeping it subsumes
        — after which those versions are only obtainable from this
        node via snapshot install (the serve path's plan walk simply
        no longer resolves them, and the advertised floor tells
        clients why).  Returns ledger rows compacted."""
        if not self.config.snapshot_serve:
            return 0
        retain = self.config.snapshot_retain_versions
        if retain < 0:
            return 0
        compacted = 0
        advanced = False
        with self.storage._lock.prio(PRIO_LOW, "snap-floor"):
            for actor, bv in list(self.bookie.actors().items()):
                target = bv.contained_prefix() - retain
                if target <= bv.snap_floor or target <= 0:
                    continue
                ts = int(self.clock.new_timestamp())
                self.storage.conn.execute("BEGIN IMMEDIATE")
                try:
                    compacted += self.bookie.compact_below_floor(
                        actor, target
                    )
                    self.bookie.persist_floor(actor, target, ts)
                except BaseException:
                    self.storage.conn.execute("ROLLBACK")
                    raise
                self.storage.conn.execute("COMMIT")
                bv.set_snap_floor(target)
                advanced = True
        if advanced:
            self.metrics.counter("corro_snapshot_floor_advances_total")
            self.metrics.gauge(
                "corro_snapshot_floor",
                self.bookie.for_actor(self.actor_id).snap_floor,
            )
        return compacted

    def _queue_or_defer_cv(self, cv: ChangeV1,
                           traceparent: Optional[str] = None) -> None:
        with self._bcast_gate:
            if self._loop is None:
                self._pre_start_cvs.append((cv, traceparent))
                return
            loop = self._loop
        self.metrics.counter("corro_channel_sends_total", channel="bcast")
        loop.call_soon_threadsafe(
            self._bcast_queue.put_nowait,
            (cv, self.config.max_transmissions, 0, traceparent, None),
        )

    def _queue_or_defer_broadcast(
        self, version: int, db_version: int, last_seq: int, ts: Timestamp
    ) -> None:
        """Queue one committed local version's broadcast, or buffer it
        until start() when the event loop isn't up yet (writes before
        start() must still gossip)."""
        self._dispatch_local_broadcast(
            [(version, db_version, last_seq, ts)],
            traceparent=tracing.current_traceparent(),
        )

    def _wbcast_executor(self):
        """The single-thread local-broadcast collection worker (lazy),
        or None once stop() closed it (no pool rebirth after teardown).
        ONE thread on purpose: collection + on_change + enqueue stay in
        version order, exactly like the old loop-serialized path."""
        with self._wbcast_lock:
            if self._wbcast_closed:
                return None
            pool = self._wbcast_pool
            if pool is None:
                from concurrent.futures import ThreadPoolExecutor

                pool = self._wbcast_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="corro-wbcast",
                )
            return pool

    def _dispatch_local_broadcast(self, entries: List[tuple],
                                  traceparent: Optional[str] = None) -> None:
        """Route committed-version entries ``(version, db_version,
        last_seq, ts)`` to collection + broadcast enqueue.
        ``traceparent`` carries the committing write's span context onto
        the collection worker (contextvars don't cross threads), so the
        collect span and the remote apply spans share its trace id.

        Collection (SQL) and chunk encoding NEVER run on the event loop
        (the pre-round-6 path scheduled them there with
        ``call_soon_threadsafe``, stalling SWIM acks under write
        bursts): on a live agent they run on the ordered write-bcast
        worker; the deterministic scheduler (``_SyncLoop`` stand-in
        loop) runs them inline so its synchronous queue contract holds;
        with no loop at all they defer to start()."""
        with self._bcast_gate:
            if self._loop is None:
                self._pre_start_broadcasts.extend(entries)
                return
            live_loop = isinstance(self._loop, asyncio.AbstractEventLoop)
        if live_loop:
            pool = self._wbcast_executor()
            if pool is not None:  # None: stop() already tore it down
                pool.submit(
                    self._broadcast_local_commits, entries, traceparent
                )
        else:
            self._broadcast_local_commits(entries, traceparent)

    def _broadcast_local_commits(self, entries: List[tuple],
                                 traceparent: Optional[str] = None) -> None:
        """Worker body: one coalesced collection for the entries' whole
        db_version span, then per-changeset on_change + broadcast
        enqueue in version order.  A failure here must not surface as an
        unretrieved executor exception — the versions are already
        durable and anti-entropy serves them regardless."""
        try:
            # the collect span re-parents on the committing write's
            # trace; its own context rides the queued broadcasts so a
            # remote's first-arrival apply span completes the chain
            with tracing.span(
                "bcast.collect", remote=traceparent, entries=len(entries)
            ) as sp:
                self.metrics.counter("corro_trace_spans_total")
                cvs = self._local_commit_changesets(entries)
                sp.set(changesets=len(cvs))
            tp_out = (
                sp.traceparent
                if self.config.bcast_trace_propagation else None
            )
        except Exception:
            self.metrics.counter("corro_local_broadcast_errors_total")
            logger.debug("local broadcast collection failed", exc_info=True)
            return
        for cv in cvs:
            # per-changeset isolation, like the old per-version
            # dispatch: a raising on_change subscriber drops THAT
            # version's broadcast, not the rest of the group's
            try:
                if self.on_change is not None:
                    self.on_change(cv)
                self._queue_or_defer_cv(cv, tp_out)
            except Exception:
                self.metrics.counter("corro_local_broadcast_errors_total")
                logger.debug(
                    "local broadcast dispatch failed", exc_info=True
                )

    def _local_commit_changesets(
        self, entries: List[tuple]
    ) -> List[ChangeV1]:
        """Committed local versions -> their broadcast changesets, via
        ONE range collection on a read-only pool connection (no storage
        lock — the rows are committed data) split by db_version in
        memory.  A combined group's db_versions are consecutive (the
        group held the storage lock across all its batches), so the
        span contains exactly the entries' changes; chunking per
        version is identical to the per-commit path."""
        if not entries:
            return []
        dbvs = [e[1] for e in entries]
        with self.storage.reader() as conn:
            changes = self.storage.collect_changes_ro(
                conn, (min(dbvs), max(dbvs))
            )
        by_dbv: Dict[int, List] = {}
        for ch in changes:
            by_dbv.setdefault(int(ch.db_version), []).append(ch)
        cvs: List[ChangeV1] = []
        for version, db_version, last_seq, ts in entries:
            for chunk, seqs in ChunkedChanges(
                by_dbv.get(db_version, []), 0, last_seq
            ):
                cs = Changeset.full(
                    Version(version), chunk, seqs, last_seq=last_seq, ts=ts
                )
                cvs.append(
                    ChangeV1(actor_id=ActorId(self.actor_id), changeset=cs)
                )
        return cvs

    def _record_rtt(self, addr, rtt_s: float) -> None:
        for m in self.members.alive():
            if tuple(m.addr) == tuple(addr):
                self.members.record_rtt(m.actor_id, rtt_s * 1000.0)
                break

    def _on_breaker(self, addr, opened: bool) -> None:
        """Transport circuit-breaker transition → member quarantine:
        an opened breaker deprioritizes the peer in fanout sampling
        (like a high-RTT peer); a half-open success restores it."""
        self.members.quarantine_by_addr(addr, opened)
        self.metrics.counter(
            "corro_members_quarantine_transitions_total",
            state="open" if opened else "restored",
        )
        addr_s = f"{addr[0]}:{addr[1]}"
        self._flight_event(
            "breaker_open" if opened else "breaker_close", addr=addr_s
        )
        self._flight_event(
            "quarantine", addr=addr_s, on=opened, reason="breaker"
        )

    async def _broadcast_loop(self) -> None:
        """Buffered, rate-limited dissemination over uni-streams.

        Parity (broadcast/mod.rs:399-801): payloads accumulate until the
        64 KiB cutoff or the flush tick; sends ride cached TCP
        uni-streams under the 10 MiB/s governor; retransmissions requeue
        with a send-count-scaled backoff; when the pending set overflows
        the most-transmitted payloads are dropped first.
        """
        from corrosion_tpu.agent.transport import TokenBucket

        cfg = self.config
        bucket = TokenBucket(cfg.bcast_rate_limit, clock=self._clock)
        # (due_time, frame, cv, remaining, sent_to) — sent_to mirrors the
        # reference's per-payload sent_to set (broadcast/mod.rs:683-690):
        # a payload is never retransmitted to a peer that already got it
        pending: List[tuple] = []
        buffer: List[tuple] = []  # (frame, cv, remaining, sent_to)
        buf_bytes = 0
        last_flush = self._clock.monotonic()

        async def flush():
            nonlocal buffer, buf_bytes, last_flush
            batch, buffer, buf_bytes = buffer, [], 0
            last_flush = self._clock.monotonic()
            if not batch:
                return
            # per-destination frame groups: each payload picks its own
            # fanout targets (all-ring0 + global sample for our own
            # changes' first transmission; random sample after)
            by_dest: Dict[Tuple[str, int], List[tuple]] = {}
            for frame, cv, remaining, sent_to in batch:
                local = cv.actor_id.bytes == self.actor_id
                targets = self.members.sample(
                    cfg.fanout, self._rng,
                    ring0_first=cfg.ring0_enabled and local and not sent_to,
                    exclude=sent_to,
                )
                for m in targets:
                    by_dest.setdefault(tuple(m.addr), []).append(
                        (frame, sent_to, m.actor_id)
                    )
                # requeue while transmissions remain and coverage is not
                # exhausted; sent_to only records SUCCESSFUL deliveries,
                # so a peer that missed a transient send stays eligible
                # and keeps the entry alive (empty targets = every alive
                # member already got it)
                if remaining > 1 and targets:
                    due = self._clock.monotonic() + cfg.rebroadcast_delay * (
                        cfg.max_transmissions - remaining + 1
                    )
                    pending.append((due, frame, cv, remaining - 1, sent_to))
            self.metrics.counter("corro_broadcast_flushes_total")
            self.metrics.gauge(
                "corro_broadcast_pending_depth", float(len(pending)))
            # destinations flush CONCURRENTLY: under the shared mux
            # connection one peer's backpressured drain must not stall
            # gossip to every other peer (and even on dedicated
            # connections this overlaps the network round-trips)
            async def send_one(dest, entries):
                blob = b"".join(frame for frame, _, _ in entries)
                await bucket.consume(len(blob))
                ok = await self.transport.send_uni(
                    dest, blob, header=STREAM_UNI
                )
                if ok:
                    # mark delivered only on success so a failed send's
                    # peers stay eligible for retransmission
                    for _, sent_to, actor_id in entries:
                        sent_to.add(actor_id)
                    return len(entries)
                self.metrics.counter("corro_broadcast_send_failures_total")
                return 0

            results = await asyncio.gather(
                *(send_one(d, e) for d, e in by_dest.items()),
                return_exceptions=True,
            )
            sends = 0
            for r in results:
                if isinstance(r, int):
                    sends += r
                elif isinstance(r, BaseException):
                    # an unexpected send-path error must be VISIBLE,
                    # not filtered out by the gather
                    self.metrics.counter(
                        "corro_broadcast_send_failures_total")
                    logger.warning("broadcast send failed: %r", r)
            if sends:
                self.metrics.counter("corro_broadcast_sent_total", sends)
            dropped = _drop_most_transmitted(pending, cfg.bcast_max_pending)
            if dropped:
                self.metrics.counter(
                    "corro_broadcast_pending_dropped_total", dropped
                )

        while True:
            self._bcast_wakeups += 1
            now = self._clock.monotonic()
            # requeued retransmissions that are due
            due_now = [p for p in pending if p[0] <= now]
            if due_now:
                pending[:] = [p for p in pending if p[0] > now]
                for _, frame, cv, remaining, sent_to in due_now:
                    buffer.append((frame, cv, remaining, sent_to))
                    buf_bytes += len(frame)
            # idle agents block on the queue (or the next retransmission
            # due time) instead of polling — zero wakeups when nothing is
            # in flight
            if buffer:
                timeout = max(
                    0.001, cfg.bcast_flush_interval - (now - last_flush)
                )
            elif pending:
                timeout = max(0.001, min(p[0] for p in pending) - now)
            else:
                timeout = None
            try:
                cv, remaining, hop, tp, sig = await self._clock.wait_for(
                    self._bcast_queue.get(), timeout=timeout
                )
                frame = self.encode_broadcast_frame(cv, hop, tp, sig)
                buffer.append((frame, cv, remaining, set()))
                buf_bytes += len(frame)
            except asyncio.TimeoutError:
                pass
            if buf_bytes >= cfg.bcast_buffer_cutoff or (
                buffer
                and self._clock.monotonic() - last_flush
                >= cfg.bcast_flush_interval
            ):
                await flush()

    def encode_broadcast_frame(self, cv: ChangeV1, hop: int = 0,
                               traceparent: Optional[str] = None,
                               sig: Optional[bytes] = None) -> bytes:
        """One queued broadcast → the exact on-wire frame bytes
        (speedy UniPayload + u32-BE framing; optional debug-hop prefix).
        With ``bcast_trace_propagation`` the payload rides the versioned
        traced envelope (hop + traceparent ahead of the classic bytes —
        receivers accept both formats).  When signing is configured
        (``sig_secret``/``sig_key_file``) this node's OWN full
        changesets are signed here and ride the v2 SIGNED envelope;
        a relayed payload's origin signature (``sig``) passes through
        unchanged — a relay cannot re-sign what it did not author.
        Unsigned and trace-off configurations emit the pre-signing
        bytes exactly.  Shared by the live broadcast loop and the
        deterministic scheduler (``agent/det.py``) so both emit
        identical bytes."""
        payload = speedy.encode_uni_payload(
            UniPayload(
                broadcast=BroadcastV1(change=cv),
                cluster_id=ClusterId(self.config.cluster_id),
            )
        )
        if (sig is None and self._sig_secret is not None
                and cv.actor_id.bytes == self.actor_id):
            sig = self._sign_changeset(cv.changeset)
        if sig is not None:
            # the v2 envelope carries the trace slot structurally, but
            # the CONTENT still honors bcast_trace_propagation — signing
            # must not become a side channel that re-enables wire trace
            # context the operator turned off
            payload = speedy.encode_signed_uni(
                payload,
                traceparent if self.config.bcast_trace_propagation
                else None,
                hop, sig,
            )
        elif self.config.bcast_trace_propagation:
            payload = speedy.encode_traced_uni(payload, traceparent, hop)
        if self.config.debug_hops:
            payload = bytes([min(hop, 255)]) + payload
        return speedy.frame(payload)

    def decode_uni_frame_meta(
        self, payload: bytes
    ) -> Optional[Tuple[ChangeV1, Optional[str], int, Optional[bytes]]]:
        """One deframed uni-stream payload → ``(ChangeV1, traceparent,
        hop, sig)``, or None on a decode error / foreign cluster.
        Classic (untraced) payloads yield ``(cv, None, 0, None)``."""
        dbg_hop = 0
        if self.config.debug_hops and payload:
            dbg_hop, payload = payload[0], payload[1:]
        try:
            payload, tp, hop, sig = speedy.decode_uni_envelope(payload)
            up = speedy.decode_uni_payload(payload)
        except speedy.SpeedyError:
            self.metrics.counter("corro_wire_decode_errors_total")
            return None
        if int(up.cluster_id) != self.config.cluster_id:
            return None
        cv = up.broadcast.change
        if self.config.debug_hops:
            key = self._seen_key(cv)
            with self._seen_lock:
                self._recv_hops.setdefault(key, dbg_hop)
        return cv, tp, hop, sig

    def decode_uni_frame(self, payload: bytes) -> Optional[ChangeV1]:
        """One deframed uni-stream payload → its ChangeV1 (or None on a
        decode error / foreign cluster).  Shared by the live uni-stream
        server and the deterministic scheduler."""
        decoded = self.decode_uni_frame_meta(payload)
        return decoded[0] if decoded is not None else None

    # ------------------------------------------------------------------
    # ingest pipeline (handle_changes parity: bounded queue, batching,
    # apply workers off the event loop)
    # ------------------------------------------------------------------

    def _enqueue_ingest(self, item, source) -> None:
        """Shared bounded enqueue: drop-oldest on overflow
        (handlers.rs:904-923 policy) + channel accounting + wakeup."""
        if len(self._ingest) >= self.config.processing_queue_len:
            self._ingest.popleft()
            self.metrics.counter("corro_changes_dropped_total")
            self.metrics.counter(
                "corro_channel_drops_total", channel="changes")
        self.metrics.counter(
            "corro_channel_sends_total", channel="changes")
        self._ingest.append((item, source))
        if self._ingest_event is not None:
            self._ingest_event.set()

    def enqueue_change(self, cv: ChangeV1, source: ChangeSource) -> None:
        """Queue an incoming changeset; oldest entries drop on overflow."""
        self._enqueue_ingest(cv, source)
        if source is ChangeSource.SYNC:
            n = len(cv.changeset.changes) if cv.changeset.is_full else 0
            self.metrics.counter("corro_sync_changes_received_total", n)

    async def _change_loop(self) -> None:
        """Batch + dispatch loop: up to ``max_concurrent_applies`` batches
        in flight on the worker pool at once (handlers.rs:742-956 runs ≤5
        concurrent ``process_multiple_changes``).  Out-of-order completion
        is safe: version/seq bookkeeping is idempotent and every apply
        transaction serializes on the storage lock."""
        cfg = self.config
        inflight = self._apply_inflight
        while True:
            if not self._ingest:
                self._ingest_event.clear()
                if inflight:
                    # wake on new work OR a completed apply
                    ev = asyncio.ensure_future(self._ingest_event.wait())
                    try:
                        done, _ = await asyncio.wait(
                            inflight | {ev},
                            return_when=asyncio.FIRST_COMPLETED,
                        )
                    finally:
                        if not ev.done():
                            ev.cancel()
                    for fut in done - {ev}:
                        inflight.discard(fut)
                        self._finish_apply(fut)
                    if not self._ingest:
                        continue
                else:
                    await self._ingest_event.wait()
            # cost-based batch: drain until the summed change count hits
            # apply_queue_len or a short tick passes (handlers.rs:755)
            batch: List[tuple] = []
            cost = 0
            deadline = self._clock.monotonic() + cfg.apply_queue_timeout
            while cost < cfg.apply_queue_len:
                if not self._ingest:
                    remaining = deadline - self._clock.monotonic()
                    if remaining <= 0 or batch:
                        break
                    try:
                        await asyncio.wait_for(
                            self._ingest_event.wait(), timeout=remaining
                        )
                    except asyncio.TimeoutError:
                        break
                    continue
                item, source = self._ingest.popleft()
                batch.append((item, source))
                if source is None:
                    # raw uni payload, decoded in the worker: true change
                    # count is unknown pre-decode, so estimate from the
                    # payload size (speedy changes run ~100+ bytes) so
                    # apply_queue_len keeps bounding real batch work
                    cost += max(1, len(item[0]) >> 7)
                else:
                    cost += max(
                        1,
                        len(item.changeset.changes)
                        if item.changeset.is_full else 1,
                    )
            if not batch:
                continue
            while len(inflight) >= cfg.max_concurrent_applies:
                done, rest = await asyncio.wait(
                    inflight, return_when=asyncio.FIRST_COMPLETED
                )
                inflight.clear()
                inflight.update(rest)
                for fut in done:
                    self._finish_apply(fut)
            fut = asyncio.ensure_future(
                self._loop.run_in_executor(
                    self._apply_pool, self._apply_batch, batch
                )
            )
            inflight.add(fut)

    def _finish_apply(self, fut) -> None:
        try:
            results = fut.result()
        except asyncio.CancelledError:
            # shutdown-time cancellation is not an apply failure: let it
            # propagate instead of polluting the error metric
            raise
        except Exception:
            self.metrics.counter("corro_changes_apply_errors_total")
            return
        for cv, source, news, meta in results:
            if news and source is ChangeSource.BROADCAST:
                self.metrics.counter("corro_broadcast_rebroadcast_total")
                self.metrics.counter(
                    "corro_channel_sends_total", channel="bcast")
                self._bcast_queue.put_nowait(
                    (cv, self.config.max_transmissions,
                     self._rebroadcast_hop(cv, meta),
                     meta[0] if meta is not None else None,
                     self._meta_sig(meta))
                )

    def _apply_batch(self, batch: List[tuple]) -> List[tuple]:
        """Apply a batch on a worker thread; returns (cv, source, news).

        Raw uni-stream payloads (enqueued undecoded so the event loop
        never blocks on deserialization) are speedy-decoded here, and
        consecutive complete changesets from the same actor are merged
        into ONE apply transaction (one fsync instead of N)."""
        with self._apply_gauge_lock:
            self._apply_active += 1
            self._apply_max_overlap = max(
                self._apply_max_overlap, self._apply_active
            )
            self.metrics.gauge("corro_apply_in_flight", self._apply_active)
        self.metrics.histogram("corro_apply_batch_size", len(batch))
        out = []
        try:
            with self.metrics.timed("corro_apply_seconds"):
                items: List[tuple] = []
                for item, source in batch:
                    if source is None:
                        # raw uni payload, decode off-loop; the item
                        # carries (payload, delivering_peer) so a
                        # failed signature can blame the transport
                        payload, peer = item
                        try:
                            decoded = self.decode_uni_frame_meta(payload)
                        except Exception:
                            # decode catches SpeedyError, but a hostile
                            # frame can raise others (e.g. invalid
                            # UTF-8): one bad payload must not abort
                            # the whole batch's valid changesets
                            self.metrics.counter(
                                "corro_wire_decode_errors_total")
                            decoded = None
                        if decoded is not None:
                            cv, tp, hop, sig = decoded
                            items.append((
                                cv, ChangeSource.BROADCAST,
                                (tp, hop, sig, peer),
                            ))
                    else:
                        items.append((item, source, None))
                i, n = 0, len(items)
                while i < n:
                    cv, source, _meta = items[i]
                    j = i + 1
                    cs = cv.changeset
                    if cs.is_full and cs.is_complete():
                        actor = cv.actor_id.bytes
                        while j < n:
                            cv2, _s2, _m2 = items[j]
                            cs2 = cv2.changeset
                            if (cv2.actor_id.bytes != actor
                                    or not cs2.is_full
                                    or not cs2.is_complete()):
                                break
                            j += 1
                    if j - i > 1:
                        out.extend(self._handle_change_group(items[i:j]))
                    else:
                        t0 = time.perf_counter()
                        try:
                            news = self.handle_change(
                                cv, source, rebroadcast=False, meta=_meta,
                                record_prov=False,
                            )
                        except Exception:
                            self.metrics.counter(
                                "corro_changes_apply_errors_total")
                            news = False
                        self._record_apply_span(
                            cv, _meta, news,
                            (time.perf_counter() - t0) * 1e3,
                        )
                        out.append((cv, source, news, _meta))
                    i = j
                # one provenance flush for the whole batch (the
                # per-item calls above defer with record_prov=False)
                self._record_provenance_many(out)
                # device-resident apply: drain the write-behind queue
                # on this worker (the "ordered executemany on the apply
                # pool") once enough batches have accumulated; the
                # maintenance tick sweeps stragglers
                if self.storage.flush_should_drain():
                    self.storage.flush_pending()
        finally:
            with self._apply_gauge_lock:
                self._apply_active -= 1
                self.metrics.gauge(
                    "corro_apply_in_flight", self._apply_active
                )
        return out

    def _handle_change_group(self, group: List[tuple]) -> List[tuple]:
        """Process consecutive complete changesets from one actor in one
        merged apply transaction.  Dedup/clock/metrics/rebroadcast stay
        per changeset; if the merged transaction fails, each changeset is
        retried in its own transaction so one poisoned changeset only
        kills itself."""
        flags: List[Optional[bool]] = [None] * len(group)
        live_idx: List[int] = []
        dropped = [False] * len(group)
        for k, (cv, source, _meta) in enumerate(group):
            if self._pre_change(cv, source, _meta):
                live_idx.append(k)
            else:
                # dedup/self-origin drop: handle_change returns without
                # any accounting here, so the group path must too
                flags[k] = False
                dropped[k] = True
        t0 = time.perf_counter()
        if live_idx:
            live = [group[k][0] for k in live_idx]
            live_sources = [group[k][1] for k in live_idx]
            live_metas = [group[k][2] for k in live_idx]
            try:
                news_flags = self._apply_complete_group(
                    live[0].actor_id.bytes, live, live_sources,
                    live_metas,
                )
            except Exception:
                # not an apply error yet: the per-changeset retry below
                # may fully recover — only ITS failures count, the merge
                # abort itself gets its own series
                self.metrics.counter("corro_apply_group_fallbacks_total")
                self._flight_event(
                    "apply_group_fallback",
                    actor=live[0].actor_id.bytes.hex(), size=len(live),
                )
                news_flags = []
                for cv, src, mta in zip(live, live_sources, live_metas):
                    try:
                        news_flags.append(
                            self._process_changeset(cv, src, mta)
                        )
                    except Exception:
                        self.metrics.counter(
                            "corro_changes_apply_errors_total")
                        news_flags.append(False)
            for k, news in zip(live_idx, news_flags):
                flags[k] = news
        if any(flags):
            # one post-group sweep: compaction is idempotent maintenance,
            # so per-changeset sweeps inside one merged tx are redundant
            self._compact_best_effort()
        group_ms = (time.perf_counter() - t0) * 1e3
        out = []
        for k, (cv, source, meta) in enumerate(group):
            news = bool(flags[k])
            if not dropped[k]:
                try:
                    # per-item guard: a raising on_change subscriber
                    # must not abort accounting for the rest of a group
                    # whose transaction already committed
                    self._post_change(cv, source, news, rebroadcast=False,
                                      compact=False, meta=meta,
                                      record_prov=False)
                except Exception:
                    self.metrics.counter("corro_changes_apply_errors_total")
                self._record_apply_span(cv, meta, news, group_ms,
                                        group=len(group))
            out.append((cv, source, news, meta))
        return out

    def _apply_complete_group(
        self, actor: bytes, cvs: List[ChangeV1],
        sources: Optional[List[ChangeSource]] = None,
        metas: Optional[List] = None,
    ) -> List[bool]:
        """Merge several COMPLETE changesets from ``actor`` under one
        storage lock + one apply transaction.  The already-have gate is
        evaluated up front (before any mutation), and the in-memory
        bookie state is snapshotted and RESTORED if the transaction
        fails — otherwise the rolled-back versions would read as
        'contained' and the per-changeset retry in
        ``_handle_change_group`` would silently skip them.  Bookkeeping
        rows flush via the bookie's executemany batch variants.

        ``sources`` gates the equivocation bookkeeping per changeset
        (digests remembered / compared for BROADCAST only); omitted =
        sync-like, no digest bookkeeping (harness seeding paths)."""
        if sources is None:
            sources = [ChangeSource.SYNC] * len(cvs)
        if metas is None:
            metas = [None] * len(cvs)
        with self.storage._lock:
            booked = self.bookie.for_actor(actor)
            flags: List[bool] = []
            to_apply: List[ChangeV1] = []
            # version -> (cs, source, meta) accepted within THIS
            # batch: a back-to-back conflicting pair lands here before
            # any digest is remembered, so the in-batch dup must
            # compare against the batch member directly
            batch_cs: Dict[int, tuple] = {}
            for cv, src, mta in zip(cvs, sources, metas):
                v = int(cv.changeset.version)
                if v in batch_cs:
                    first_cs, first_src, first_meta = batch_cs[v]
                    if (self.config.equivocation_detection
                            and src is ChangeSource.BROADCAST
                            and first_src is ChangeSource.BROADCAST):
                        dup_dig = _changes_digest(cv.changeset.changes)
                        if dup_dig != _changes_digest(first_cs.changes):
                            # the in-batch conflicting pair runs the
                            # same signed-attribution decision as the
                            # dup paths — with the first member's
                            # signature verified directly (it is in
                            # hand, no store round-trip needed)
                            self._equiv_verdict(
                                actor, cv.changeset, "content", mta,
                                first=(first_cs,
                                       self._meta_sig(first_meta)),
                                digest=dup_dig,
                            )
                    flags.append(False)
                    continue
                if booked.contains_version(v) and v not in booked.partials:
                    # same duplicate gate as _process_changeset_locked:
                    # a conflicting re-send must not slip past the
                    # merged path's dedup either (broadcast scope —
                    # see _check_content_equivocation)
                    if src is ChangeSource.BROADCAST:
                        self._check_content_equivocation(
                            actor, cv.changeset, mta
                        )
                    flags.append(False)
                    continue
                batch_cs[v] = (cv.changeset, src, mta)
                to_apply.append(cv)
                flags.append(True)
            if not to_apply:
                return flags
            snapshot = self.bookie.snapshot_actor(actor)
            try:
                with self.storage.apply_tx():
                    for cv in to_apply:
                        self.storage.apply_changes_in_tx(
                            cv.changeset.changes
                        )
                    rows: List[tuple] = []
                    for cv in to_apply:
                        cs = cv.changeset
                        v = int(cs.version)
                        # in-memory BEFORE persist: the gap diff reads
                        # the post-apply needed set (persist_version
                        # contract)
                        booked.apply_version(
                            v, cs.max_db_version(), int(cs.last_seq),
                            cs.ts,
                        )
                        rows.append((
                            v, cs.max_db_version(), int(cs.last_seq),
                            int(cs.ts) if cs.ts is not None else None,
                        ))
                    self.bookie.persist_versions(actor, rows)
                    self.bookie.clear_partials(actor, [r[0] for r in rows])
            except BaseException:
                # the DB rolled back: memory must match, or every one
                # of these versions would be skipped as already-applied
                self.bookie.restore_actor(actor, snapshot)
                raise
            if self.config.equivocation_detection:
                for cv in to_apply:
                    cs = cv.changeset
                    _cs, src, mta = batch_cs[int(cs.version)]
                    if src is ChangeSource.BROADCAST:
                        self._remember_digest(
                            actor, int(cs.version),
                            _changes_digest(cs.changes),
                            sig=self._meta_sig(mta),
                        )
            return flags

    # ------------------------------------------------------------------
    # change ingestion (handle_changes parity)
    # ------------------------------------------------------------------

    def _seen_key(self, cv: ChangeV1):
        cs = cv.changeset
        if cs.is_full:
            return (cv.actor_id.bytes, int(cs.version), cs.seqs)
        if cs.is_empty_variant:
            return (cv.actor_id.bytes, "empty", cs.versions)
        return (cv.actor_id.bytes, "empty_set", cs.ranges)

    # -- equivocation defense (docs/faults.md) -------------------------

    def _screen_changeset(self, cs) -> Optional[str]:
        """Structural sanity screen for full changesets; returns the
        rejection kind or None.  A correct origin can never produce an
        inverted seq span, a ``last_seq`` below the span end, or a
        claimed width past ``_MAX_SEQ_SPAN`` — such metadata would
        wedge partial-version buffering (a version whose completion
        seq can never arrive) or lie about completeness."""
        if not cs.is_full or cs.seqs is None or cs.last_seq is None:
            return None
        s, e = int(cs.seqs[0]), int(cs.seqs[1])
        last = int(cs.last_seq)
        if s < 0 or e < s or last < e:
            return "span"
        if (e - s) >= _MAX_SEQ_SPAN or last >= _MAX_SEQ_SPAN:
            return "span"
        for ch in cs.changes:
            if not s <= int(ch.seq) <= e:
                return "span"
        return None

    def _load_equiv_digests(self) -> None:
        """Boot-time reload of the accepted-content digests (newest
        ``seen_cache_size``, re-inserted oldest-first so the in-memory
        FIFO keeps evicting in age order), their signatures, and the
        persisted SIGNED-equivocation proofs — a proven equivocator
        stays permanently quarantined across its victim's reboot."""
        conn = self.storage.conn
        conn.execute(
            "CREATE TABLE IF NOT EXISTS __corro_equiv_digests ("
            " actor_id BLOB NOT NULL, version INTEGER NOT NULL,"
            " digest BLOB NOT NULL, PRIMARY KEY (actor_id, version))"
        )
        # pre-signing databases hold the 3-column table: widen in place
        cols = {r[1] for r in conn.execute(
            "PRAGMA table_info(__corro_equiv_digests)"
        ).fetchall()}
        if "sig" not in cols:
            conn.execute(
                "ALTER TABLE __corro_equiv_digests ADD COLUMN sig BLOB"
            )
        conn.execute(
            "CREATE TABLE IF NOT EXISTS __corro_equiv_proofs ("
            " actor_id BLOB NOT NULL PRIMARY KEY,"
            " version INTEGER NOT NULL, kind TEXT NOT NULL,"
            " msg_a BLOB, sig_a BLOB, msg_b BLOB, sig_b BLOB)"
        )
        rows = conn.execute(
            "SELECT actor_id, version, digest, sig"
            " FROM __corro_equiv_digests ORDER BY rowid DESC LIMIT ?",
            (self.config.seen_cache_size,),
        ).fetchall()
        for actor, v, digest, sig in reversed(rows):
            key = (bytes(actor), int(v))
            self._equiv_digests[key] = bytes(digest)
            if sig is not None:
                self._equiv_sigs[key] = bytes(sig)
        for (actor,) in conn.execute(
            "SELECT actor_id FROM __corro_equiv_proofs"
        ).fetchall():
            actor = bytes(actor)
            self._equiv_quarantined[actor] = float("inf")
            self._equiv_proofed.add(actor)
            # the member record may not exist yet at boot; the
            # _pre_change drop path re-asserts the flag when the
            # proven actor's traffic next shows up
            self.members.set_quarantined(
                actor, True, reason="signed_equivocation"
            )

    def _remember_digest(self, actor: bytes, v: int, digest: bytes,
                         sig: Optional[bytes] = None) -> None:
        """Record the accepted content digest for ``(actor, v)`` —
        in-memory FIFO + durable write-through — plus the origin
        signature when the delivery carried one: a later conflicting
        SIGNED re-claim needs both halves of the pair to form a proof.
        Only the raw signature is stored; the message it covers is
        rebuilt at EVIDENCE time (``_stored_sig_message``) from the
        digest and bookkeeping — a complete changeset's seq span is
        always ``(0, last_seq)``, so the accept hot path pays no
        message construction.  Callers hold the storage lock (both
        sites sit inside apply paths), so the durable row commits on
        the shared write connection without a re-acquire; persistence
        failure never blocks the apply seam."""
        evicted = None
        with self._equiv_lock:
            dig = self._equiv_digests
            dig[(actor, v)] = digest
            if sig is not None:
                self._equiv_sigs[(actor, v)] = sig
            if len(dig) > self.config.seen_cache_size:
                evicted = next(iter(dig))
                dig.pop(evicted)
                self._equiv_sigs.pop(evicted, None)
        try:
            self.storage.conn.execute(
                "INSERT OR REPLACE INTO __corro_equiv_digests"
                " (actor_id, version, digest, sig)"
                " VALUES (?, ?, ?, ?)",
                (actor, v, digest, sig),
            )
            if evicted is not None:
                self.storage.conn.execute(
                    "DELETE FROM __corro_equiv_digests"
                    " WHERE actor_id = ? AND version = ?",
                    evicted,
                )
        except Exception:
            logger.debug("equiv digest persist failed", exc_info=True)

    def _stored_sig_message(self, actor: bytes, v: int,
                            digest: bytes) -> Optional[bytes]:
        """Rebuild the exact ``sig_message`` the ACCEPTED content was
        signed over, from bookkeeping + the stored digest: a complete
        changeset's seq span is ``(0, last_seq)`` by definition, and
        ``last_seq``/``ts`` were recorded at apply time.  Evidence-time
        only — the accept path stores just the 64-byte signature."""
        bv = self.bookie.for_actor(actor)
        entry = bv.versions.get(v)
        if entry is None:
            return None
        _dbv, last_seq = entry
        ts = self.bookie.version_ts(actor, v)
        return _sig_message_raw(
            actor, v, 0, last_seq, last_seq, ts, digest
        )

    # -- signed attribution (docs/faults.md) ---------------------------

    @staticmethod
    def _meta_sig(meta) -> Optional[bytes]:
        return meta[2] if meta is not None and len(meta) > 2 else None

    @staticmethod
    def _meta_peer(meta):
        return meta[3] if meta is not None and len(meta) > 3 else None

    def _sign_changeset(self, cs) -> Optional[bytes]:
        """Sign one of OUR full changesets (cached per version: the
        broadcast loop re-frames on retransmission, the statement
        signed never changes)."""
        if self._sig_secret is None or not cs.is_full:
            return None
        v = int(cs.version)
        sig = self._sig_own_cache.get(v)
        if sig is None:
            from corrosion_tpu.types import crypto

            sig = crypto.sign(
                self._sig_secret, sig_message(self.actor_id, cs)
            )
            cache = self._sig_own_cache
            cache[v] = sig
            if len(cache) > 1024:
                cache.pop(next(iter(cache)))
        return sig

    def _verify_changeset_sig(self, actor: bytes, cs,
                              sig: Optional[bytes],
                              digest: Optional[bytes] = None,
                              ) -> Optional[bool]:
        """Evidence-time verification: True/False when it actually ran
        (counted under ``corro_sig_verifications_total{result=}``),
        None when unverifiable (no signature on the delivery, or the
        origin has no key in the trust directory).  ``digest`` skips
        the ``_changes_digest`` recompute when the caller already paid
        for it (every content-conflict caller has)."""
        if sig is None:
            return None
        pub = self._sig_pubkeys.get(actor)
        if pub is None:
            return None
        from corrosion_tpu.types import crypto

        ok = crypto.verify_cached(pub, sig_message(actor, cs, digest), sig)
        self.metrics.counter(
            "corro_sig_verifications_total",
            result="ok" if ok else "fail",
        )
        return ok

    def _sig_evidence_budget(self) -> bool:
        """Token-bucket admission for evidence-triggered verification
        (``sig_evidence_verify_rate``/s refill, 2x burst).  The spot
        check has its own interval bound; this one keeps the paths an
        ATTACKER can fire at will — digest conflicts and span-screen
        trips are both manufacturable from any accepted changeset —
        from turning ~ms pure-Python verifies into an ingest tax."""
        rate = self.config.sig_evidence_verify_rate
        if rate <= 0.0:
            return True
        now = self._clock.monotonic()
        with self._sig_lock:
            self._sig_ev_tokens = min(
                2.0 * rate,
                self._sig_ev_tokens + (now - self._sig_ev_stamp) * rate,
            )
            self._sig_ev_stamp = now
            if self._sig_ev_tokens < 1.0:
                return False
            self._sig_ev_tokens -= 1.0
        return True

    def _spot_check_due(self, actor: bytes, v: int) -> bool:
        """Deterministic, bounded spot-check sampling: a pure hash of
        (this node, actor, version) against ``sig_spot_check_rate`` —
        no rng stream, so virtual campaigns replay identically — and a
        minimum spacing on the injected clock so pure-Python
        verification can never dominate ingest."""
        rate = self.config.sig_spot_check_rate
        # the ACTOR must be keyed before anything else: an admitted
        # candidate claims the interval slot, and verification of an
        # unkeyed actor returns None — in a partially-keyed cluster a
        # chatty unkeyed actor would otherwise eat every slot and the
        # keyed actors' tripwire would go dark
        if rate <= 0.0 or self._sig_pubkeys.get(actor) is None:
            return False
        # interval bound FIRST: it rejects almost every candidate
        # during bursts, and a float compare is ~10x cheaper than the
        # sampling hash — the hash only runs when a verify could
        # actually be admitted
        now = self._clock.monotonic()
        # check + claim under one lock hold: two apply workers racing
        # the stamp would both admit a verify inside one interval (the
        # hash between them costs ~µs, far under a saved ~ms verify)
        with self._sig_lock:
            if now - self._sig_last_spot \
                    < self.config.sig_spot_check_min_interval_s:
                return False
            h = hashlib.blake2b(
                b"sig-spot" + self.actor_id + actor + struct.pack("<Q", v),
                digest_size=8,
            ).digest()
            if int.from_bytes(h, "big") / 2.0**64 >= rate:
                return False
            self._sig_last_spot = now
        return True

    def _get_breaker(self, addr):
        """The per-peer transport breaker for ``addr``, created with a
        bounded insert when absent (this path is reachable with
        attacker-controlled ephemeral source addresses — tampered
        deliveries from unknown hosts).  None when the transport
        carries no breaker registry.

        Delegates to ``Transport._breaker`` (same thresholds — the
        transport is constructed from this config — same on_evict
        restore via ``on_breaker``, and its registry lock: this runs
        on apply-pool threads concurrently with the loop's dials).
        The fallback covers registry-only doubles like the virtual
        cluster's ``_TransportStub``, which are single-threaded."""
        transport = self.transport
        mk = getattr(transport, "_breaker", None)
        if mk is not None:
            return mk(addr)
        breakers = getattr(transport, "breakers", None)
        if breakers is None:
            return None
        b = breakers.get(addr)
        if b is None:
            from corrosion_tpu.agent.transport import (
                CircuitBreaker, prune_breakers,
            )

            prune_breakers(
                breakers, 4 * getattr(transport, "max_cached", 256),
                on_evict=lambda a: self._on_breaker(a, False),
            )
            b = breakers[addr] = CircuitBreaker(
                self.config.breaker_threshold,
                self.config.breaker_cooldown,
                now=self._clock.monotonic,
            )
        return b

    def _trip_breaker(self, addr) -> None:
        """Force the per-peer transport breaker OPEN (verified-hostile
        evidence: tampered bytes or garbage serves are not ordinary
        flakiness worth `threshold` free strikes)."""
        addr = tuple(addr)
        b = self._get_breaker(addr)
        if b is not None and b.trip():
            self.metrics.counter("corro_transport_breaker_opens_total")
            self._on_breaker(addr, True)

    def _strike_breaker(self, addr) -> None:
        """One breaker failure strike (AMBIGUOUS evidence: a sync
        session deadline could be an honest slow peer, so it earns
        `threshold` free strikes before quarantine — unlike the hard
        `_trip_breaker` reserved for verified-hostile bytes).  Keeps a
        slow-trickle server from being re-selected round after round
        forever: enough deadline aborts open its breaker and
        `_choose_sync_peers` stops offering it sessions."""
        addr = tuple(addr)
        fail = getattr(self.transport, "_breaker_failure", None)
        if fail is not None:
            fail(addr)
            return
        b = self._get_breaker(addr)
        if b is not None and b.record_failure():
            self.metrics.counter("corro_transport_breaker_opens_total")
            self._on_breaker(addr, True)

    def _blame_relay(self, peer) -> None:
        """A signature FAILURE convicts the DELIVERY, never the named
        origin: the one thing an invalid signature proves is that the
        (claimed origin, content, signature) triple was not produced
        by the origin's key — the tamperer could be the delivering
        relay or a forger upstream, but the origin cannot be framed by
        it.  So the delivering transport eats breaker-class (bounded,
        half-open-recoverable) quarantine, and the origin's verdict
        state is never touched."""
        if peer is None:
            return
        addr = tuple(peer)
        if not any(tuple(m.addr) == addr for m in self.members.all()):
            # live inbound streams carry the peer's EPHEMERAL port,
            # not its gossip address; when exactly one member shares
            # the host the delivery is attributable anyway (distinct
            # hosts in real deployments — a loopback harness stays
            # unattributed rather than blaming the wrong node).
            # NAMED RESIDUAL (docs/faults.md): an UNREGISTERED process
            # co-located with that one member (container/NAT sharing
            # its host) can draw this transport-class blame onto it.
            # Bounded by construction — it is breaker-class (sampling
            # deprioritization, half-open-recoverable once the
            # tampered traffic stops), never the actor-class verdict
            # a signature proof mints — and the alternative, dropping
            # the fallback, would leave live tampering relays entirely
            # unattributable since exact addr matches never happen on
            # real inbound sockets.
            same_host = [
                m for m in self.members.all()
                if tuple(m.addr)[0] == addr[0]
            ]
            if len(same_host) == 1:
                addr = tuple(same_host[0].addr)
        # breaker FIRST: a newly-opened breaker's _on_breaker labels
        # the member reason="breaker", and the equal-rank relabel rule
        # means whichever transport-class reason lands LAST wins — the
        # specific evidence class must be the one that sticks
        self._trip_breaker(addr)
        hit = self.members.quarantine_by_addr(addr, True,
                                              reason="sig_failure")
        if hit:
            self.metrics.counter(
                "corro_members_quarantine_transitions_total",
                state="sig_failure",
            )
            self._flight_event(
                "quarantine", addr=f"{addr[0]}:{addr[1]}", on=True,
                reason="sig_failure",
            )

    def _equiv_verdict(self, actor: bytes, cs, kind: str, meta,
                       first: Optional[tuple] = None,
                       digest: Optional[bytes] = None) -> bool:
        """Attribution decision once hostile evidence fired (a content
        conflict or a span-screen trip).  Returns True when the ORIGIN
        was convicted (quarantined), False when blame landed on the
        delivering relay instead.

        * delivery signature INVALID → the bytes were tampered in
          transit; the relay's breaker is quarantined and the origin
          is untouched (unframeable);
        * delivery signature VALID → the origin really said this.  A
          signed span-garbage claim, or a signed conflict against an
          accepted content whose OWN stored signature also verifies,
          is a persistable PROOF: the quarantine is permanent;
        * unverifiable (unsigned delivery / unknown key) → the
          pre-signing bounded-window verdict, byte-for-byte;
        * signed but over the evidence-verification budget
          (``sig_evidence_verify_rate``) → the conflicting message is
          dropped with NO verdict (every caller is a drop path; the
          content never applies).  Falling back to the unsigned
          bounded-window verdict here would let a tampered-copy flood
          frame the origin — the one thing the signature exists to
          prevent.

        ``first`` = ``(accepted_cs, accepted_sig)`` for the in-batch
        conflicting-pair path, where the accepted half is in hand
        before any digest was stored.  ``digest`` = the incoming
        changeset's ``_changes_digest`` when the caller already
        computed it."""
        sig = self._meta_sig(meta)
        if sig is not None and self._sig_pubkeys.get(actor) is not None:
            if not self._sig_evidence_budget():
                self.metrics.counter(
                    "corro_sig_verifications_total", result="skipped"
                )
                return False
            if digest is None:
                digest = _changes_digest(cs.changes)
        ver = self._verify_changeset_sig(actor, cs, sig, digest)
        if ver is False:
            self._blame_relay(self._meta_peer(meta))
            return False
        proof = None
        if ver is True:
            from corrosion_tpu.types import crypto

            msg = sig_message(actor, cs, digest)
            v = int(cs.version)
            pub = self._sig_pubkeys.get(actor)
            if kind == "span":
                # one signed, structurally-impossible claim is its own
                # proof: no relay could mint it without the origin key
                proof = (v, kind, msg, sig, None, None)
            elif first is not None:
                first_cs, first_sig = first
                if first_sig is not None and pub is not None:
                    smsg = sig_message(actor, first_cs)
                    if smsg != msg and crypto.verify_cached(
                            pub, smsg, first_sig):
                        proof = (v, kind, smsg, first_sig, msg, sig)
            else:
                with self._equiv_lock:
                    ssig = self._equiv_sigs.get((actor, v))
                    sdigest = self._equiv_digests.get((actor, v))
                if ssig is not None and sdigest is not None \
                        and pub is not None:
                    smsg = self._stored_sig_message(actor, v, sdigest)
                    if (smsg is not None and smsg != msg
                            and crypto.verify_cached(pub, smsg, ssig)):
                        proof = (v, kind, smsg, ssig, msg, sig)
        self._note_equivocation(actor, kind, proof=proof)
        return True

    def _check_content_equivocation(self, actor: bytes, cs,
                                    meta=None) -> bool:
        """Compare a duplicate complete changeset's content digest
        against the accepted one for its (actor, version); a mismatch
        is equivocation (returns True after counting + quarantining).
        Byte-identical replays compare equal and stay plain
        duplicates.

        BROADCAST scope only (callers gate, and digests are only
        remembered for broadcast-applied contents): the gossiped bytes
        of one version are immutable — the origin frames them once and
        rebroadcast relays them verbatim — so any difference is
        hostile.  Sync-served content is NOT: ``_collect_changes_on``
        reconstructs a version from the CURRENT clock/data tables, so
        a re-serve after later overwrites legitimately differs from
        the original broadcast, and comparing across the two paths
        would quarantine honest origins under ordinary overwrite
        workloads.

        Two windows the per-node detector deliberately leaves to the
        CROSS-NODE checker (``ClusterObserver.no_divergence``):
        conflicting contents split across nodes so each sees only one
        (nothing to compare locally), and a conflicting pair racing a
        node's first arrival before any digest is remembered — except
        the same-apply-batch case, which ``_apply_complete_group``
        compares directly.

        Cost note: this hashes the duplicate's contents (sort + repr +
        blake2b over its few changes) whenever an accepted digest
        exists — broadcast fanout duplicates of recent versions pay
        it.  That is the price of the defense: the dedup key
        deliberately excludes content, so any cheaper per-key shortcut
        would let a later conflicting re-send launder through the
        cache.  ``equivocation_detection = false`` restores the plain
        dict-hit duplicate path."""
        if not self.config.equivocation_detection:
            return False
        if not (cs.is_full and cs.is_complete()):
            return False
        with self._equiv_lock:
            prev = self._equiv_digests.get((actor, int(cs.version)))
        if prev is None:
            return False
        dig = _changes_digest(cs.changes)
        if prev == dig:
            return False
        return self._equiv_verdict(actor, cs, "content", meta, digest=dig)

    def _note_equivocation(self, actor: bytes, kind: str,
                           proof: Optional[tuple] = None) -> None:
        """Count one hostile observation and quarantine the origin
        actor through the Members path (the breaker-quarantine shape,
        protocol-level evidence): out of ring0, deprioritized in
        sampling, reason surfaced in ``cluster_members`` — and its
        further changesets drop at ``_pre_change``, so an equivocator
        cannot keep poisoning CRDT state.

        UNSIGNED evidence gets a bounded WINDOW
        (``equiv_quarantine_s``), not a permanent severance: without a
        verified signature, attribution rests on a forgeable actor id
        (mTLS authenticates the channel, not the claimed origin of
        relayed changesets), so a hostile relay could frame an honest
        actor — an unbounded drop-all would let one forged message
        inflict permanent divergence, worse than the attack it guards.

        A verified signed ``proof`` (``_equiv_verdict``) removes that
        caveat: only the origin's key could have produced the
        conflicting pair, so the verdict becomes PERMANENT
        (``quarantine_reason="signed_equivocation"``), persisted to
        ``__corro_equiv_proofs`` so it survives this victim's restart.

        The already-accepted first content stays either way: it is
        consistent cluster-wide as long as it won every node's first
        arrival, which the no-divergence checker verifies cross-node."""
        self.metrics.counter(
            "corro_sync_equivocations_total", kind=kind
        )
        hold = self.config.equiv_quarantine_s
        deadline = (self._clock.monotonic() + hold) if hold > 0 \
            else float("inf")
        if proof is not None:
            deadline = float("inf")
        with self._equiv_lock:
            prev_deadline = self._equiv_quarantined.get(actor)
            first = prev_deadline is None
            # a signed proof escalates; a later unsigned observation
            # must never SHORTEN a standing permanent verdict
            if prev_deadline is None or deadline > prev_deadline \
                    or proof is not None:
                self._equiv_quarantined[actor] = deadline
            # escalation = the FIRST proof over a standing unsigned
            # verdict.  Tracked as a set, not inferred from the
            # deadline: equiv_quarantine_s=0 gives unsigned verdicts
            # an inf deadline too, and a proof must still relabel
            # those to signed_equivocation
            escalate = (proof is not None and not first
                        and actor not in self._equiv_proofed)
            if proof is not None:
                self._equiv_proofed.add(actor)
        reason = "signed_equivocation" if proof is not None \
            else "equivocation"
        # per-VERDICT journal record (the drop-volume "quarantined"
        # kind stays counter-only: one line per dropped message would
        # flood the bounded ring during an attack)
        self._flight_event("equivocation", actor=actor.hex(), kind=kind)
        if proof is not None:
            self._persist_equiv_proof(actor, proof)
        if first or escalate:
            logger.warning(
                "equivocation detected (kind=%s, %s) from %s: "
                "quarantining", kind, reason, actor.hex(),
            )
            self.members.set_quarantined(actor, True, reason=reason)
            self.metrics.counter(
                "corro_members_quarantine_transitions_total",
                state=reason,
            )
            self._flight_event(
                "quarantine", actor=actor.hex(), on=True,
                reason=reason,
            )

    def _persist_equiv_proof(self, actor: bytes, proof: tuple) -> None:
        """Durably record a signed-equivocation proof (idempotent —
        the first proof for an actor wins; re-offenses don't rewrite
        it).  Best-effort like the digest write-through: persistence
        failure must never break the verdict seam (the in-memory
        deadline already went permanent)."""
        v, kind, msg_a, sig_a, msg_b, sig_b = proof
        try:
            with self.storage._lock:
                self.storage.conn.execute(
                    "INSERT OR IGNORE INTO __corro_equiv_proofs"
                    " (actor_id, version, kind, msg_a, sig_a,"
                    "  msg_b, sig_b) VALUES (?, ?, ?, ?, ?, ?, ?)",
                    (actor, v, kind, msg_a, sig_a, msg_b, sig_b),
                )
        except Exception:
            logger.debug("equiv proof persist failed", exc_info=True)

    def _rebroadcast_hop(self, cv: ChangeV1, meta=None) -> int:
        """Hop count for re-gossiping a received payload: received hop
        + 1 from the traced envelope when the payload carried one,
        falling back to the debug_hops receipt table (0 without
        either)."""
        if self.config.debug_hops:
            with self._seen_lock:
                return self._recv_hops.get(self._seen_key(cv), 0) + 1
        if meta is not None:
            return meta[1] + 1
        return 0

    def handle_change(self, cv: ChangeV1, source: ChangeSource,
                      rebroadcast: bool = True, meta=None,
                      record_prov: bool = True) -> bool:
        """Process one incoming changeset; returns True if it was news.

        ``rebroadcast=False`` when called from the change loop's worker
        thread — the loop requeues news itself on the event loop.
        ``meta`` is the envelope receipt context ``(traceparent, hop,
        sig, peer)``, when the payload carried one — ``sig`` is the
        origin's Ed25519 signature from the signed envelope and
        ``peer`` the delivering transport's address (the blame target
        when that signature fails to verify).  ``record_prov=False``
        when the caller flushes the whole batch's provenance in one
        pass (``_record_provenance_many``).
        """
        if not self._pre_change(cv, source, meta):
            return False
        news = self._process_changeset(cv, source, meta)
        self._post_change(cv, source, news, rebroadcast, meta=meta,
                          record_prov=record_prov)
        return news

    def _pre_change(self, cv: ChangeV1, source: ChangeSource,
                    meta=None) -> bool:
        """Hostile screen + dedup + clock ingestion ahead of applying;
        False = drop."""
        actor = cv.actor_id.bytes
        if actor == self.actor_id:
            return False
        deadline = self._equiv_quarantined.get(actor)
        if deadline is not None:
            if self._clock.monotonic() < deadline:
                # a detected equivocator's traffic is poison while the
                # verdict holds: drop everything, count the volume
                self.metrics.counter(
                    "corro_sync_equivocations_total", kind="quarantined"
                )
                if actor in self._equiv_proofed:
                    # permanent (signed-proof) verdicts re-assert the
                    # Members flag: the record can postdate the boot
                    # reload (e.g. a reborn node that re-learned the
                    # proven actor through gossip).  Keyed on proof
                    # state, not deadline==inf: equiv_quarantine_s=0
                    # parks UNSIGNED verdicts at inf too, and those
                    # must not masquerade as signed
                    m = self.members.get(actor)
                    if m is not None and not m.quarantined:
                        self.members.set_quarantined(
                            actor, True, reason="signed_equivocation"
                        )
                return False
            # verdict expired: re-admit (bounded blast radius for a
            # FRAMED honest actor — attribution is unsigned).  The
            # digests survive, so a real equivocator's next conflicting
            # dup re-quarantines immediately.
            with self._equiv_lock:
                self._equiv_quarantined.pop(actor, None)
            self.members.set_quarantined(actor, False,
                                         reason="equivocation")
            self.metrics.counter(
                "corro_members_quarantine_transitions_total",
                state="equivocation_expired",
            )
            self._flight_event(
                "quarantine", actor=actor.hex(), on=False,
                reason="expired",
            )
        key = self._seen_key(cv)
        if source is ChangeSource.BROADCAST:
            with self._seen_lock:
                dup = key in self._seen
                if not dup:
                    self._seen[key] = None
                    if len(self._seen) > self.config.seen_cache_size:
                        evicted = next(iter(self._seen))
                        self._seen.pop(evicted)
                        self._recv_hops.pop(evicted, None)
            if dup:
                # the dedup cache must not LAUNDER equivocation: a
                # conflicting re-send shares the (actor, version, seqs)
                # key with the accepted content, so the duplicate path
                # is exactly where conflicting contents hide
                self._check_content_equivocation(actor, cv.changeset,
                                                 meta)
                return False
        # structural screen AFTER dedup: fanout duplicates drop on the
        # dict hit without paying the O(changes) span walk — a garbage
        # duplicate is inert either way (dropped, never applied or
        # buffered); only first arrivals and sync deliveries pay
        if self.config.equivocation_detection:
            kind = self._screen_changeset(cv.changeset)
            if kind is not None:
                self._equiv_verdict(actor, cv.changeset, kind, meta)
                return False
            # bounded random spot check (broadcast first arrivals
            # only): the hot path stays verification-free unless the
            # deterministic sample + minimum interval both admit it
            sig = self._meta_sig(meta)
            cs = cv.changeset
            if (sig is not None and source is ChangeSource.BROADCAST
                    and cs.is_full and cs.is_complete()
                    and self._spot_check_due(actor, int(cs.version))
                    and self._verify_changeset_sig(actor, cs, sig)
                    is False):
                self._blame_relay(self._meta_peer(meta))
                return False
        # clock ingestion: a remote ts past max_delta_ns (the 300 ms
        # gossip clock-delta rule) is REJECTED — the merge raises and
        # the local clock stays unpolluted; the changeset itself still
        # applies (data-plane convergence must not hinge on a peer's
        # oscillator)
        if cv.changeset.ts is not None:
            try:
                self.clock.update_with_timestamp(cv.changeset.ts)
            except Exception:
                pass
        return True

    def _post_change(self, cv: ChangeV1, source: ChangeSource, news: bool,
                     rebroadcast: bool, compact: bool = True,
                     meta=None, record_prov: bool = True) -> None:
        """Accounting + rebroadcast + subscription fan-out after an
        apply (``compact=False`` when the caller sweeps once per merged
        transaction group instead of per changeset)."""
        if compact and news and cv.changeset.is_full:
            # a remote apply can overwrite our own rows' change entries
            self._compact_best_effort()
        self.metrics.counter(
            "corro_changes_received_total",
            source=source.value,
            news=str(news).lower(),
        )
        if news and record_prov:
            self._record_provenance(cv, source, meta)
        if (rebroadcast and news and source is ChangeSource.BROADCAST
                and self._loop):
            self.metrics.counter("corro_broadcast_rebroadcast_total")
            self.metrics.counter(
                "corro_channel_sends_total", channel="bcast")
            self._bcast_queue.put_nowait(
                (cv, self.config.max_transmissions,
                 self._rebroadcast_hop(cv, meta),
                 meta[0] if meta is not None else None,
                 self._meta_sig(meta))
            )
        if news and self.on_change is not None:
            self.on_change(cv)

    def _record_provenance(self, cv: ChangeV1, source: ChangeSource,
                           meta) -> None:
        """Change provenance: on the FIRST arrival of each (actor,
        version), record origin-commit → apply lag per arrival path
        (``corro_change_lag_seconds{path=broadcast|rebroadcast|sync}``)
        and refresh the origin actor's staleness base — the node's own
        convergence measurement, no external harness required."""
        self._record_provenance_many(((cv, source, True, meta),))

    def _record_provenance_many(self, results) -> None:
        """Batched provenance for a whole apply batch (same semantics
        as :meth:`_record_provenance`, same ``results`` tuples
        ``_apply_batch`` returns): one dedupe-lock hold, one wall-clock
        read, and one metrics-lock hold for N changesets — per-item
        recording costs ~5% of ingest throughput, the bench overhead
        A/B's whole budget."""
        if not self.config.provenance:
            return
        now = self._clock.wall()
        # ONE arrival-HLC observation for the whole batch (mirroring the
        # single wall-clock read above): the items share one arrival
        # instant, and per-item observe_timestamp calls would take the
        # contended HLClock lock N times inside the _prov_lock hold
        hlc_now = int(self.clock.observe_timestamp())
        lags = []
        with self._prov_lock:
            seen = self._prov_seen
            origin_ts = self._origin_ts_wall
            for cv, source, news, meta in results:
                if not news:
                    continue
                cs = cv.changeset
                ts = cs.ts
                if ts is None or not cs.is_full:
                    continue
                actor = cv.actor_id.bytes
                key = (actor, int(cs.version))
                if key in seen:
                    continue
                # the first-seen STAMP (wall + the batch's arrival-HLC
                # observation): the timeline plane's raw material —
                # ClusterObserver derives the time-resolved coverage
                # curve of an (actor, version) wave from these across
                # nodes.  An observation, not `clock.last` (after
                # _pre_change merged the changeset ts, `last` can EQUAL
                # the origin commit ts and would stamp every arrival at
                # its own commit instant) and not new_timestamp
                # (telemetry must not advance the protocol clock)
                seen[key] = (now, hlc_now)
                if len(seen) > self.config.seen_cache_size:
                    seen.pop(next(iter(seen)))
                origin = ts.wall_seconds()
                if origin > origin_ts.get(actor, 0.0):
                    origin_ts[actor] = origin
                # idle clock for eviction: LOCAL receipt time, so an
                # actively-writing actor is never evicted no matter
                # how skewed its origin clock is
                self._origin_seen_wall[actor] = now
                if source is ChangeSource.SYNC:
                    lkey = _PROV_KEY_SYNC
                elif meta is not None and meta[1] > 0:
                    lkey = _PROV_KEY_REBROADCAST
                else:
                    lkey = _PROV_KEY_BROADCAST
                lags.append((lkey, now - origin if now > origin else 0.0))
        if lags:
            self.metrics.histogram_keyed_many(
                "corro_change_lag_seconds", lags
            )

    def _record_apply_span(self, cv: ChangeV1, meta, news: bool,
                           dur_ms: float, group: int = 0) -> None:
        """Complete the broadcast trace on the receiving node: one
        ``bcast.apply`` span per version FIRST ARRIVAL that carried a
        traceparent (non-news duplicates would drown the ring — the
        fanout delivers every payload several times per node)."""
        if not news or meta is None or meta[0] is None:
            return
        cs = cv.changeset
        attrs = {
            "actor": cv.actor_id.bytes.hex(),
            "hop": meta[1],
        }
        if cs.is_full:
            attrs["version"] = int(cs.version)
        if group:
            # merged-transaction apply: the duration is the group's
            attrs["group"] = group
        if tracing.record("bcast.apply", remote=meta[0],
                          duration_ms=dur_ms, **attrs) is not None:
            self.metrics.counter("corro_trace_spans_total")

    def _process_changeset(self, cv: ChangeV1,
                           source: ChangeSource = ChangeSource.SYNC,
                           meta=None) -> bool:
        # hold the storage lock across the have-it-already checks AND the
        # apply transaction: concurrent apply workers mutate the same
        # booked RangeSets, and those mutations are multi-step
        with self.storage._lock:
            return self._process_changeset_locked(cv, source, meta)

    def _process_changeset_locked(self, cv: ChangeV1,
                                  source: ChangeSource,
                                  meta=None) -> bool:
        actor = cv.actor_id.bytes
        cs = cv.changeset
        booked = self.bookie.for_actor(actor)
        ts = int(cs.ts) if cs.ts is not None else None

        if cs.is_empty_variant:
            s, e = int(cs.versions[0]), int(cs.versions[1])
            if booked.cleared.contains_span(s, e):
                return False
            with self.storage.apply_tx():
                booked.mark_cleared(s, e)
                self.bookie.persist_cleared(actor, s, e, ts)
            return True

        if cs.is_empty_set:
            # a sync EmptySet is one COMPLETE per-ts group of the
            # server's cleared ranges, so processing it justifies
            # advancing the watermark even when every range was already
            # held; marking is idempotent, so out-of-order groups are
            # safe (handlers.rs:539-734, peer.rs:715-762)
            new = False
            with self.storage.apply_tx():
                for s, e in cs.ranges:
                    if not booked.cleared.contains_span(int(s), int(e)):
                        booked.mark_cleared(int(s), int(e))
                        self.bookie.persist_cleared(actor, int(s), int(e), ts)
                        new = True
                if ts is not None:
                    booked.update_cleared_ts(cs.ts)
                    self.bookie.persist_sync_state(actor, ts)
            return new

        v = int(cs.version)
        if booked.contains_version(v) and v not in booked.partials:
            # duplicate of an accepted version: a cache-evicted
            # rebroadcast lands here — conflicting gossiped contents
            # must be caught, byte-identical replays absorbed.
            # Broadcast scope only: see _check_content_equivocation
            if source is ChangeSource.BROADCAST:
                self._check_content_equivocation(actor, cs, meta)
            return False

        if cs.is_complete():
            with self.storage.apply_tx():
                self.storage.apply_changes_in_tx(cs.changes)
                booked.apply_version(
                    v, cs.max_db_version(), int(cs.last_seq), cs.ts
                )
                self.bookie.persist_version(
                    actor, v, cs.max_db_version(), int(cs.last_seq), ts
                )
                self.bookie.clear_partial(actor, v)
            if (self.config.equivocation_detection
                    and source is ChangeSource.BROADCAST):
                self._remember_digest(
                    actor, v, _changes_digest(cs.changes),
                    sig=self._meta_sig(meta),
                )
            return True

        # partial: buffer + maybe promote.  Buffered blobs are the
        # speedy binary codec behind a one-byte format prefix (legacy
        # JSON blobs from older databases still decode on read)
        with self.storage.apply_tx():
            self.bookie.buffer_changes(
                actor, v,
                [(int(ch.seq), wire.encode_buffered_change(ch))
                 for ch in cs.changes],
            )
            partial = booked.insert_partial(
                v, (int(cs.seqs[0]), int(cs.seqs[1])), int(cs.last_seq), cs.ts
            )
            self.bookie.persist_partial(
                actor, v, (int(cs.seqs[0]), int(cs.seqs[1])),
                int(cs.last_seq), ts,
            )
            if partial.is_complete():
                buffered = [
                    wire.decode_buffered_change(blob)
                    for _, blob in self.bookie.buffered_changes(actor, v)
                ]
                self.storage.apply_changes_in_tx(buffered)
                booked.apply_version(
                    v, max((int(c.db_version) for c in buffered), default=0),
                    int(cs.last_seq), cs.ts,
                )
                self.bookie.persist_version(
                    actor, v,
                    max((int(c.db_version) for c in buffered), default=0),
                    int(cs.last_seq), ts,
                )
                self.bookie.clear_partial(actor, v)
                # promoted partials record NO digest: their chunks can
                # legitimately mix broadcast and sync deliveries, and
                # sync-served content reflects serve-time compaction —
                # an unreliable identity for 'what the actor gossiped'
        return True

    # ------------------------------------------------------------------
    # anti-entropy sync
    # ------------------------------------------------------------------

    def generate_sync(self) -> SyncStateV1:
        # snapshot under the storage/bookie lock: RangeSet mutations are
        # multi-step, so an unlocked reader could zip mismatched span
        # lists.  The snapshot is cached against the bookie generation
        # (dirty flag bumped by every bookkeeping mutation), so a burst
        # of inbound handshakes re-walks every actor's RangeSets only
        # when something actually changed.  The returned state is a
        # SHARED immutable snapshot — callers must not mutate it.
        with self.storage._lock:
            gen = self.bookie.gen
            cached = self._sync_gen_cache
            if cached is not None and cached[0] == gen:
                self.metrics.counter(
                    "corro_sync_state_cache_total", hit="true")
                return cached[1]
            state = self._generate_sync_locked()
            self._sync_gen_cache = (gen, state)
            self.metrics.counter(
                "corro_sync_state_cache_total", hit="false")
            return state

    def _generate_sync_locked(self) -> SyncStateV1:
        state = SyncStateV1(actor_id=ActorId(self.actor_id))
        for actor, bv in self.bookie.actors().items():
            last = bv.last()
            if last == 0:
                continue
            aid = ActorId(actor)
            state.heads[aid] = Version(last)
            spans = bv.needed_spans()
            if spans:
                state.need[aid] = spans
            partials = bv.partial_needs()
            if partials:
                state.partial_need[aid] = {
                    Version(v): gaps for v, gaps in partials.items()
                }
            if self.config.snapshot_serve and bv.snap_floor > 0:
                # advertised floors drive the client-side snapshot
                # dispatch: needs at-or-below a floor cannot be served
                # change-by-change from this node (docs/sync.md)
                state.snap_floors[aid] = bv.snap_floor
            if actor == self.actor_id:
                state.last_cleared_ts = bv.last_cleared_ts
        return state

    def _clear_buffered_meta(self, chunk: int = 1000) -> int:
        """Delete buffered-change/seq bookkeeping rows for versions that
        are now cleared, in bounded chunks (clear_buffered_meta_loop
        parity, util.rs:425-480).  Returns rows deleted.

        The storage lock is released and re-acquired between chunks at
        the LOW tier: the spans are snapshotted up front, so a 10k-row
        sweep becomes many short maintenance holds instead of one long
        one that starves applies and client writes."""
        deleted = 0
        with self.storage._lock:
            work = [
                (actor, s, e)
                for actor, bv in self.bookie.actors().items()
                for s, e in bv.cleared.spans()
            ]
        for actor, s, e in work:
            for table in ("__corro_seq_bookkeeping",
                          "__corro_buffered_changes"):
                while True:
                    with self.storage._lock.prio(
                        PRIO_LOW, "buffered-meta"
                    ):
                        cur = self.storage.conn.execute(
                            f"DELETE FROM {table} WHERE rowid IN ("
                            f"SELECT rowid FROM {table} WHERE actor_id=? "
                            "AND version BETWEEN ? AND ? LIMIT ?)",
                            (actor, s, e, chunk),
                        )
                    deleted += cur.rowcount
                    if cur.rowcount < chunk:
                        break
        if deleted:
            self.metrics.counter(
                "corro_buffered_meta_cleared_total", deleted
            )
        return deleted

    async def _maintenance_loop(self) -> None:
        """WAL checkpoint + incremental vacuum + compaction leftovers +
        buffered-meta clearing (handlers.rs:394-534, util.rs:425-480).
        The SQL body runs on the apply pool: a WAL checkpoint of a busy
        database takes 100ms+, and running it on the event loop stalled
        SWIM acks every maintenance tick."""
        while True:
            await self._clock.sleep(self.config.maintenance_interval)
            try:
                await self._loop.run_in_executor(
                    self._apply_pool, self._maintenance_pass
                )
            except Exception:
                pass
            self.metrics.gauge(
                "corro_members_ring0", len(self.members.ring0())
            )

    def _maintenance_pass(self) -> None:
        """One blocking maintenance sweep (worker thread)."""
        try:
            # crash-leftover impacted versions from before a restart +
            # snapshot-floor advancement (the dedicated compaction loop
            # normally runs this faster; this is the backstop cadence)
            self._compaction_pass()
            self._clear_buffered_meta()
        except Exception:
            pass
        try:
            from corrosion_tpu.agent.locks import PRIO_LOW

            # maintenance yields the connection to applies and API
            # writes (LOW tier) and gets interrupted rather than
            # stalling them behind a long truncate/vacuum
            with self.storage._lock.prio(PRIO_LOW, "maintenance"), \
                    self.storage.interruptible(30.0):
                (wal_pages, _) = self.storage.conn.execute(
                    "PRAGMA wal_checkpoint(PASSIVE)"
                ).fetchone()[1:]
                if wal_pages is not None and \
                        wal_pages > self.config.wal_truncate_pages:
                    self.storage.conn.execute(
                        "PRAGMA wal_checkpoint(TRUNCATE)")
                    self.metrics.counter("corro_db_wal_truncations")
                (freelist,) = self.storage.conn.execute(
                    "PRAGMA freelist_count"
                ).fetchone()
                if freelist > self.config.vacuum_free_pages:
                    self.storage.conn.execute(
                        f"PRAGMA incremental_vacuum({freelist // 2})"
                    )
                    self.metrics.counter("corro_db_vacuums")
                # db/queue gauges moved to scrape time
                # (metric_gauges): one owner per series name, and
                # a scrape reads current values instead of stale
                # maintenance-tick snapshots
                if wal_pages is not None:
                    self.metrics.gauge(
                        "corro_db_wal_pages", wal_pages
                    )
        except Exception:
            pass

    async def _sync_loop(self) -> None:
        from corrosion_tpu.utils.backoff import Backoff

        delays = iter(
            Backoff(
                base=self.config.sync_interval_min,
                cap=self.config.sync_interval_max,
                rng=self._rng,
            )
        )
        while True:
            await self._clock.sleep(next(delays))
            try:
                await self.sync_round()
            except Exception:
                self.metrics.counter("corro_sync_round_errors_total")

    def _breaker_open(self, m: Member) -> bool:
        """Is the transport circuit breaker for this member's address
        open right now?  (Quarantine normally mirrors this, but the
        breaker can open between the transition callback and the next
        membership update — check both.)"""
        if self.transport is None:
            return False
        b = self.transport.breakers.get(tuple(m.addr))
        return b is not None and b.is_open

    def _choose_sync_peers(self, ours: SyncStateV1) -> List[Member]:
        """Peer choice heuristic (handlers.rs:963-1074): sample 2x the
        desired count uniformly, then keep the best by (most needed
        from them, longest since last sync, lowest RTT).

        Quarantined / breaker-open members are excluded outright — a
        dead-but-undetected peer chosen here would absorb a whole sync
        round (the partial-retry path already filters them; this keeps
        the first pass from wasting its round the same way)."""
        peers = [
            m for m in self.members.alive()
            if m.state is MemberState.ALIVE and not m.quarantined
            and not self._breaker_open(m)
        ]
        if not peers:
            return []
        desired = max(min(len(peers) // 100, 10), min(3, len(peers)))
        desired = min(desired, self.config.sync_peers)
        cands = self._rng.sample(peers, min(desired * 2, len(peers)))
        cands.sort(
            key=lambda m: (
                -ours.need_len_for_actor(ActorId(m.actor_id)),
                m.last_sync_ts,
                m.rtt_ms if m.rtt_ms is not None else float("inf"),
            )
        )
        return cands[:desired]

    async def sync_round(self) -> int:
        """One full client round: choose peers, parallel_sync them."""
        ours = self.generate_sync()
        chosen = self._choose_sync_peers(ours)
        if not chosen:
            return 0
        return await self.parallel_sync(chosen, ours)

    async def parallel_sync(
        self, members: Sequence[Member], ours: Optional[SyncStateV1] = None,
        _retry: bool = True,
    ) -> int:
        """Sync with several peers at once, deduping needs across them
        (peer.rs:1039-1466): handshake everyone, then allocate each need
        to exactly one server — two peers serving disjoint halves of a
        node's gaps is the healthy case, not a coincidence.

        Degraded-mode hardening: a peer failing MID-STREAM is a
        retryable partial round, not an aborted one — everything it
        served before dying is already ingested, and the remaining needs
        are recomputed from bookkeeping and retried once against peers
        not used this round (``_retry=False`` bounds the recursion)."""
        if ours is None:
            ours = self.generate_sync()
        # the whole client round is one trace; each handshake's
        # BiPayload carries its traceparent so the servers' spans share
        # the trace id (sync.rs:32-67 propagation)
        # timed() records on every exit path — including handshake-
        # timeout rounds, which are exactly the slow ones
        with self.metrics.timed("corro_sync_client_round_seconds"), \
                tracing.span("sync.client_round", peers=len(members)) as sp:
            self.metrics.counter("corro_trace_spans_total")
            attempts = await asyncio.gather(
                *(self._sync_handshake(m) for m in members),
                return_exceptions=True,
            )
            sessions = [s for s in attempts if isinstance(s, dict)]
            self.metrics.counter(
                "corro_sync_handshakes_total", len(attempts))
            failed = len(attempts) - len(sessions)
            if failed:
                self.metrics.counter(
                    "corro_sync_handshake_failures_total", failed)
            if not sessions:
                self.metrics.counter("corro_sync_empty_rounds_total")
                return 0
            snap_sess = None
            try:
                # snapshot-or-changes dispatch (docs/sync.md): a server
                # whose advertised floors cover needs it can no longer
                # serve change-by-change gets a snap_request instead of
                # need allocation (its needs are satisfied wholesale by
                # the install + tail round)
                snap_sess, sessions = self._pick_snapshot_session(
                    sessions, ours
                )
                self._allocate_needs(sessions, ours)
                kind_counts: Dict[str, int] = {}
                for sess in sessions:
                    for _actor, needs in sess["needs"].items():
                        for nd in needs:
                            k = nd.kind if nd.kind in (
                                "full", "partial", "empty"
                            ) else "other"
                            kind_counts[k] = kind_counts.get(k, 0) + 1
                if snap_sess is not None:
                    kind_counts["snapshot"] = 1
                for k, c in kind_counts.items():
                    self.metrics.counter(
                        "corro_sync_needs_requested_total", c, kind=k
                    )
            except BaseException:
                # one malformed peer state must not leak the other sessions
                for s in sessions:
                    s["writer"].close()
                if snap_sess is not None:
                    snap_sess["writer"].close()
                raise
            session_tasks = [self._sync_session(s) for s in sessions]
            if snap_sess is not None:
                session_tasks.append(
                    self._snapshot_client_session(snap_sess)
                )
                sessions = sessions + [snap_sess]
            results = await asyncio.gather(
                *session_tasks,
                return_exceptions=True,
            )
            total = 0
            partial = 0
            for r in results:
                if isinstance(r, tuple):
                    count, complete = r
                    total += count
                    if not complete:
                        partial += 1
                else:
                    partial += 1
            sp.set(sessions=len(sessions), changes=total)
            if partial:
                self.metrics.counter(
                    "corro_sync_partial_sessions_total", partial)
            if partial and _retry:
                # retryable partial round: needs the dead peer(s) never
                # served are still in bookkeeping — recompute and push
                # them to peers untouched this round (bounded: one pass)
                used = {tuple(m.addr) for m in members}
                spare = [
                    m for m in self.members.alive()
                    if m.state is MemberState.ALIVE
                    and tuple(m.addr) not in used
                    and not m.quarantined
                ]
                if spare:
                    self.metrics.counter(
                        "corro_sync_partial_retries_total")
                    retry_peers = self._rng.sample(
                        spare, min(partial, len(spare))
                    )
                    total += await self.parallel_sync(
                        retry_peers, None, _retry=False
                    )
            return total

    def _allocate_needs(
        self, sessions: List[dict], ours: SyncStateV1
    ) -> None:
        # cross-peer dedup with round-robin allocation: servers take
        # turns draining ≤10 needs from their own advertised queue while
        # a shared requested-set skips what another server already got —
        # so N servers holding the same data end up serving disjoint
        # slices of it (peer.rs:1240-1371)
        from collections import deque

        req_full: set = set()  # (actor_bytes, version)
        req_partial: Dict[tuple, RangeSet] = {}  # (actor, version) -> seqs
        queues: List = []
        for s in sessions:
            theirs = s["theirs"]
            needs = ours.compute_available_needs(theirs)
            if theirs.last_cleared_ts is not None:
                known = self.bookie.for_actor(
                    theirs.actor_id.bytes
                ).last_cleared_ts
                if known is None or int(known) < int(theirs.last_cleared_ts):
                    needs.setdefault(theirs.actor_id, []).append(
                        SyncNeedV1.empty(known)
                    )
            q = deque()
            # per-session need cap (Byzantine serve-path hardening,
            # docs/faults.md): the 10-version chunking loop over a
            # hostile server's lying head would otherwise allocate an
            # unbounded queue BEFORE a single request goes out.
            # Bounded work per session; what got cut is still in
            # bookkeeping for future rounds against honest peers
            capped = False
            cap = self.SYNC_CLIENT_NEED_CAP
            for actor, actor_needs in needs.items():
                for n in actor_needs:
                    if n.kind == "full":
                        lo, hi = n.versions
                        while lo <= hi:  # 10-version chunks (peer.rs:1285)
                            if len(q) >= cap:
                                capped = True
                                break
                            q.append(
                                (actor, SyncNeedV1.full(lo, min(lo + 9, hi)))
                            )
                            lo += 10
                    elif len(q) >= cap:
                        capped = True
                    else:
                        q.append((actor, n))
                    if capped:
                        break
                if capped:
                    break
            if capped:
                self._sync_client_reject("need_cap")
            queues.append(q)
            s["needs"] = {}
        while any(queues):
            for s, q in zip(sessions, queues):
                taken = 0
                while q and taken < 10:
                    actor, n = q.popleft()
                    ab = actor.bytes
                    out: List[SyncNeedV1] = []
                    if n.kind == "full":
                        span = RangeSet()
                        for v in range(n.versions[0], n.versions[1] + 1):
                            if (ab, v) not in req_full:
                                req_full.add((ab, v))
                                span.insert(v, v)
                        out.extend(
                            SyncNeedV1.full(a, b) for a, b in span.spans()
                        )
                    elif n.kind == "partial":
                        key = (ab, int(n.version))
                        got = req_partial.setdefault(key, RangeSet())
                        fresh = []
                        for s0, e0 in n.seqs:
                            for a, b in got.gaps(s0, e0):
                                fresh.append((a, b))
                                got.insert(a, b)
                        if fresh:
                            out.append(SyncNeedV1.partial(n.version, fresh))
                    else:
                        out.append(n)  # empty-need is per-server
                    if out:
                        s["needs"].setdefault(actor, []).extend(out)
                        taken += 1

    # -- Byzantine sync-serve client defenses (docs/faults.md) ---------

    def _screen_sync_state(self, theirs: SyncStateV1) -> Optional[str]:
        """Structural sanity screen on a sync SERVER's advertised
        state — the serve-path mirror of ``_screen_changeset``.
        Returns the reject reason or None.  A lying head past
        ``SYNC_MAX_ADVERTISED_HEAD`` (no real history allocates a
        version per nanosecond for millennia) or inverted need/seq
        spans (the wire decoder rejects these; the in-process virtual
        path hands the object straight over, so the screen must check
        too) mark a hostile server whose serves cannot be trusted."""
        for head in theirs.heads.values():
            if int(head) >= self.SYNC_MAX_ADVERTISED_HEAD:
                return "advertised_range"
        for spans in theirs.need.values():
            for s, e in spans:
                if s < 0 or e < s:
                    return "advertised_range"
        for partials in theirs.partial_need.values():
            for seq_spans in partials.values():
                for s, e in seq_spans:
                    if s < 0 or e < s:
                        return "advertised_range"
        return None

    def _sync_client_reject(self, reason: str, addr=None,
                            trip: bool = False,
                            strike: bool = False) -> None:
        """Count one client-side serve-path rejection
        (``corro_sync_client_rejects_total{reason=}``); ``trip``
        opens the peer's breaker — verified-garbage serves are
        hostile, not flaky — while ``strike`` records one ordinary
        breaker failure (ambiguous evidence like a session deadline:
        `threshold` of them before quarantine)."""
        self.metrics.counter(
            "corro_sync_client_rejects_total", reason=reason
        )
        if addr is not None:
            if trip:
                self._trip_breaker(tuple(addr))
            elif strike:
                self._strike_breaker(tuple(addr))

    async def _sync_handshake(self, m: Member) -> Optional[dict]:
        """Open a bi-stream, send SyncStart + Clock, read the server's
        State (+Clock); returns a session dict or None on reject."""
        try:
            # through the transport so connects share the timeout and feed
            # RTT samples into the member rings (ring0 classification)
            reader, writer = await self.transport.open_bi(tuple(m.addr))
        except (OSError, asyncio.TimeoutError):
            return None
        try:
            tp = tracing.current_traceparent()
            writer.write(
                speedy.frame(
                    speedy.encode_bi_payload(
                        BiPayload(
                            actor_id=ActorId(self.actor_id),
                            trace_ctx={"traceparent": tp} if tp else None,
                        ),
                        ClusterId(self.config.cluster_id),
                    )
                )
            )
            writer.write(
                speedy.frame(
                    speedy.encode_sync_message(self.clock.new_timestamp())
                )
            )
            await writer.drain()
            frames = speedy.FrameReader()
            backlog: List = []
            while True:
                data = await asyncio.wait_for(reader.read(65536), timeout=10.0)
                if not data:
                    writer.close()
                    return None
                batch = frames.feed(data)
                for i, payload in enumerate(batch):
                    msg = speedy.decode_sync_message(payload)
                    if isinstance(msg, tuple) and msg[0] == "rejection":
                        self.metrics.counter("corro_sync_rejected_total")
                        writer.close()
                        return None
                    if isinstance(msg, Timestamp):
                        try:
                            self.clock.update_with_timestamp(msg)
                        except Exception:
                            pass
                    elif isinstance(msg, SyncStateV1):
                        reason = self._screen_sync_state(msg)
                        if reason is not None:
                            # a structurally-lying advertised state is
                            # hostile: refuse the whole session before
                            # a single need is computed from it
                            self._sync_client_reject(
                                reason, tuple(m.addr), trip=True
                            )
                            writer.close()
                            return None
                        # frames decoded after State in the same read
                        # (routinely the server's Clock) carry over to
                        # the session instead of being dropped
                        backlog.extend(
                            speedy.decode_sync_message(p)
                            for p in batch[i + 1 :]
                        )
                        return {
                            "member": m,
                            "reader": reader,
                            "writer": writer,
                            "frames": frames,
                            "theirs": msg,
                            "backlog": backlog,
                        }
                    else:
                        backlog.append(msg)
        except (asyncio.TimeoutError, OSError, ConnectionError,
                speedy.SpeedyError):
            writer.close()
            return None

    @staticmethod
    def _request_batches(
        needs: Dict[ActorId, List[SyncNeedV1]],
        per_request: int = 10,
        full_chunk: int = 10,
    ):
        """Split a needs map into Request frames the way the reference's
        client drains them (peer.rs:1240-1371): Full ranges chunked into
        ≤``full_chunk``-version sub-ranges, ≤``per_request`` needs per
        Request message."""
        flat: List[Tuple[ActorId, SyncNeedV1]] = []
        for actor, actor_needs in needs.items():
            for n in actor_needs:
                if n.kind == "full":
                    s, e = n.versions
                    while s <= e:
                        hi = min(s + full_chunk - 1, e)
                        flat.append((actor, SyncNeedV1.full(s, hi)))
                        s = hi + 1
                else:
                    flat.append((actor, n))
        for i in range(0, len(flat), per_request):
            batch = flat[i : i + per_request]
            grouped: List[Tuple[ActorId, List[SyncNeedV1]]] = []
            for actor, n in batch:
                if grouped and grouped[-1][0] == actor:
                    grouped[-1][1].append(n)
                else:
                    grouped.append((actor, [n]))
            yield grouped

    async def _ingest_sync_change(self, cv: ChangeV1) -> None:
        if cv.changeset.is_empty_set:
            # EmptySet groups advance the cleared watermark per group,
            # so they must apply in served order and must never be
            # dropped — bypass the drop-oldest ingest queue (the
            # reference likewise gives emptysets their own ordered
            # channel, handlers.rs:539-734)
            # route through _apply_batch so the in-flight gauge and
            # batch-size histogram see sync emptyset work too
            await self._loop.run_in_executor(
                self._apply_pool, self._apply_batch,
                [(cv, ChangeSource.SYNC)],
            )
        else:
            self.enqueue_change(cv, ChangeSource.SYNC)

    # -- per-session sync observability --------------------------------
    #
    # Round-level timers and session-count gauges existed before; these
    # add the per-SESSION layer: a live-session registry behind admin
    # `sync_sessions` (peer, age, needs-remaining), one
    # corro_sync_session_seconds{role=} sample per session, and the
    # session's byte volume counted by role/direction.

    def _sync_session_begin(self, role: str, peer: str,
                            needs_total: int) -> dict:
        self._sync_sess_seq += 1
        live = {
            "id": self._sync_sess_seq, "role": role, "peer": peer,
            "started": self._clock.monotonic(),
            "needs_total": needs_total,
            "needs_done": 0, "changes": 0, "bytes": 0,
        }
        self._sync_live[live["id"]] = live
        return live

    def _sync_session_end(self, live: dict, role: str,
                          direction: str) -> None:
        self._sync_live.pop(live["id"], None)
        self.metrics.histogram(
            "corro_sync_session_seconds",
            self._clock.monotonic() - live["started"], role=role,
        )
        if live["bytes"]:
            self.metrics.counter(
                "corro_sync_session_bytes_total", live["bytes"],
                role=role, dir=direction,
            )

    def sync_sessions(self) -> List[dict]:
        """Live sync sessions, both roles (admin ``sync_sessions``).

        Per-need completion is a SERVER-side notion (the server runs
        one job per need; the client just reads the stream until the
        server half-closes), so ``needs_done``/``needs_remaining`` are
        null for client sessions — their progress signal is
        ``changes`` (changesets ingested so far), which must keep
        moving for a healthy backfill."""
        now = self._clock.monotonic()
        out = []
        for e in list(self._sync_live.values()):
            client = e["role"] == "client"
            out.append({
                "id": e["id"], "role": e["role"], "peer": e["peer"],
                "age_s": round(now - e["started"], 3),
                "needs_total": e["needs_total"],
                "needs_done": None if client else e["needs_done"],
                "needs_remaining": None if client else max(
                    0, e["needs_total"] - e["needs_done"]
                ),
                "changes": e["changes"],
                "bytes": e["bytes"],
            })
        return out

    async def _sync_session(self, s: dict) -> Tuple[int, bool]:
        """Send this session's allocated requests, then ingest served
        changesets until the server closes its side.

        Returns ``(changes_ingested, complete)``: a mid-stream peer
        failure keeps everything already ingested (bookkeeping is
        idempotent and incremental) and reports ``complete=False`` so
        the round can retry the remainder elsewhere — a partial round,
        not an aborted one."""
        m, reader, writer = s["member"], s["reader"], s["writer"]
        frames = s["frames"]
        count = 0
        needs_total = sum(len(v) for v in s["needs"].values())
        live = self._sync_session_begin(
            "client", m.actor_id.hex(), needs_total
        )
        self._flight_event(
            "sync_client_start", peer=m.actor_id.hex(), needs=needs_total
        )
        complete = False
        try:
            for msg in s["backlog"]:
                if isinstance(msg, ChangeV1):
                    await self._ingest_sync_change(msg)
                    count += 1
                    live["changes"] = count
                elif isinstance(msg, Timestamp):
                    try:
                        self.clock.update_with_timestamp(msg)
                    except Exception:
                        pass
            for batch in self._request_batches(s["needs"]):
                writer.write(
                    speedy.frame(speedy.encode_sync_message(("request", batch)))
                )
            await writer.drain()
            # half-close: no more requests; the server serves then
            # closes (EOF-terminated like the reference)
            if writer.can_write_eof():
                writer.write_eof()
            # Byzantine serve-path hardening (docs/faults.md): a
            # whole-session deadline on the injected clock (each read
            # has a 10 s timeout, so a slow-trickle server feeding one
            # byte per window would otherwise hold the session — and
            # its allocated needs — hostage forever), plus a budget of
            # undecodable frames before the serve is judged hostile
            # and the peer's breaker trips
            deadline = None
            if self.config.sync_session_deadline_s > 0:
                deadline = (self._clock.monotonic()
                            + self.config.sync_session_deadline_s)
            frame_errs = 0
            aborted = False
            while True:
                read_timeout = 10.0
                if deadline is not None:
                    remaining = deadline - self._clock.monotonic()
                    if remaining <= 0:
                        # one STRIKE, not a hard trip: a blown session
                        # deadline could be honest slowness, but enough
                        # of them must stop the peer being re-selected
                        # every round (the slow-trickle containment the
                        # vcluster campaign seam already models)
                        self._sync_client_reject(
                            "deadline", tuple(m.addr), strike=True
                        )
                        aborted = True
                        break
                    read_timeout = min(read_timeout, remaining)
                data = await asyncio.wait_for(
                    reader.read(65536), timeout=read_timeout
                )
                if not data:
                    break  # server closed: session complete
                live["bytes"] += len(data)
                try:
                    payloads = frames.feed(data)
                except speedy.SpeedyError:
                    # oversized/corrupt framing: unrecoverable stream
                    self._sync_client_reject(
                        "frame_garbage", tuple(m.addr), trip=True
                    )
                    aborted = True
                    break
                for payload in payloads:
                    try:
                        msg = speedy.decode_sync_message(payload)
                    except speedy.SpeedyError:
                        frame_errs += 1
                        self._sync_client_reject("frame_garbage")
                        if frame_errs > self.SYNC_CLIENT_FRAME_BUDGET:
                            self._trip_breaker(tuple(m.addr))
                            aborted = True
                            break
                        continue
                    if isinstance(msg, Timestamp):
                        try:
                            self.clock.update_with_timestamp(msg)
                        except Exception:
                            pass
                    elif isinstance(msg, ChangeV1):
                        await self._ingest_sync_change(msg)
                        count += 1
                        live["changes"] = count
                if aborted:
                    break
            if aborted:
                return count, False
            self.members.update_sync_ts(m.actor_id, self._clock.wall())
            self.metrics.counter("corro_sync_client_rounds_total")
            complete = True
            # per-change accounting happens at enqueue_change
            return count, True
        except (asyncio.TimeoutError, OSError, ConnectionError,
                speedy.SpeedyError):
            return count, False
        finally:
            writer.close()
            self._sync_session_end(live, "client", "received")
            self._flight_event(
                "sync_client_end", peer=m.actor_id.hex(),
                changes=count, bytes=live["bytes"], complete=complete,
            )

    async def _serve_tcp(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        """Dispatch an inbound TCP connection by its one-byte stream
        prelude (the TCP analogue of QUIC accept_uni/accept_bi); all
        bytes after it are LengthDelimited speedy frames — the
        reference's exact stream content."""
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        try:
            try:
                prelude = await asyncio.wait_for(
                    reader.readexactly(1), timeout=10.0
                )
            except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                    OSError, ConnectionError):
                writer.close()
                return
            if prelude == STREAM_UNI:
                await self._serve_uni(reader, writer)
            elif prelude == STREAM_BI:
                await self._serve_sync(reader, writer)
            elif prelude == STREAM_MUX:
                from corrosion_tpu.agent.mux import serve_mux

                await serve_mux(self, reader, writer)
            else:
                writer.close()
        except asyncio.CancelledError:
            writer.close()
            raise
        finally:
            self._conn_tasks.discard(task)

    # UniPayload::V1 / Broadcast / Change variant tags: three zero u32s.
    # decode_uni_payload accepts nothing else, so frames failing this
    # cheap prelude check can be rejected before consuming a bounded
    # ingest-queue slot (a junk burst must not evict real changesets).
    _UNI_PRELUDE = b"\x00" * 12

    def enqueue_uni_payload(self, payload: bytes, peer=None) -> None:
        """Queue one RAW uni-stream payload for off-loop decoding: the
        event loop only deframes (+ a 12-byte tag sanity check); speedy
        decode happens in the apply worker pool (``_apply_batch``), so a
        burst of inbound gossip never blocks the loop on
        deserialization.  Same bounded drop-oldest policy as
        ``enqueue_change``.  The traced/signed envelope, if present, is
        walked (fixed-offset arithmetic only — no string or change
        decode) so the prelude screen applies to every wire format.
        ``peer`` is the delivering transport's address, carried through
        to the worker so a failed origin signature can blame the
        delivery (docs/faults.md, signed attribution)."""
        off = 1 if self.config.debug_hops else 0
        try:
            start = speedy.traced_uni_payload_start(payload, off)
        except speedy.SpeedyError:
            self.metrics.counter("corro_wire_decode_errors_total")
            return
        if payload[start : start + 12] != self._UNI_PRELUDE:
            self.metrics.counter("corro_wire_decode_errors_total")
            return
        self._enqueue_ingest((payload, peer), None)

    def _ingest_uni_payloads(self, payloads, peer=None) -> None:
        """Deframed uni payloads → ingest queue (shared by the
        dedicated uni stream server and the mux demux)."""
        for payload in payloads:
            self.enqueue_uni_payload(payload, peer)

    async def _serve_uni(self, reader, writer) -> None:
        """Long-lived inbound broadcast stream: speedy UniPayload frames
        (broadcast.rs:37-55) → ingest queue."""
        frames = speedy.FrameReader()
        ingest = self._ingest_uni_payloads
        peer = writer.get_extra_info("peername")
        if peer is not None:
            peer = tuple(peer[:2])

        try:
            while True:
                data = await reader.read(65536)
                if not data:
                    return
                ingest(frames.feed(data), peer)
        except (OSError, ConnectionError, speedy.SpeedyError):
            return
        finally:
            writer.close()

    async def _send_sync_msg(self, writer, msg) -> None:
        writer.write(speedy.frame(speedy.encode_sync_message(msg)))
        await writer.drain()

    # sync serving knobs (peer.rs:344-348)
    SYNC_CHUNK_MAX = 8 * 1024
    SYNC_CHUNK_MIN = 1024
    SYNC_ADAPT_THRESHOLD = 0.5  # halve the chunk beyond this send time
    SYNC_SLOW_ABORT = 5.0  # abort the session beyond this send time
    SYNC_NEED_JOBS = 6  # concurrent need jobs per session (peer.rs:843)
    SYNC_MAX_PARTIAL_SPANS = 1024  # clamp hostile partial seqs lists
    SYNC_MAX_SESSION_NEEDS = 10_000  # total needs one session may request
    # -- Byzantine sync-SERVE client hardening (docs/faults.md) --------
    # the server-side caps above bound what a hostile CLIENT can cost a
    # server; these bound what a hostile SERVER can cost a client:
    # a head no real history could reach (one version per committed
    # local transaction — a claim past 2^48 is a structural lie, and
    # naively chunking it into 10-version requests would allocate
    # ~10^13 needs)
    SYNC_MAX_ADVERTISED_HEAD = 1 << 48
    # max needs the client allocates toward ONE server session
    SYNC_CLIENT_NEED_CAP = 10_000
    # undecodable frames tolerated per session before the serve is
    # definitively garbage and the peer's breaker trips
    SYNC_CLIENT_FRAME_BUDGET = 3
    # batched serve pipeline (docs/sync.md): versions resolved/collected
    # per storage-lock window, and the byte budget one coalesced write
    # accumulates before draining when the session carries no adaptive
    # chunk budget (live sessions drain per sess["chunk"] so slow-reader
    # adaptation keeps seeing real backpressure)
    SYNC_RESOLVE_CHUNK = 256
    SYNC_DRAIN_BUDGET = 64 * 1024

    async def _serve_sync(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        """Sync server (serve_sync, peer.rs:1469): read the SyncStart
        BiPayload, reject if over capacity or cross-cluster, send our
        State + Clock, then serve Request needs until the client
        half-closes; closing our side ends the session."""
        if self._sync_sem.locked():
            # rejections were silent: count them by reason so capacity
            # pressure is visible next to the accept/serve series
            self.metrics.counter(
                "corro_sync_rejections_sent_total", reason="capacity")
            await self._send_sync_msg(
                writer, ("rejection", speedy.REJECTION_MAX_CONCURRENCY)
            )
            writer.close()
            return
        async with self._sync_sem:
            self._sync_server_sessions += 1
            jobs: set = set()
            job_sem = asyncio.Semaphore(self.SYNC_NEED_JOBS)
            sess = {"chunk": self.SYNC_CHUNK_MAX}
            total_needs = 0
            srv_span = None  # opened once the SyncStart is decoded
            live = None  # session registry entry, once the peer is known

            async def run_need(actor_b: bytes, need: SyncNeedV1) -> None:
                async with job_sem:
                    await self._serve_need(writer, actor_b, need, sess)
                    if live is not None:
                        live["needs_done"] += 1

            async def run_snapshot() -> None:
                async with job_sem:
                    await self._serve_snapshot(writer, sess)
                    if live is not None:
                        live["needs_done"] += 1

            try:
                frames = speedy.FrameReader()
                payloads: List[bytes] = []
                while not payloads:
                    data = await asyncio.wait_for(
                        reader.read(65536), timeout=10.0
                    )
                    if not data:
                        return
                    payloads = frames.feed(data)
                _bi, cluster = speedy.decode_bi_payload(payloads[0])
                peer_hex = _bi.actor_id.bytes.hex()
                live = self._sync_session_begin("server", peer_hex, 0)
                sess["live"] = live
                self._flight_event("sync_server_start", peer=peer_hex)
                # re-parent on the client's traceparent so both ends of
                # the round log the same trace id (sync.rs:32-67)
                srv_span = tracing.span(
                    "sync.server",
                    remote=(_bi.trace_ctx or {}).get("traceparent"),
                )
                srv_span.__enter__()
                self.metrics.counter("corro_trace_spans_total")
                if int(cluster) != self.config.cluster_id:
                    self.metrics.counter(
                        "corro_sync_rejections_sent_total",
                        reason="cluster",
                    )
                    await self._send_sync_msg(
                        writer,
                        ("rejection", speedy.REJECTION_DIFFERENT_CLUSTER),
                    )
                    return
                await self._send_sync_msg(writer, self.generate_sync())
                await self._send_sync_msg(writer, self.clock.new_timestamp())
                queued = payloads[1:]
                eof = False
                while not eof:
                    if queued:
                        msgs, queued = queued, []
                    else:
                        try:
                            data = await asyncio.wait_for(
                                reader.read(65536), timeout=10.0
                            )
                        except asyncio.TimeoutError:
                            # a stalled client that never half-closes
                            # still gets its jobs reaped below (their
                            # drains hit the slow-peer abort budget)
                            break
                        if not data:
                            eof = True  # client half-closed: no more needs
                            msgs = []
                        else:
                            msgs = frames.feed(data)
                    for payload in msgs:
                        msg = speedy.decode_sync_message(payload)
                        if isinstance(msg, Timestamp):
                            try:
                                self.clock.update_with_timestamp(msg)
                            except Exception:
                                pass
                        elif isinstance(msg, tuple) \
                                and msg[0] == "snap_request":
                            # snapshot serve (docs/sync.md): one job
                            # through the same semaphore/abort budgets
                            # as changeset needs.  With serving off the
                            # request is ignored — the client times out
                            # of the session and falls back
                            if not self.config.snapshot_serve:
                                continue
                            total_needs += 1
                            t = asyncio.ensure_future(
                                run_snapshot()
                            )
                            jobs.add(t)
                            if live is not None:
                                live["needs_total"] = total_needs
                        elif isinstance(msg, tuple) and msg[0] == "request":
                            # needs run as concurrent jobs, up to
                            # SYNC_NEED_JOBS at once (peer.rs:836-844);
                            # frame writes are atomic per message, so
                            # interleaved jobs cannot corrupt the stream
                            for actor, needs in msg[1]:
                                for need in needs:
                                    total_needs += 1
                                    if (total_needs
                                            > self.SYNC_MAX_SESSION_NEEDS):
                                        # hostile request stream: stop
                                        # accepting, serve what's queued
                                        eof = True
                                        break
                                    t = asyncio.ensure_future(
                                        run_need(actor.bytes, need)
                                    )
                                    jobs.add(t)
                                if eof:
                                    break
                            if live is not None:
                                live["needs_total"] = total_needs
                # requests done (EOF or stall): wait for serving to end
                if jobs:
                    results = await asyncio.gather(
                        *jobs, return_exceptions=True
                    )
                    jobs.clear()
                    errors = [r for r in results if isinstance(r, Exception)]
                    if errors:
                        if any(isinstance(r, _SlowPeer) for r in errors):
                            self.metrics.counter(
                                "corro_sync_slow_peer_aborts_total"
                            )
                        else:
                            self.metrics.counter(
                                "corro_sync_serve_errors_total"
                            )
                        # a failed serve must NOT end as a clean EOF the
                        # client mistakes for a complete session — and
                        # close() would wait on a reader that may not be
                        # reading; reset the stream instead
                        writer.transport.abort()
            except (asyncio.TimeoutError, OSError, ConnectionError,
                    speedy.SpeedyError) as e:
                # swallowed for the protocol, but the span must not
                # read as a clean session
                if srv_span is not None:
                    srv_span.span.set(error=repr(e))
                return
            finally:
                self._sync_server_sessions -= 1
                if srv_span is not None:
                    srv_span.span.set(needs=total_needs)
                    srv_span.__exit__(None, None, None)
                if live is not None:
                    self._sync_session_end(live, "server", "served")
                    self._flight_event(
                        "sync_server_end", peer=live["peer"],
                        needs=total_needs, bytes=live["bytes"],
                    )
                for t in jobs:
                    t.cancel()
                writer.close()

    async def _serve_need(self, writer: asyncio.StreamWriter, actor: bytes,
                          need: SyncNeedV1,
                          sess: Optional[dict] = None) -> None:
        bv = self.bookie.for_actor(actor)
        kind = need.kind
        self.metrics.counter(
            "corro_sync_needs_served_total",
            kind=kind if kind in ("full", "partial", "empty") else "other",
        )
        if kind == "full":
            s, e = need.versions
            # clamp hostile/stale ranges to what we can possibly serve
            s, e = max(1, int(s)), min(int(e), bv.last())
            if self.config.sync_batched_serve:
                await self._serve_full_range_batched(
                    writer, actor, bv, s, e, sess
                )
                return
            # per-version parity oracle: newest first (peer.rs serve
            # order) — under a chunk budget or a slow-peer abort the
            # requester keeps the freshest data.  A version served as a
            # cleared span jumps the cursor BELOW the whole span — no
            # per-version spin over large ranges
            v, i = e, 0
            while v >= s:
                span = await self._serve_version(
                    writer, actor, bv, v, sess=sess
                )
                v = (span[0] - 1) if span is not None else (v - 1)
                i += 1
                if i % 64 == 63:
                    await asyncio.sleep(0)  # don't starve the event loop
        elif kind == "partial":
            v = int(need.version)
            await self._serve_version(
                writer, actor, bv, v,
                # span-count clamp: a hostile seqs list cannot force an
                # unbounded number of per-span re-scans
                seq_spans=[
                    tuple(sp)
                    for sp in need.seqs[: self.SYNC_MAX_PARTIAL_SPANS]
                ],
                sess=sess,
            )
        elif kind == "empty":
            # only cleared ranges strictly NEWER than the requester's
            # last-seen ts, one EmptySet per distinct stamping ts oldest
            # first (peer.rs:715-762): each message is a complete per-ts
            # group, so the requester can advance its watermark per
            # message without ever missing a sibling range
            if bv.last_cleared_ts is None:
                return
            since = int(need.ts) if need.ts is not None else None
            for group_ts, spans in self.bookie.cleared_since(actor, since):
                cs = Changeset.empty_set(spans, Timestamp(group_ts))
                await self._send_sync_change(writer, actor, cs)

    async def _serve_version(
        self, writer, actor: bytes, bv, v: int,
        seq_spans: Optional[List[Tuple[int, int]]] = None,
        sess: Optional[dict] = None,
    ) -> Optional[Tuple[int, int]]:
        """Serve one version; returns the enclosing (lo, hi) span when
        it went out as a cleared/empty changeset (so a full-range serve
        can skip the rest of the span), else None."""
        if bv.cleared.contains(v):
            lo, hi = v, v
            for s, e in bv.cleared:
                if s <= v <= e:
                    lo, hi = s, e
                    break
            cs = Changeset.empty((Version(lo), Version(hi)), bv.last_cleared_ts)
            await self._send_sync_change(writer, actor, cs, sess)
            return (lo, hi)
        entry = bv.versions.get(v)
        if entry is None:
            # we may still hold part of it: serve the buffered seqs we have
            # (two partial peers with complementary chunks can complete each
            # other even after the origin dies)
            partial = bv.partials.get(v)
            if partial is None:
                return
            have = partial.seqs.spans()
            if seq_spans is not None:
                have = [
                    clipped
                    for s, e in seq_spans
                    for clipped in partial.seqs.intersection_spans(s, e)
                ]
            buffered = {
                seq: wire.decode_buffered_change(blob)
                for seq, blob in self.bookie.buffered_changes(actor, v)
            }
            for s, e in have:
                chunk = [buffered[q] for q in range(s, e + 1) if q in buffered]
                cs = Changeset.full(
                    Version(v), chunk, (s, e), partial.last_seq,
                    partial.ts or Timestamp(0),
                )
                await self._send_sync_change(writer, actor, cs, sess)
            return
        db_version, last_seq = entry
        site = None if actor == self.actor_id else actor
        changes = self.storage.collect_changes((db_version, db_version), site)
        # Full changesets carry a non-optional ts on the wire
        # (broadcast.rs:118): re-serve with the ts recorded at apply time
        row_ts = self.bookie.version_ts(actor, v)
        ts = Timestamp(row_ts) if row_ts is not None else Timestamp(0)
        if not changes:
            # the version HAD rows (versions are only allocated for
            # non-empty transactions); all gone means newer versions
            # overwrote them — read-time cleared detection: serve an
            # EmptySet so the requester records a cleared range, not a
            # hollow full version (peer.rs:350-762 behavior, pinned by
            # its test_handle_need)
            cs = Changeset.empty((Version(v), Version(v)), ts)
            await self._send_sync_change(writer, actor, cs, sess)
            return (v, v)
        if seq_spans is not None:
            changes = [
                c
                for c in changes
                if any(s <= int(c.seq) <= e for s, e in seq_spans)
            ]
            for s, e in seq_spans:
                span_changes = [c for c in changes if s <= int(c.seq) <= e]
                cs = Changeset.full(
                    Version(v), span_changes, (s, e), last_seq,
                    (bv.partials[v].ts or ts) if v in bv.partials else ts,
                )
                await self._send_sync_change(writer, actor, cs, sess)
            return
        chunker = ChunkedChanges(
            changes, 0, last_seq,
            max_buf_size=sess["chunk"] if sess else MAX_CHANGES_BYTE_SIZE,
        )
        for chunk, seqs in chunker:
            cs = Changeset.full(Version(v), chunk, seqs, last_seq, ts)
            await self._send_sync_change(writer, actor, cs, sess)

    # -- batched serve pipeline (docs/sync.md) -------------------------
    #
    # The serve mirror of the batched apply pipeline: a full-range need
    # is resolved version->db_version in ONE in-memory bookkeeping pass
    # per SYNC_RESOLVE_CHUNK versions (a short storage-lock hold), the
    # whole span is collected with one sentinel + one cell query per
    # table on a read-only pool connection OFF the event loop, split by
    # db_version in memory, encoded to frames in the worker, and sent
    # as coalesced buffered writes with one drain per SYNC_DRAIN_BUDGET
    # bytes.  collect(chunk N+1) overlaps encode/send(chunk N).  Served
    # bytes are pinned identical to the per-version oracle
    # (_serve_version) by tests/test_serve_batched.py.

    def _serve_executor(self):
        pool = self._serve_pool
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            pool = self._serve_pool = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="corro-serve",
            )
        return pool

    async def _serve_full_range_batched(
        self, writer, actor: bytes, bv, s: int, e: int,
        sess: Optional[dict] = None,
    ) -> None:
        """Serve a full version range [s, e] newest-first through the
        batched pipeline; bytes identical to the per-version oracle."""
        if e < s:
            return
        loop = asyncio.get_running_loop()
        pool = self._serve_executor()
        fut = loop.run_in_executor(
            pool, self._collect_serve_chunk, actor, bv, s, e,
            sess["chunk"] if sess else MAX_CHANGES_BYTE_SIZE,
        )
        try:
            while fut is not None:
                frames, cursor = await fut
                if cursor >= s:
                    # prefetch: collect the next chunk while this sends
                    fut = loop.run_in_executor(
                        pool, self._collect_serve_chunk, actor, bv, s,
                        cursor,
                        sess["chunk"] if sess else MAX_CHANGES_BYTE_SIZE,
                    )
                else:
                    fut = None
                await self._send_sync_frames(writer, frames, sess)
        except BaseException:
            if fut is not None:
                # a send abort (e.g. _SlowPeer) abandons the in-flight
                # prefetch: consume its outcome so a collection error
                # can't surface as an unretrieved-exception log
                fut.add_done_callback(
                    lambda f: f.cancelled() or f.exception()
                )
            raise

    def _collect_serve_chunk(
        self, actor: bytes, bv, lo: int, hi: int, max_buf: int,
    ) -> Tuple[List[bytes], int]:
        """Worker-thread body: resolve + collect + encode one chunk of a
        full-range need, newest first from ``hi`` down to (at most)
        ``lo``.  Returns (encoded frames in serve order, next cursor —
        the version the per-version oracle would continue at)."""
        chunk_lo = max(lo, hi - self.SYNC_RESOLVE_CHUNK + 1)
        # phase A — bookkeeping resolution under the storage lock: pure
        # in-memory walk mirroring the oracle's per-version decisions
        plan: List[tuple] = []
        with self.storage._lock:
            last_cleared_ts = bv.last_cleared_ts
            v = hi
            while v >= chunk_lo:
                if bv.cleared.contains(v):
                    span_lo, span_hi = v, v
                    for cs_s, cs_e in bv.cleared.overlapping(v, v):
                        span_lo, span_hi = cs_s, cs_e
                    plan.append(("cleared", span_lo, span_hi))
                    v = span_lo - 1
                    continue
                entry = bv.versions.get(v)
                if entry is not None:
                    plan.append(("version", v, entry[0], entry[1]))
                else:
                    partial = bv.partials.get(v)
                    if partial is not None:
                        plan.append((
                            "partial", v, partial.seqs.spans(),
                            partial.last_seq, partial.ts,
                        ))
                v -= 1
            next_cursor = v
        # phase B — DB reads on a read-only pool connection, NO storage
        # lock: one range collection + one batched ts lookup + buffered
        # reads, all inside one read transaction (one WAL snapshot)
        version_items = [it for it in plan if it[0] == "version"]
        site = None if actor == self.actor_id else actor
        by_dbv: Dict[int, List] = {}
        ts_by_v: Dict[int, int] = {}
        buffered_by_v: Dict[int, dict] = {}
        with self.storage.reader() as conn:
            conn.execute("BEGIN")
            try:
                if version_items:
                    dbvs = [it[2] for it in version_items]
                    for ch in self.storage.collect_changes_ro(
                        conn, (min(dbvs), max(dbvs)), site
                    ):
                        by_dbv.setdefault(int(ch.db_version), []).append(ch)
                    ts_by_v = self.bookie.version_ts_many(
                        actor, [it[1] for it in version_items], conn=conn
                    )
                for it in plan:
                    if it[0] == "partial":
                        buffered_by_v[it[1]] = {
                            seq: wire.decode_buffered_change(blob)
                            for seq, blob in self.bookie.buffered_changes(
                                actor, it[1], conn=conn
                            )
                        }
            finally:
                if conn.in_transaction:
                    conn.execute("COMMIT")
        # phase C — encode frames in serve order (still in the worker,
        # so the event loop never pays for speedy encoding)
        frames: List[bytes] = []
        for it in plan:
            if it[0] == "cleared":
                cs = Changeset.empty(
                    (Version(it[1]), Version(it[2])), last_cleared_ts
                )
                frames.append(self.encode_sync_change_frame(actor, cs))
            elif it[0] == "version":
                v, dbv, last_seq = it[1], it[2], it[3]
                row_ts = ts_by_v.get(v)
                ts = Timestamp(row_ts) if row_ts is not None else Timestamp(0)
                changes = by_dbv.get(dbv)
                if not changes:
                    # read-time cleared detection (oracle parity): the
                    # version's rows were all overwritten since
                    cs = Changeset.empty((Version(v), Version(v)), ts)
                    frames.append(self.encode_sync_change_frame(actor, cs))
                    continue
                chunker = ChunkedChanges(
                    changes, 0, last_seq, max_buf_size=max_buf
                )
                for chunk, seqs in chunker:
                    cs = Changeset.full(
                        Version(v), chunk, seqs, last_seq, ts
                    )
                    frames.append(self.encode_sync_change_frame(actor, cs))
            else:
                v, have, last_seq, pts = it[1], it[2], it[3], it[4]
                buffered = buffered_by_v.get(v, {})
                for hs, he in have:
                    chunk = [
                        buffered[q]
                        for q in range(hs, he + 1)
                        if q in buffered
                    ]
                    cs = Changeset.full(
                        Version(v), chunk, (hs, he), last_seq,
                        pts or Timestamp(0),
                    )
                    frames.append(self.encode_sync_change_frame(actor, cs))
        return frames, next_cursor

    def encode_sync_change_frame(self, actor: bytes, cs: Changeset) -> bytes:
        """One served changeset → its exact on-wire frame bytes (speedy
        SyncMessage + u32-BE framing).  Shared by the per-version oracle
        and the batched pipeline so both emit identical bytes."""
        cv = ChangeV1(actor_id=ActorId(actor), changeset=cs)
        return speedy.frame(speedy.encode_sync_message(cv))

    async def _send_sync_frames(self, writer, frames: List[bytes],
                                sess: Optional[dict] = None) -> None:
        """Coalesced framing: buffer whole encoded changeset frames into
        one write with a single drain per chunk budget, instead of a
        write+drain round per changeset.  The budget is the session's
        ADAPTIVE chunk size (re-read after every drain): blocks stay
        small enough that a slow reader still backpressures individual
        drains past the adapt threshold — a block far above the
        transport's high-water mark would hide the stall from the
        timing-based halving/abort logic entirely."""
        buf: List[bytes] = []
        size = 0
        for f in frames:
            buf.append(f)
            size += len(f)
            self.metrics.counter("corro_sync_served_total")
            if size >= (sess["chunk"] if sess else self.SYNC_DRAIN_BUDGET):
                await self._drain_sync_block(writer, b"".join(buf), sess)
                buf, size = [], 0
        if buf:
            await self._drain_sync_block(writer, b"".join(buf), sess)

    async def _send_sync_change(self, writer, actor: bytes, cs: Changeset,
                                sess: Optional[dict] = None) -> None:
        """Send one changeset frame (the per-version oracle's framing:
        one write + one timed drain per changeset)."""
        self.metrics.counter("corro_sync_served_total")
        await self._drain_sync_block(
            writer, self.encode_sync_change_frame(actor, cs), sess
        )

    async def _drain_sync_block(self, writer, blob: bytes,
                                sess: Optional[dict] = None) -> None:
        """Write one buffered block and drain, timing the flush: a slow
        reader first halves the session's chunk budget (8 KiB floor
        1 KiB), then aborts the session outright
        (peer.rs:344-348,796-811)."""
        writer.write(blob)
        if sess is not None and "live" in sess:
            # per-session served-byte accounting: every serve path
            # (oracle and batched) funnels its writes through here
            sess["live"]["bytes"] += len(blob)
        t0 = self._clock.monotonic()
        try:
            await asyncio.wait_for(
                writer.drain(), timeout=self.SYNC_SLOW_ABORT
            )
        except asyncio.TimeoutError:
            raise _SlowPeer("peer too slow: send exceeded abort budget")
        if sess is not None:
            elapsed = self._clock.monotonic() - t0
            if elapsed > self.SYNC_ADAPT_THRESHOLD:
                if sess["chunk"] <= self.SYNC_CHUNK_MIN:
                    raise _SlowPeer(
                        "peer too slow even at the minimum chunk size"
                    )
                sess["chunk"] //= 2
                self.metrics.counter("corro_sync_chunk_halvings_total")

    # -- snapshot bootstrap (docs/sync.md, agent/snapshot.py) ----------
    #
    # The serve half answers a snap_request session with a consistent,
    # scrubbed VACUUM-INTO copy streamed as snap_chunk frames over the
    # coalesced sync framing (the adaptive drain/slow-peer budgets
    # apply to snapshot blocks exactly as to changeset blocks); the
    # client half stages the stream into a sidecar, verifies the
    # whole-snapshot digest, and atomically swaps it in under the
    # storage lock behind a journal marker so a crash at ANY point
    # boots into a clean retry (snapshot.recover_pending_install).
    # Dispatch is the pure function pair snapshot.covered_below_floor
    # / snapshot.client_behind over (client needs, server floors).

    def _snapshot_wanted(self, ours: SyncStateV1,
                         theirs: SyncStateV1) -> bool:
        """Should this client request a snapshot from this server
        instead of change-by-change needs?  True exactly when the
        server advertises snapshot floors covering at least one needed
        version (it compacted that history — changes can no longer
        deliver it) and the client is strictly behind the server on
        every actor it tracks (the install-safety gate)."""
        from corrosion_tpu.agent import snapshot as snaplib

        if not self.config.snapshot_install:
            return False
        floors = theirs.snap_floors
        if not floors:
            return False
        if not snaplib.client_behind(ours.heads, theirs.heads):
            return False
        needs = ours.compute_available_needs(theirs)
        return snaplib.covered_below_floor(needs, floors) >= 1

    def _pick_snapshot_session(self, sessions: List[dict],
                               ours: SyncStateV1):
        """Snapshot-or-changes dispatch over one round's handshaken
        sessions: at most ONE session installs — the first whose
        server can no longer serve the client's below-floor needs as
        changes.  Returns ``(snap_session_or_None, remaining)``.
        Shared with the virtual cluster's sync round so the campaign
        exercises the REAL selection policy."""
        if self.config.snapshot_install:
            for s in sessions:
                if self._snapshot_wanted(ours, s["theirs"]):
                    return s, [x for x in sessions if x is not s]
        return None, sessions

    def _snapshot_build(self) -> Tuple[str, bytes, int]:
        """Build (or reuse) the serve-side snapshot file; returns
        ``(path, digest, size)``.  Worker-thread body — one VACUUM at
        a time, and a restart storm's reborn clients share the cached
        file for ``snapshot_cache_s`` instead of re-vacuuming per
        serve."""
        with self._snap_build_lock:
            return self._snapshot_build_locked()

    def _snapshot_build_locked(self) -> Tuple[str, bytes, int]:
        from corrosion_tpu.agent import snapshot as snaplib

        now = self._clock.monotonic()
        cached = self._snap_cache
        if (
            cached is not None
            and now - cached[0] <= self.config.snapshot_cache_s
            and os.path.exists(cached[1])
        ):
            return cached[1], cached[2], cached[3]
        cache = self.config.db_path + ".snap-serve"
        tmp = cache + ".tmp"
        if os.path.exists(tmp):
            os.unlink(tmp)
        # write-behind barrier: the snapshot must carry every winner
        # whose apply was already announced, not just the flushed ones
        self.storage.flush_barrier()
        snaplib.build_snapshot(self.config.db_path, tmp)
        os.replace(tmp, cache)
        digest = snaplib.file_digest(cache)
        size = os.path.getsize(cache)
        self._snap_cache = (now, cache, digest, size)
        self.metrics.counter("corro_snapshot_builds_total")
        return cache, digest, size

    def _snapshot_build_open(self):
        """``(open file, digest, size)`` with the handle opened UNDER
        the build lock: a slow serve that out-lives ``snapshot_cache_s``
        must keep streaming the inode its offer advertised — a
        concurrent rebuild ``os.replace``s the cache path, and bytes
        from the NEW inode would fail the client's digest gate and
        breaker-trip an honest server."""
        with self._snap_build_lock:
            path, digest, size = self._snapshot_build_locked()
            return open(path, "rb"), digest, size

    def _snapshot_serve_record(self, peer, size: int) -> None:
        """Serve-side accounting, shared by the live stream path and
        the virtual cluster's in-memory seam."""
        self.metrics.counter("corro_snapshot_serves_total")
        self.metrics.counter(
            "corro_snapshot_bytes_total", size, dir="served"
        )
        self._flight_event("snap_serve", peer=peer, bytes=size)

    async def _serve_snapshot(self, writer, sess: dict) -> None:
        """Serve one snapshot session: offer (digest + size), chunked
        file stream, done — every block through ``_drain_sync_block``
        so the slow-reader halving/abort budgets bound a stalled
        client exactly as on a changeset serve."""
        loop = asyncio.get_running_loop()
        pool = self._serve_executor()
        # the handle opens under the build lock (POSIX: os.replace of
        # the cache path cannot retarget an open fd), so the streamed
        # bytes always hash to the digest this offer advertises
        f, digest, size = await loop.run_in_executor(
            pool, self._snapshot_build_open
        )
        try:
            await self._drain_sync_block(
                writer,
                speedy.frame(
                    speedy.encode_sync_message(
                        ("snap_offer", digest, size)
                    )
                ),
                sess,
            )
            chunk = max(1, self.config.snapshot_chunk_bytes)
            sent = 0
            while True:
                data = await loop.run_in_executor(pool, f.read, chunk)
                if not data:
                    break
                sent += len(data)
                await self._drain_sync_block(
                    writer,
                    speedy.frame(
                        speedy.encode_sync_message(("snap_chunk", data))
                    ),
                    sess,
                )
        finally:
            f.close()
        await self._drain_sync_block(
            writer,
            speedy.frame(speedy.encode_sync_message(("snap_done",))),
            sess,
        )
        live = sess.get("live") if sess else None
        self._snapshot_serve_record(
            live["peer"] if live else None, sent
        )

    # -- client-side staging + crash-safe install ----------------------

    def _snapshot_stage_begin(self, peer, digest: bytes, size: int,
                              their_heads,
                              crash_at: Optional[str] = None) -> dict:
        """Open the staging sidecar + journal marker for an offered
        snapshot.  ``their_heads`` is the server's advertised per-actor
        head map at dispatch time — the install-safety gate
        (``snapshot.client_behind``) re-runs over it under the storage
        lock before the swap, so ANY change applied mid-transfer beyond
        what the snapshot holds (a local write, or another actor's
        broadcast this client may be the only holder of) aborts the
        install instead of being rolled back.  ``crash_at`` is the
        fault harness's injected death stage (faults.SnapFault); never
        set on a production path."""
        from corrosion_tpu.agent import snapshot as snaplib

        db = self.config.db_path
        sp = snaplib.staged_path(db)
        if os.path.exists(sp):
            os.unlink(sp)
        snaplib.write_marker(db, "staging", digest, size)
        f = open(sp, "wb")
        return {
            "f": f, "path": sp, "digest": bytes(digest),
            "size": int(size), "n": 0, "peer": peer,
            "their_heads": {
                (a.bytes if isinstance(a, ActorId) else bytes(a)): int(h)
                for a, h in dict(their_heads).items()
            },
            "t0": self._clock.monotonic(), "crash_at": crash_at,
        }

    def _snapshot_stage_feed(self, st: dict, data: bytes) -> None:
        from corrosion_tpu.agent import snapshot as snaplib

        st["f"].write(data)
        st["n"] += len(data)
        if st["n"] > st["size"]:
            raise snaplib.SnapshotError(
                "snapshot stream exceeds the offered size"
            )
        self.metrics.counter(
            "corro_snapshot_bytes_total", len(data), dir="received"
        )

    def _snapshot_abort(self, st: dict, reason: str, addr=None,
                        trip: bool = False) -> None:
        """Discard a staged snapshot cleanly: sidecar + marker go, the
        previous database is untouched, the rejection is counted
        (``corro_sync_client_rejects_total{reason=}``) and — for
        verified-hostile serves like a digest mismatch — the peer's
        breaker trips so the retry round falls back to change-by-change
        via another peer."""
        from corrosion_tpu.agent import snapshot as snaplib

        f = st.pop("f", None)
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        try:
            if os.path.exists(st["path"]):
                os.unlink(st["path"])
        except OSError:
            pass
        snaplib.clear_marker(self.config.db_path)
        self.metrics.counter(
            "corro_snapshot_installs_total",
            result=reason[5:] if reason.startswith("snap_") else reason,
        )
        self._sync_client_reject(reason, addr, trip=trip)
        self._flight_event(
            "snap_abort", peer=st.get("peer"), reason=reason
        )

    def _snapshot_install_staged(self, st: dict, addr=None) -> bool:
        """Verify, prepare, and atomically install a fully-staged
        snapshot (worker-thread body; the virtual cluster calls it
        inline).  Returns True on success; False after a clean abort —
        the caller's needs stay in bookkeeping, so the partial-round
        retry falls back to change-by-change via another peer."""
        from corrosion_tpu.agent import snapshot as snaplib

        db = self.config.db_path
        f = st.pop("f")
        f.flush()
        os.fsync(f.fileno())
        f.close()
        if st["n"] != st["size"] \
                or snaplib.file_digest(st["path"]) != st["digest"]:
            # the containment gate: a truncated, corrupted, or
            # divergent-minted snapshot (a hostile server advertising
            # the honest digest over tampered bytes) dies here — trip
            # the breaker, never install
            self._snapshot_abort(st, "snap_digest", addr, trip=True)
            return False
        try:
            snaplib.prepare_staged(
                st["path"], self.actor_id, self.incarnation
            )
        except Exception:
            self._snapshot_abort(st, "snap_prepare", addr, trip=True)
            return False
        t_swap = self._clock.monotonic()
        with self.storage._lock:
            # the install-safety gate, re-run over EVERY tracked actor
            # at the last possible moment: a change applied
            # mid-transfer beyond the server's recorded heads — our
            # own write, or another actor's broadcast this client may
            # be the only remaining holder of — must abort the swap,
            # not be rolled back by it
            ours = {}
            for actor, bv in self.bookie.actors().items():
                head = bv.last()
                if head:
                    ours[bytes(actor)] = head
            if not snaplib.client_behind(ours, st["their_heads"]):
                self._snapshot_abort(st, "snap_stale", addr)
                return False
            snaplib.write_marker(db, "installing", st["digest"],
                                 st["size"])
            if st.get("crash_at") == "installing":
                raise snaplib.SnapshotCrash("installing")
            try:
                self.storage.install_snapshot(st["path"])
                if st.get("crash_at") == "swapped":
                    raise snaplib.SnapshotCrash("swapped")
                snaplib.clear_marker(db)
                self._post_install_reload()
            except snaplib.SnapshotCrash:
                # injected death: leave marker/sidecar exactly as the
                # crash found them (the boot recovery contract under
                # test); the harness closes the agent
                raise
            except BaseException:
                # a FAILED swap: storage came back up on whatever file
                # survived (install_snapshot's recovery), so every
                # in-memory view must follow its connection — without
                # this the Bookie would keep writing into the closed
                # pre-swap handle
                try:
                    self._post_install_reload()
                except Exception:
                    logger.exception(
                        "post-failure snapshot reload failed"
                    )
                raise
        self.metrics.counter(
            "corro_snapshot_installs_total", result="ok"
        )
        self.metrics.histogram(
            "corro_snapshot_install_seconds",
            self._clock.monotonic() - st["t0"],
        )
        self.metrics.gauge(
            "corro_snapshot_swap_seconds",
            round(self._clock.monotonic() - t_swap, 6),
        )
        self._flight_event(
            "snap_install", peer=st.get("peer"), bytes=st["n"]
        )
        return True

    def _post_install_reload(self) -> None:
        """Rebuild every in-memory view of storage after the swap
        (caller holds the storage lock).  Object identities survive —
        the Bookie and CrConn rebuild IN PLACE so every component
        holding a reference keeps working against the installed
        database."""
        self.bookie.reload(self.storage.conn)
        self.bookie.backfill_own_sync_state(self.actor_id)
        self._sync_gen_cache = None
        self._snap_cache = None
        # node-local planes the snapshot scrubbed: membership table and
        # incarnation re-persist from the live in-memory state
        self._members_table()
        self._persist_members()
        self._persist_incarnation()
        # the digest FIFO is node-local (scrubbed); signed proofs are
        # portable and rode the snapshot — reload re-creates the
        # tables and re-asserts the proof-backed permanent verdicts
        with self._equiv_lock:
            self._equiv_digests.clear()
            self._equiv_sigs.clear()
        if self.config.equivocation_detection:
            self._load_equiv_digests()
        self._register_backfills()

    async def _snapshot_client_session(self, s: dict) -> Tuple[int, bool]:
        """One outbound snapshot session (the dispatch chose install
        over change-by-change): request, stage the chunk stream,
        verify, install, then rely on the next anti-entropy round for
        the tail delta.  The PR 13 serve-path client defenses apply
        symmetrically — whole-session deadline on the injected clock,
        frame-validation budget, offer screen — and every failure is a
        clean abort that keeps the needs in bookkeeping for the
        partial-round retry."""
        from corrosion_tpu.agent import snapshot as snaplib

        m, reader, writer = s["member"], s["reader"], s["writer"]
        frames = s["frames"]
        addr = tuple(m.addr)
        peer_hex = m.actor_id.hex()
        live = self._sync_session_begin("client", peer_hex, 1)
        self._flight_event(
            "sync_client_start", peer=peer_hex, needs=1
        )
        their_heads = s["theirs"].heads
        st: Optional[dict] = None
        installed = False
        try:
            writer.write(
                speedy.frame(
                    speedy.encode_sync_message(("snap_request",))
                )
            )
            await writer.drain()
            if writer.can_write_eof():
                writer.write_eof()
            deadline = None
            if self.config.sync_session_deadline_s > 0:
                deadline = (self._clock.monotonic()
                            + self.config.sync_session_deadline_s)
            frame_errs = 0
            done = False
            eof = False
            while not (done or eof):
                read_timeout = 10.0
                if deadline is not None:
                    remaining = deadline - self._clock.monotonic()
                    if remaining <= 0:
                        self._sync_client_reject(
                            "deadline", addr, strike=True
                        )
                        break
                    read_timeout = min(read_timeout, remaining)
                data = await asyncio.wait_for(
                    reader.read(65536), timeout=read_timeout
                )
                if not data:
                    eof = True
                    break
                live["bytes"] += len(data)
                try:
                    payloads = frames.feed(data)
                except speedy.SpeedyError:
                    self._sync_client_reject(
                        "frame_garbage", addr, trip=True
                    )
                    break
                for payload in payloads:
                    try:
                        msg = speedy.decode_sync_message(payload)
                    except speedy.SpeedyError:
                        frame_errs += 1
                        self._sync_client_reject("frame_garbage")
                        if frame_errs > self.SYNC_CLIENT_FRAME_BUDGET:
                            self._trip_breaker(addr)
                            done = True
                        continue
                    if isinstance(msg, Timestamp):
                        try:
                            self.clock.update_with_timestamp(msg)
                        except Exception:
                            pass
                    elif isinstance(msg, tuple) and msg[0] == "snap_offer":
                        _tag, digest, size = msg
                        if st is not None or size <= 0 \
                                or size > self.config.snapshot_max_bytes:
                            self._sync_client_reject(
                                "snap_offer", addr, trip=True
                            )
                            done = True
                            continue
                        st = await asyncio.to_thread(
                            self._snapshot_stage_begin, peer_hex,
                            digest, size, their_heads,
                        )
                    elif isinstance(msg, tuple) and msg[0] == "snap_chunk":
                        if st is None:
                            # chunks with no prior offer: the same
                            # frame-validation budget as undecodable
                            # frames — an endless offer-less chunk
                            # stream must trip the breaker, not burn
                            # the whole session deadline every round
                            frame_errs += 1
                            self._sync_client_reject("snap_offer")
                            if frame_errs > self.SYNC_CLIENT_FRAME_BUDGET:
                                self._trip_breaker(addr)
                                done = True
                            continue
                        try:
                            await asyncio.to_thread(
                                self._snapshot_stage_feed, st, msg[1]
                            )
                        except snaplib.SnapshotError:
                            self._snapshot_abort(
                                st, "snap_stream", addr, trip=True
                            )
                            st = None
                            done = True
                    elif isinstance(msg, tuple) and msg[0] == "snap_done":
                        if st is None:
                            break
                        installed = await asyncio.to_thread(
                            self._snapshot_install_staged, st, addr
                        )
                        st = None
                        done = True
            if st is not None:
                # stream ended without snap_done — a truncated serve,
                # a blown session deadline (already a breaker STRIKE
                # above), or an honest server crash.  None of these is
                # VERIFIED hostility, so no breaker trip: tripping here
                # would let a slow link cycle a bootstrapping client
                # through honest peers' breakers forever.  Tampered
                # bytes still die on the digest gate (trip=True there)
                self._snapshot_abort(st, "snap_stream", addr)
                st = None
            if installed:
                self.members.update_sync_ts(
                    m.actor_id, self._clock.wall()
                )
            return (1 if installed else 0), installed
        except (asyncio.TimeoutError, OSError, ConnectionError,
                speedy.SpeedyError, snaplib.SnapshotError,
                sqlite3.Error) as e:
            # sqlite3.Error covers a storage-level install failure
            # (disk full mid-swap): install_snapshot restores a
            # working connection on whatever file survives, and the
            # abort here cleans the sidecar/marker + counts the
            # failure instead of gather() swallowing it silently
            if isinstance(e, sqlite3.Error):
                logger.error("snapshot install failed: %s", e)
            if st is not None:
                self._snapshot_abort(st, "snap_stream", addr)
                st = None
            return 0, False
        finally:
            writer.close()
            self._sync_session_end(live, "client", "received")
            self._flight_event(
                "sync_client_end", peer=peer_hex,
                changes=0, bytes=live["bytes"], complete=installed,
            )


# ---------------------------------------------------------------------------
# UDP protocol
# ---------------------------------------------------------------------------


_SWIM_KINDS = frozenset(
    ("announce", "announce_ack", "probe", "ack", "ping_req",
     "probe_relay", "leave", "change")
)


class _UdpProtocol(asyncio.DatagramProtocol):
    def __init__(self, agent: Agent):
        self.agent = agent

    def datagram_received(self, data: bytes, addr) -> None:
        a = self.agent
        # wire sniff: JSON envelopes start with '{'; foca datagrams
        # start with the uuid length prefix (0x10).  Receivers accept
        # both so mixed-wire clusters interoperate.
        if not data.startswith(b"{"):
            from corrosion_tpu.agent import swim_foca

            swim_foca.handle_datagram(a, data, addr)
            return
        try:
            msg = wire.decode_datagram(data)
        except ValueError:
            return
        if msg.get("c", 0) != a.config.cluster_id:
            # cross-cluster SWIM traffic is dropped wholesale: the
            # sender is not a member here and must not refresh (or
            # create) a membership entry
            a.metrics.counter("corro_swim_cluster_rejected_total")
            return
        kind = msg.get("k")
        a.metrics.counter(
            "corro_gossip_datagrams_received_total",
            # remote-supplied: clamp to the known protocol kinds so a
            # hostile peer can't mint unbounded series
            kind=kind if kind in _SWIM_KINDS else "other",
        )
        if kind == "announce":
            a._ingest_piggyback(msg.get("pb", []))
            a._send_udp(addr, {"k": "announce_ack", "pb": a._piggyback(10)})
        elif kind == "announce_ack":
            a._ingest_piggyback(msg.get("pb", []))
        elif kind == "probe":
            a._ingest_piggyback(msg.get("pb", []))
            a._send_udp(addr, {"k": "ack", "n": msg["n"], "pb": a._piggyback()})
        elif kind == "ack":
            a._ingest_piggyback(msg.get("pb", []))
            fut = a._acks.get(msg.get("n"))
            if fut and not fut.done():
                fut.set_result(True)
        elif kind == "ping_req":
            target = tuple(msg["target"])
            a._send_udp(
                target,
                {
                    "k": "probe_relay",
                    "n": msg["n"],
                    "reply_to": msg["reply_to"],
                    "pb": a._piggyback(),
                },
            )
        elif kind == "leave":
            # graceful departure: mark down at the leaver's own
            # incarnation (its refutations have stopped, so the record
            # sticks and piggybacks onward)
            try:
                actor = wire._unb64(msg["a"])
                inc = int(msg.get("i", 0))
            except (KeyError, ValueError, TypeError):
                return
            m = a.members.get(actor) if actor else None
            if m is not None:
                a.members.upsert(
                    actor, m.addr, MemberState.DOWN,
                    max(m.incarnation, inc),
                )
        elif kind == "probe_relay":
            a._ingest_piggyback(msg.get("pb", []))
            a._send_udp(
                tuple(msg["reply_to"]),
                {"k": "ack", "n": msg["n"], "pb": a._piggyback()},
            )
        elif kind == "change":
            # legacy datagram path (changesets normally ride uni-streams
            # now); still accepted, routed through the bounded queue
            try:
                cv = wire.change_v1_from_dict(msg["cv"])
            except (KeyError, ValueError):
                return
            a.enqueue_change(cv, ChangeSource.BROADCAST)


# ---------------------------------------------------------------------------
# sync state <-> wire dicts
# ---------------------------------------------------------------------------


def _sync_state_to_dict(st: SyncStateV1) -> dict:
    return {
        "actor": wire._b64(st.actor_id.bytes),
        "heads": {wire._b64(a.bytes): int(v) for a, v in st.heads.items()},
        "need": {
            wire._b64(a.bytes): [list(sp) for sp in spans]
            for a, spans in st.need.items()
        },
        "partial_need": {
            wire._b64(a.bytes): {
                str(int(v)): [list(sp) for sp in spans]
                for v, spans in partials.items()
            }
            for a, partials in st.partial_need.items()
        },
        "last_cleared_ts": (
            int(st.last_cleared_ts) if st.last_cleared_ts is not None else None
        ),
    }


def _parse_addr(s: str) -> Tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host or "127.0.0.1", int(port))


def _drop_most_transmitted(pending: List[tuple], cap: int) -> int:
    """Overflow policy for the retransmission set: drop the payloads with
    the MOST sends so far (smallest ``remaining``), keeping fresh changes'
    retransmissions alive.  Parity: ``drop_oldest_broadcast`` drops max
    send_count (``broadcast/mod.rs:782-801``).  Entries are
    ``(due, frame, cv, remaining, sent_to)``; returns the drop count."""
    if len(pending) <= cap:
        return 0
    pending.sort(key=lambda p: p[3])
    dropped = len(pending) - cap
    del pending[:dropped]
    return dropped
