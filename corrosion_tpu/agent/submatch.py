"""Sharded columnar subscription matcher core.

The per-sub incremental path in :mod:`corrosion_tpu.agent.pubsub` runs
one scoped SQL evaluation PER SUBSCRIPTION per change wave — correct,
but at production fan-out (10^5..10^6 standing subscriptions) the cost
is ``O(subs × waves)`` SQL round-trips for work that is almost entirely
redundant: every subscription on a table re-derives the same per-pk
liveness and row content from the same change batch.

This module factors the shared work out, in the same one-encode /
one-dispatch discipline as the batched apply and group-commit planes:

* a change wave for a table is resolved ONCE through the columnar CRDT
  merge kernel (:func:`corrosion_tpu.ops.merge.encode_change_batch` +
  ``select_winners``): duplicate and superseded changes coalesce to one
  verdict per pk, and row liveness falls out of the final causal length
  (odd = live) without touching the database;
* live rows are fetched ONCE per (table, wave) — not once per sub;
* subscriptions register *predicate specs* (:class:`SubSpec`) into a
  per-shard inverted index (:class:`ShardIndex`): pk IN-list predicates
  index ``pk -> subs`` so a wave pk reaches exactly the subscriptions
  whose filter contains it, and whole-table subscriptions fan out to
  every wave pk.  Matching is set membership, not SQL.

The pubsub manager owns one :class:`ShardIndex` per matcher shard and
consumes :func:`resolve_wave` + :func:`match_wave` from its shard
workers; ``bench.py --subs`` drives the same two functions directly at
the 100k-sub headline.  Queries whose shape the spec language cannot
express keep the per-sub path — the parity oracle — untouched.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Callable, Dict, FrozenSet, List, Optional, Set, Tuple

from corrosion_tpu.types.change import SENTINEL_CID


def shard_of(sub_id: str, n_shards: int) -> int:
    """Stable shard assignment for a subscription id.

    blake2s, not ``hash()``: the assignment must survive restarts
    (``PYTHONHASHSEED`` randomizes ``hash(str)``) so restored
    subscriptions land on the same shard their persisted state was
    maintained from."""
    if n_shards <= 1:
        return 0
    digest = hashlib.blake2s(sub_id.encode(), digest_size=4).digest()
    return int.from_bytes(digest, "big") % n_shards


@dataclass(frozen=True)
class SubSpec:
    """One columnar-matchable subscription predicate.

    ``proj_idx`` indexes into the table's declared column order (the
    fetch row), ``pk_filter`` is the packed-pk membership set of a pk
    IN-list predicate (None = whole table).  Only shapes whose verdict
    is fully decidable from (pk, liveness, current row) qualify — the
    detector in pubsub.py guarantees that before registering."""

    sub_id: str
    table: str
    proj_idx: Tuple[int, ...]
    pk_filter: Optional[FrozenSet[bytes]] = None


class ShardIndex:
    """Inverted predicate index for one matcher shard.

    ``pk -> subs`` for IN-list predicates plus a broad (whole-table)
    set per table.  Mutated under the manager's lock; read by the
    shard's own worker thread only after the wave buffer referencing it
    was routed under the same lock."""

    def __init__(self) -> None:
        self.specs: Dict[str, SubSpec] = {}
        self._broad: Dict[str, Set[str]] = {}
        self._by_pk: Dict[str, Dict[bytes, Set[str]]] = {}

    def add(self, spec: SubSpec) -> None:
        self.remove(spec.sub_id)
        self.specs[spec.sub_id] = spec
        if spec.pk_filter is None:
            self._broad.setdefault(spec.table, set()).add(spec.sub_id)
            return
        per = self._by_pk.setdefault(spec.table, {})
        for pk in spec.pk_filter:
            per.setdefault(pk, set()).add(spec.sub_id)

    def remove(self, sub_id: str) -> None:
        spec = self.specs.pop(sub_id, None)
        if spec is None:
            return
        if spec.pk_filter is None:
            broad = self._broad.get(spec.table)
            if broad:
                broad.discard(sub_id)
                if not broad:
                    del self._broad[spec.table]
            return
        per = self._by_pk.get(spec.table)
        if not per:
            return
        for pk in spec.pk_filter:
            subs = per.get(pk)
            if subs:
                subs.discard(sub_id)
                if not subs:
                    del per[pk]
        if not per:
            del self._by_pk[spec.table]

    def has(self, table: str) -> bool:
        return table in self._broad or table in self._by_pk

    def subs_on(self, table: str) -> Set[str]:
        out: Set[str] = set(self._broad.get(table, ()))
        for subs in self._by_pk.get(table, {}).values():
            out |= subs
        return out


def resolve_wave(changes, backend: str = "auto"):
    """Coalesce one table's change wave to per-pk verdicts.

    Returns ``(pks, alive)``: unique pks in first-appearance order and
    their net liveness after the whole wave (final causal length odd).
    The columnar merge kernel resolves duplicates and superseded
    changes in one segmented scan; a wave the kernel cannot encode
    (non-int clock fields) falls back to a max-cl dict pass with the
    same semantics."""
    from corrosion_tpu.ops import merge as mergeops

    plan = mergeops.encode_change_batch(changes, SENTINEL_CID)
    if plan is None:
        seen: Dict[bytes, int] = {}
        for ch in changes:
            cl = int(ch.cl)
            if cl > seen.get(ch.pk, -1):
                seen[ch.pk] = cl
        return list(seen.keys()), [cl % 2 == 1 for cl in seen.values()]
    dec = mergeops.select_winners(plan, backend=backend)
    return list(plan.pk_values), [bool(a) for a in dec.alive.tolist()]


def match_wave(
    index: ShardIndex,
    table: str,
    pks: List[bytes],
    fetch: Callable[[List[bytes]], Dict[bytes, tuple]],
) -> Tuple[Dict[str, Dict[bytes, Optional[tuple]]], int]:
    """Fan one resolved wave out to every subscribed predicate.

    ``fetch(pks) -> {pk: row}`` returns the CURRENT rows (post-apply
    database state, declared column order); it is called ONCE with
    every wave pk that reaches at least one subscription, and row
    presence decides the verdict (present -> upsert, absent ->
    delete).  The wave's own liveness bits (:func:`resolve_wave`) are
    deliberately NOT trusted for the final verdict: the database may
    have resolved a buffered change differently (a stale delete loses
    to a newer column version already applied — the row stays live) or
    moved past the wave (a later applied wave deleted a row this one
    inserted) — in both cases the database is the converged truth the
    per-sub oracle would read, so parity requires deciding from it.
    Returns ``(verdicts, n_pairs)`` where ``verdicts[sub_id][pk]`` is
    the row tuple (upsert) or None (delete), and ``n_pairs`` counts
    delivered (sub, pk) verdicts for the throughput counters."""
    broad = index._broad.get(table)
    by_pk = index._by_pk.get(table)
    need = [
        pk for pk in pks if broad or (by_pk and pk in by_pk)
    ]
    rows = fetch(need) if need else {}
    verdicts: Dict[str, Dict[bytes, Optional[tuple]]] = {}
    n_pairs = 0
    for pk in need:
        row = rows.get(pk)
        targets = by_pk.get(pk) if by_pk else None
        if targets:
            for sid in targets:
                verdicts.setdefault(sid, {})[pk] = row
            n_pairs += len(targets)
        if broad:
            for sid in broad:
                verdicts.setdefault(sid, {})[pk] = row
            n_pairs += len(broad)
    return verdicts, n_pairs
