"""SWIM over the foca binary wire: the agent-side protocol driver.

With ``AgentConfig.swim_wire == "foca"`` the agent's SWIM datagrams are
binary foca messages (``bridge/foca.py``) instead of the JSON envelope —
the wire the reference relays verbatim
(``crates/corro-agent/src/broadcast/mod.rs:185-324``).  The message
flows map onto the existing host state machine (probe futures, suspicion
reaper, ``Members``):

* ``Announce`` → ``Feed`` (receiver replies with its active members);
* ``Ping(n)`` → ``Ack(n)`` — resolves the prober's ack future;
* indirect probe chain (``handlers.rs`` / foca probe semantics):
  origin → helper ``PingReq{target, n}``; helper → target
  ``IndirectPing{origin, n}``; target → helper ``IndirectAck{target:
  origin, n}``; helper → origin ``ForwardedAck{origin: target, n}``;
* ``Gossip`` — pure update carrier (graceful leave rides this with a
  self=Down update, foca ``leave_cluster``);
* ``TurnUndead`` — "you are down here": the receiver renews its
  identity (fresh ts + bumped incarnation) and re-announces, foca
  ``Identity::renew`` auto-rejoin (``actor.rs:199-210``).

Identity semantics: a member's ``Actor.ts`` names its *identity
generation* — an update carrying a newer ts than we know replaces the
member wholesale (fresh incarnation space), which is how a renewed
(rejoined) node overrides its own stale DOWN record.

Every non-Broadcast datagram piggybacks cluster updates
(freshness-prioritized: least-retransmitted entries first, foca's
update backlog policy) up to the 1178-byte packet cap.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, List, Optional, Tuple

from corrosion_tpu.agent.members import MemberState
from corrosion_tpu.bridge import foca

if TYPE_CHECKING:  # pragma: no cover
    from corrosion_tpu.agent.runtime import Agent

_STATE_TO_WIRE = {
    MemberState.ALIVE: foca.STATE_ALIVE,
    MemberState.SUSPECT: foca.STATE_SUSPECT,
    MemberState.DOWN: foca.STATE_DOWN,
}
_WIRE_TO_STATE = {v: k for k, v in _STATE_TO_WIRE.items()}


def self_actor(agent: "Agent") -> foca.FocaActor:
    return foca.FocaActor(
        id=agent.actor_id,
        addr=tuple(agent.gossip_addr),
        ts=agent._identity_ts,
        cluster_id=agent.config.cluster_id,
    )


def _member_actor(agent: "Agent", actor_id: bytes,
                  addr: Tuple[str, int]) -> foca.FocaActor:
    return foca.FocaActor(
        id=actor_id,
        addr=tuple(addr),
        ts=agent._swim_ts.get(actor_id, 0),
        cluster_id=agent.config.cluster_id,
    )


def _nil_actor(agent: "Agent", addr: Tuple[str, int]) -> foca.FocaActor:
    """Announce target: only the gossip addr is known (Actor::from
    <SocketAddr>, actor.rs:172-180 — nil id, zero ts)."""
    return foca.FocaActor(
        id=b"\x00" * 16, addr=tuple(addr), ts=0,
        cluster_id=agent.config.cluster_id,
    )


def _backlog_limit(agent: "Agent", n_members: int) -> int:
    """The shared decay budget: one update rides at most this many
    sends after it last changed (both the piggyback selection and the
    gossip-round skip-check key on it)."""
    from corrosion_tpu.utils.swimscale import scaled_update_retransmissions

    return scaled_update_retransmissions(n_members + 1)


def backlog_has_fresh(agent: "Agent") -> bool:
    """True while any member update still has retransmission budget."""
    members = agent.members.all()
    limit = _backlog_limit(agent, len(members))
    return any(
        agent._swim_update_tx.get(m.actor_id, 0) < limit for m in members
    )


def piggyback(agent: "Agent", k: int = 5) -> List[foca.FocaMember]:
    """Self entry + up to k freshest (least-transmitted) member
    updates.  Transmission counts persist on the agent and an entry
    decays out of the backlog after the cluster-size-scaled
    retransmission limit — foca's update queue policy (reset to fresh
    whenever the record changes)."""
    out = [foca.FocaMember(
        actor=self_actor(agent),
        incarnation=agent.incarnation,
        state=foca.STATE_ALIVE,
    )]
    members = agent.members.all()
    limit = _backlog_limit(agent, len(members))
    members.sort(
        key=lambda m: agent._swim_update_tx.get(m.actor_id, 0)
    )
    for m in members[:k]:
        tx = agent._swim_update_tx.get(m.actor_id, 0)
        if tx >= limit:
            break  # sorted ascending: everything after is decayed too
        agent._swim_update_tx[m.actor_id] = tx + 1
        out.append(foca.FocaMember(
            actor=_member_actor(agent, m.actor_id, m.addr),
            incarnation=m.incarnation,
            state=_STATE_TO_WIRE[m.state],
        ))
    return out


def send(agent: "Agent", addr: Tuple[str, int], dst: foca.FocaActor,
         message: foca.FocaMessage,
         updates: Optional[List[foca.FocaMember]] = None) -> None:
    if agent._udp is None:
        return
    if agent.fault_filter is not None:
        # same injection seam as Agent._send_udp: SWIM datagrams are
        # unreliable by design, so an injected drop is indistinguishable
        # from the network eating the packet
        act = agent.fault_filter("udp", tuple(addr))
        if act is not None and act.drop:
            agent.metrics.counter(
                "corro_transport_faults_injected_total", kind="udp"
            )
            return
        if act is not None and act.delay and agent._loop is not None:
            agent._loop.call_later(
                act.delay, _send_now, agent, addr, dst, message, updates
            )
            return
    _send_now(agent, addr, dst, message, updates)


def _send_now(agent: "Agent", addr: Tuple[str, int], dst: foca.FocaActor,
              message: foca.FocaMessage,
              updates: Optional[List[foca.FocaMember]] = None) -> None:
    if agent._udp is None:
        return
    d = foca.FocaDatagram(
        src=self_actor(agent),
        src_incarnation=agent.incarnation,
        dst=dst,
        message=message,
        updates=piggyback(agent) if updates is None else updates,
    )
    data = foca.encode_datagram(d)
    agent.metrics.counter(
        "corro_gossip_datagrams_sent_total",
        kind=foca_kind_label(message.tag),
    )
    agent._udp.sendto(data, tuple(addr))


_RESOLVE_TTL = 30.0
_resolve_cache: dict = {}  # host -> (ip, expires_at)


def _resolve_host(host: str) -> str:
    """Hostname → numeric IP with a short success-only TTL cache:
    getaddrinfo blocks, and the announce loop re-announces the same
    bootstrap hosts every cycle — a slow DNS server must not stall the
    event loop (and with it every in-flight probe) on each pass.
    Failures are NOT cached (a bootstrap peer whose record appears
    later must still resolve) and entries expire so re-scheduled hosts
    pick up their new address."""
    import socket
    import time

    hit = _resolve_cache.get(host)
    now = time.monotonic()
    if hit is not None and hit[1] > now:
        return hit[0]
    try:
        infos = socket.getaddrinfo(host, None, type=socket.SOCK_DGRAM)
    except OSError:
        return host  # send() will fail; retried next cycle
    ip = infos[0][4][0]
    _resolve_cache[host] = (ip, now + _RESOLVE_TTL)
    if len(_resolve_cache) > 512:
        _resolve_cache.clear()  # crude bound; bootstrap sets are tiny
    return ip


def _resolve(addr: Tuple[str, int]) -> Tuple[str, int]:
    """Bootstrap entries may be hostnames; the wire's SocketAddr form
    is numeric (the reference resolves bootstrap names before
    announcing)."""
    import ipaddress

    host, port = addr
    try:
        ipaddress.ip_address(host)
        return (host, port)
    except ValueError:
        return (_resolve_host(host), port)


def announce(agent: "Agent", addr: Tuple[str, int]) -> None:
    addr = _resolve(addr)
    send(agent, addr, _nil_actor(agent, addr),
         foca.FocaMessage(tag=foca.ANNOUNCE), updates=[])


def probe(agent: "Agent", m, nonce: int) -> None:
    send(agent, m.addr, _member_actor(agent, m.actor_id, m.addr),
         foca.FocaMessage(tag=foca.PING, probe_number=nonce))


def ping_req(agent: "Agent", helper, target, nonce: int) -> None:
    send(
        agent, helper.addr,
        _member_actor(agent, helper.actor_id, helper.addr),
        foca.FocaMessage(
            tag=foca.PING_REQ, probe_number=nonce,
            peer=_member_actor(agent, target.actor_id, target.addr),
        ),
    )


def gossip_round(agent: "Agent", k_targets: int = 3) -> int:
    """One periodic-gossip round (foca ``Config.periodic_gossip``, on
    in the WAN preset the reference uses): send a pure update-carrier
    ``Gossip`` datagram to a few random alive members — dissemination
    must not ride only on probe/ack piggyback, whose volume shrinks
    exactly when the cluster is quiet.  Skips the round entirely when
    the update backlog has fully decayed (nothing fresh to carry).
    Returns the number of datagrams sent."""
    if not backlog_has_fresh(agent):
        return 0
    alive = agent.members.alive()
    if not alive:
        return 0
    targets = agent._rng.sample(alive, min(k_targets, len(alive)))
    for m in targets:
        send(agent, m.addr, _member_actor(agent, m.actor_id, m.addr),
             foca.FocaMessage(tag=foca.GOSSIP))
    return len(targets)


def leave(agent: "Agent") -> None:
    """Graceful leave: Gossip datagrams carrying our own Down update
    (foca leave_cluster, broadcast/mod.rs:327-366)."""
    down_self = foca.FocaMember(
        actor=self_actor(agent),
        incarnation=agent.incarnation,
        state=foca.STATE_DOWN,
    )
    for m in agent.members.alive():
        send(agent, m.addr,
             _member_actor(agent, m.actor_id, m.addr),
             foca.FocaMessage(tag=foca.GOSSIP), updates=[down_self])


def _ingest_update(agent: "Agent", fm: foca.FocaMember) -> None:
    if fm.actor.cluster_id != agent.config.cluster_id:
        return
    if fm.actor.id == agent.actor_id:
        # refutation: someone says we are suspect/down at an incarnation
        # that supersedes ours — bump past it; our next piggybacked self
        # entry (on every outgoing datagram) carries the refutation
        if (fm.state != foca.STATE_ALIVE
                and fm.incarnation >= agent.incarnation):
            agent.incarnation = fm.incarnation + 1
            agent._persist_incarnation()
        return
    known_ts = agent._swim_ts.get(fm.actor.id)
    # ts == 0 means the SENDER never learned this identity's generation
    # (e.g. pre-seeded membership): apply by plain incarnation rules —
    # dropping those would starve dissemination of exactly the
    # suspicion/down records failure detection rides on.  Only a REAL
    # but older ts is a stale generation.
    if 0 < fm.actor.ts < (known_ts or 0):
        return
    if fm.actor.ts > 0 and (known_ts is None or fm.actor.ts > known_ts):
        # new member or renewed identity: fresh incarnation space
        # replaces whatever record (possibly DOWN) we held — and any
        # suspicion timer the OLD generation had armed
        agent._swim_ts[fm.actor.id] = fm.actor.ts
        if known_ts is not None:
            agent.members.remove(fm.actor.id)
            agent._suspects.pop(fm.actor.id, None)
    if agent.members.upsert(
        fm.actor.id, fm.actor.addr, _WIRE_TO_STATE[fm.state],
        fm.incarnation,
    ):
        # a changed record is fresh news: back into the gossip backlog,
        # and the shared per-node suspicion-timer bookkeeping runs
        # (foca: every member that LEARNS a suspicion starts its own
        # deadline — detection must not serialize behind the
        # first-hand suspecter's gossip)
        agent._swim_update_tx[fm.actor.id] = 0
        agent.note_member_state(fm.actor.id, _WIRE_TO_STATE[fm.state])


def handle_datagram(agent: "Agent", data: bytes, addr) -> None:
    try:
        d = foca.decode_datagram(data)
    except (foca.FocaError, ValueError):
        return
    if d.src.cluster_id != agent.config.cluster_id:
        agent.metrics.counter("corro_swim_cluster_rejected_total")
        return
    # dst validation: id-addressed datagrams must name us; nil-id dst
    # (an addr-addressed join/announce) is accepted as-is — it reached
    # our socket, and requiring literal addr equality would drop joins
    # whose bootstrap entry spells our address differently (hostname,
    # 0.0.0.0 bind) — the reference resolves bootstrap names to socket
    # addrs before announcing, which our config layer does not
    if d.dst.id != b"\x00" * 16 and d.dst.id != agent.actor_id:
        return  # addressed to some other identity
    tag = d.message.tag
    agent.metrics.counter(
        "corro_gossip_datagrams_received_total",
        kind=foca_kind_label(tag),
    )
    # a member we hold DOWN is talking: tell it (foca notify_down_members
    # → TurnUndead) so it renews its identity and rejoins
    held = agent.members.get(d.src.id)
    if (held is not None and held.state is MemberState.DOWN
            and tag != foca.TURN_UNDEAD
            and d.src.ts <= agent._swim_ts.get(d.src.id, 0)):
        send(agent, d.src.addr, d.src,
             foca.FocaMessage(tag=foca.TURN_UNDEAD), updates=[])
    # the sender itself is live first-hand evidence
    if d.src.id != agent.actor_id and d.src.id != b"\x00" * 16:
        _ingest_update(agent, foca.FocaMember(
            actor=d.src, incarnation=d.src_incarnation,
            state=foca.STATE_ALIVE,
        ))
    for fm in d.updates:
        _ingest_update(agent, fm)

    if tag == foca.ANNOUNCE:
        # feed the joiner our view (foca Feed reply)
        send(agent, d.src.addr, d.src,
             foca.FocaMessage(tag=foca.FEED),
             updates=piggyback(agent, k=10))
    elif tag == foca.PING:
        send(agent, d.src.addr, d.src,
             foca.FocaMessage(tag=foca.ACK,
                              probe_number=d.message.probe_number))
    elif tag == foca.ACK:
        fut = agent._acks.get(d.message.probe_number)
        if fut and not fut.done():
            fut.set_result(True)
    elif tag == foca.PING_REQ:
        target = d.message.peer
        if target is not None:
            send(agent, target.addr, target,
                 foca.FocaMessage(
                     tag=foca.INDIRECT_PING, peer=d.src,
                     probe_number=d.message.probe_number,
                 ))
    elif tag == foca.INDIRECT_PING:
        origin = d.message.peer
        if origin is not None:
            # reply to the HELPER (the datagram's sender) naming the
            # origin; the helper forwards
            send(agent, d.src.addr, d.src,
                 foca.FocaMessage(
                     tag=foca.INDIRECT_ACK, peer=origin,
                     probe_number=d.message.probe_number,
                 ))
    elif tag == foca.INDIRECT_ACK:
        origin = d.message.peer
        if origin is not None:
            send(agent, origin.addr, origin,
                 foca.FocaMessage(
                     tag=foca.FORWARDED_ACK, peer=d.src,
                     probe_number=d.message.probe_number,
                 ))
    elif tag == foca.FORWARDED_ACK:
        fut = agent._acks.get(d.message.probe_number)
        if fut and not fut.done():
            fut.set_result(True)
    elif tag == foca.TURN_UNDEAD:
        # we are down in the sender's view: renew identity and rejoin
        agent.rejoin()
    # FEED / GOSSIP / BROADCAST carry no extra handling beyond updates


_KIND_LABELS = {
    foca.PING: "probe", foca.ACK: "ack", foca.PING_REQ: "ping_req",
    foca.INDIRECT_PING: "indirect_ping",
    foca.INDIRECT_ACK: "indirect_ack",
    foca.FORWARDED_ACK: "forwarded_ack",
    foca.ANNOUNCE: "announce", foca.FEED: "feed", foca.GOSSIP: "gossip",
    foca.BROADCAST: "broadcast", foca.TURN_UNDEAD: "turn_undead",
}


def foca_kind_label(tag: int) -> str:
    return _KIND_LABELS.get(tag, "other")
