"""Backup / restore.

Parity: ``corrosion backup`` (``corrosion/src/main.rs:155-220``: VACUUM
INTO a consistent snapshot, then scrub node-local state — members and the
site-local identity marker — so the backup can seed any node) and
``corrosion restore`` (``sqlite3-restore``: take exclusive locks and swap
the database in place; ``main.rs:221-324``).

Ours uses sqlite's online backup API for the copy-in (safe against a live
writer on the same connection path thanks to WAL + the backup API's
page-tracking), which replaces the reference's byte-range lock dance.
"""

from __future__ import annotations

import os
import sqlite3
from typing import Optional


def _connect(path: str) -> sqlite3.Connection:
    """Open an agent database with the CRR layer's SQL functions
    registered (expression indexes reference them)."""
    from corrosion_tpu.agent.storage import register_udfs

    conn = sqlite3.connect(path)
    register_udfs(conn)
    return conn


def backup(db_path: str, out_path: str) -> None:
    """Write a consistent, scrubbed snapshot of the database."""
    if os.path.exists(out_path):
        raise FileExistsError(out_path)
    src = _connect(db_path)
    try:
        src.execute("VACUUM INTO ?", (out_path,))
    finally:
        src.close()
    snap = _connect(out_path)
    try:
        # scrub node-local state: membership and gossip runtime tables are
        # not part of the data being backed up
        tables = {
            r[0]
            for r in snap.execute(
                "SELECT name FROM sqlite_master WHERE type='table'"
            )
        }
        if "__corro_members" in tables:
            snap.execute("DELETE FROM __corro_members")
        snap.commit()
        snap.execute("VACUUM")
    finally:
        snap.close()


def restore(backup_path: str, db_path: str,
            site_id: Optional[bytes] = None) -> None:
    """Replace the database at db_path with the backup's contents, giving
    the restored node its OWN identity.

    The site-ordinal rewrite (reference: ``main.rs:221-324``): the backup
    origin's site_id is moved to a fresh ordinal — keeping every clock
    row's attribution intact — and ordinal 1 (the local-identity slot our
    triggers stamp) gets a new site_id, so the restored node never
    impersonates the node that made the backup.

    Must run while no agent owns db_path (the CLI enforces this).
    """
    import uuid

    src = _connect(backup_path)
    dst = _connect(db_path)
    try:
        src.backup(dst)
        new_site = site_id or uuid.uuid4().bytes
        row = dst.execute(
            "SELECT site_id FROM __corro_sites WHERE ordinal=1"
        ).fetchone()
        if row is not None and bytes(row[0]) != new_site:
            old_site = bytes(row[0])
            # move the origin identity to a fresh ordinal...
            (max_ord,) = dst.execute(
                "SELECT COALESCE(MAX(ordinal), 1) FROM __corro_sites"
            ).fetchone()
            new_ord = max_ord + 1
            dst.execute(
                "UPDATE __corro_sites SET ordinal=? WHERE ordinal=1", (new_ord,)
            )
            # ...rewriting its attribution in every clock table...
            tables = [
                r[0]
                for r in dst.execute(
                    "SELECT name FROM __corro_crr_tables"
                ).fetchall()
            ]
            for t in tables:
                for suffix in ("__corro_clock", "__corro_cl"):
                    dst.execute(
                        f'UPDATE "{t}{suffix}" SET site_ordinal=? '
                        "WHERE site_ordinal=1",
                        (new_ord,),
                    )
            # ...and installing the restored node's own identity at slot 1
            dst.execute(
                "INSERT INTO __corro_sites (ordinal, site_id) VALUES (1, ?)",
                (new_site,),
            )
        dst.commit()
    finally:
        src.close()
        dst.close()
    for ext in ("-wal", "-shm"):
        p = db_path + ext
        if os.path.exists(p):
            os.unlink(p)
