"""Backup / restore.

Parity: ``corrosion backup`` (``corrosion/src/main.rs:155-220``: VACUUM
INTO a consistent snapshot, then scrub node-local state — members and the
site-local identity marker — so the backup can seed any node) and
``corrosion restore`` (``sqlite3-restore``: take exclusive locks and swap
the database in place; ``main.rs:221-324``).

Ours uses sqlite's online backup API for the copy-in (safe against a live
writer on the same connection path thanks to WAL + the backup API's
page-tracking), which replaces the reference's byte-range lock dance.
"""

from __future__ import annotations

import os
import sqlite3
from typing import Optional


def _connect(path: str) -> sqlite3.Connection:
    """Open an agent database with the CRR layer's SQL functions
    registered (expression indexes reference them)."""
    from corrosion_tpu.agent.snapshot import _connect as _snap_connect

    return _snap_connect(path)


def backup(db_path: str, out_path: str) -> None:
    """Write a consistent, scrubbed snapshot of the database."""
    if os.path.exists(out_path):
        raise FileExistsError(out_path)
    src = _connect(db_path)
    try:
        src.execute("VACUUM INTO ?", (out_path,))
    finally:
        src.close()
    snap = _connect(out_path)
    try:
        # scrub node-local state through the SHARED decision registry
        # (snapshot.SNAP_SCRUB/SNAP_KEEP): membership, the compaction
        # work list and the node-local equivocation digest FIFO go;
        # signed equivocation proofs (portable cluster evidence) and
        # the pending as_crr backfill queue (its rows travel
        # unversioned — the restored node's boot re-registration needs
        # the entry) stay.  An internal table with no registered
        # decision raises — a future bookkeeping table cannot silently
        # leak into backups
        from corrosion_tpu.agent.snapshot import scrub_snapshot

        scrub_snapshot(snap)
        snap.commit()
        snap.execute("VACUUM")
    finally:
        snap.close()


def restore(backup_path: str, db_path: str,
            site_id: Optional[bytes] = None) -> None:
    """Replace the database at db_path with the backup's contents, giving
    the restored node its OWN identity.

    The site-ordinal rewrite (reference: ``main.rs:221-324``): the backup
    origin's site_id is moved to a fresh ordinal — keeping every clock
    row's attribution intact — and ordinal 1 (the local-identity slot our
    triggers stamp) gets a new site_id, so the restored node never
    impersonates the node that made the backup.

    Must run while no agent owns db_path (the CLI enforces this).
    """
    import uuid

    src = _connect(backup_path)
    dst = _connect(db_path)
    has_sites = False
    try:
        src.backup(dst)
        dst.commit()
        has_sites = dst.execute(
            "SELECT 1 FROM __corro_sites WHERE ordinal=1"
        ).fetchone() is not None
    finally:
        src.close()
        dst.close()
    new_site = site_id or uuid.uuid4().bytes
    if has_sites:
        # ONE identity-rewrite implementation, shared with the
        # snapshot install path (snapshot.prepare_staged): the origin
        # moves to a fresh ordinal with its clock attribution intact,
        # ordinal 1 becomes the restored node's own site id — reusing
        # an existing ordinal when the backup already knew this id
        from corrosion_tpu.agent.snapshot import prepare_staged

        prepare_staged(db_path, new_site)
    for ext in ("-wal", "-shm"):
        p = db_path + ext
        if os.path.exists(p):
            os.unlink(p)
