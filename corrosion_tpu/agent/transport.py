"""Outbound transport: datagrams for SWIM, cached uni-streams for
changesets, bi-streams for sync.

Parity: the reference multiplexes three channel classes over one QUIC
connection (``crates/corro-agent/src/transport.rs``, ``api/peer.rs:97-342``):
unreliable datagrams for SWIM packets (≤1178 B, foca's max packet),
uni-directional streams for broadcast changesets, bi-directional streams
for sync sessions — with a connection cache keyed by address, a liveness
test + single retry, and RTT samples pushed into the member rings.

Ours maps datagrams onto the agent's UDP endpoint (with the same 1178 B
hard cap — oversize SWIM payloads are a bug, not a fragmentation
exercise) and uni/bi streams onto TCP connections to the peer's gossip
port.  Uni connections are cached and reused; a dead cached connection
is dropped and reopened once (transport.rs:213-233 retry semantics).
"""

from __future__ import annotations

import asyncio
import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple

Addr = Tuple[str, int]

# foca's max packet size (broadcast/mod.rs:943); SWIM messages must fit
MAX_UDP_PAYLOAD = 1178


class TokenBucket:
    """Byte-rate limiter (the 10 MiB/s broadcast governor,
    broadcast/mod.rs:455-458).  ``clock`` injects the time source
    (``corrosion_tpu/clock.py``); default = real time."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock=None):
        from corrosion_tpu.clock import SYSTEM_CLOCK

        self.rate = float(rate)
        self.burst = float(burst if burst is not None else rate)
        self._clock = clock or SYSTEM_CLOCK
        self._tokens = self.burst
        self._last = self._clock.monotonic()

    def _refill(self) -> None:
        now = self._clock.monotonic()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate
        )
        self._last = now

    async def consume(self, n: float) -> None:
        while True:
            self._refill()
            if self._tokens >= n:
                self._tokens -= n
                return
            need = (n - self._tokens) / self.rate
            await self._clock.sleep(min(need, 1.0))


class ConnStats:
    """Per-peer connection statistics (the TCP/TLS stand-in for the
    reference's full quinn ConnectionStats export,
    ``transport.rs:235-419``): cumulative across reconnects to the same
    address, surfaced through metrics and ``cluster members``."""

    __slots__ = ("connects", "bytes_sent", "frames_sent", "failures",
                 "faults_dropped", "redials", "breaker_opens",
                 "rtt_last_ms", "rtt_min_ms", "last_used")

    def __init__(self):
        self.connects = 0
        self.bytes_sent = 0
        self.frames_sent = 0
        self.failures = 0
        # degraded-mode accounting: injected in-flight drops (fault
        # injection), reconnect attempts after a dead cached conn, and
        # circuit-breaker open transitions — the chaos-run debug surface
        self.faults_dropped = 0
        self.redials = 0
        self.breaker_opens = 0
        self.rtt_last_ms: Optional[float] = None
        self.rtt_min_ms: Optional[float] = None
        self.last_used = 0.0

    def as_dict(self) -> dict:
        return {
            "connects": self.connects,
            "bytes_sent": self.bytes_sent,
            "frames_sent": self.frames_sent,
            "failures": self.failures,
            "faults_dropped": self.faults_dropped,
            "redials": self.redials,
            "breaker_opens": self.breaker_opens,
            "rtt_last_ms": self.rtt_last_ms,
            "rtt_min_ms": self.rtt_min_ms,
        }


class CircuitBreaker:
    """Per-peer failure quarantine: after ``threshold`` consecutive
    failures the breaker OPENS and sends fail fast (no connect attempt,
    no timeout) until ``cooldown`` elapses; then ONE half-open trial is
    allowed — success closes the breaker, failure re-opens it for
    another cooldown.  This is what keeps a broadcast flush round
    bounded when a peer is dead: every destination past the first
    timeout burns zero wall-clock on the corpse."""

    __slots__ = ("threshold", "cooldown", "failures", "opened_at",
                 "half_open_inflight", "_now")

    def __init__(self, threshold: int = 5, cooldown: float = 3.0,
                 now=None):
        self.threshold = threshold
        self.cooldown = cooldown
        # the cooldown's time source (injectable-clock seam): a
        # virtual-time campaign ages breaker cooldowns on the event
        # heap instead of the wall
        self._now = now or time.monotonic
        self.failures = 0
        self.opened_at: Optional[float] = None
        self.half_open_inflight = False

    @property
    def is_open(self) -> bool:
        return self.opened_at is not None

    def allow(self, now: Optional[float] = None) -> bool:
        if self.opened_at is None:
            return True
        now = self._now() if now is None else now
        if now - self.opened_at < self.cooldown:
            return False
        # cooldown passed: admit one half-open trial at a time
        if self.half_open_inflight:
            return False
        self.half_open_inflight = True
        return True

    def record_success(self) -> bool:
        """Returns True when this success CLOSED an open breaker
        (the half-open restore path)."""
        self.failures = 0
        self.half_open_inflight = False
        if self.opened_at is not None:
            self.opened_at = None
            return True
        return False

    def record_failure(self, now: Optional[float] = None) -> bool:
        """Returns True when this failure OPENED the breaker."""
        self.half_open_inflight = False
        if self.opened_at is not None:
            # half-open trial failed: restart the cooldown
            self.opened_at = self._now() if now is None else now
            return False
        self.failures += 1
        if self.failures >= self.threshold:
            self.opened_at = self._now() if now is None else now
            return True
        return False

    def trip(self, now: Optional[float] = None) -> bool:
        """Force the breaker OPEN immediately (verified-hostile
        evidence — tampered signed bytes, garbage sync serves — is not
        ordinary flakiness worth ``threshold`` free strikes).  Returns
        True when this call newly opened it; an already-open breaker
        just restarts its cooldown."""
        self.half_open_inflight = False
        self.failures = self.threshold
        was_closed = self.opened_at is None
        self.opened_at = self._now() if now is None else now
        return was_closed

    def state(self) -> str:
        if self.opened_at is None:
            return "closed"
        if self._now() - self.opened_at >= self.cooldown:
            return "half-open"
        return "open"


def prune_breakers(breakers: dict, cap: int, on_evict=None) -> None:
    """Bound a breaker registry at insert time, cheapest state first:
    healthy entries (closed, no strikes), then closed entries with
    partial strikes (member churn accrues these forever and losing a
    strike count is cheap), then — because the survivors can ALL be
    open: verified-hostile evidence (``runtime._trip_breaker``) mints
    immediately-open breakers keyed by attacker-controlled ephemeral
    source addresses, and ``is_open`` holds until a dial SUCCEEDS —
    the oldest-OPENED entries go too.  A memory bound beats a perfect
    memory of every hostile port; a live offender re-trips on its
    next evidence.  ``on_evict(addr)`` fires for each evicted OPEN
    entry: an open breaker carries live member-quarantine state, and
    a fresh breaker minted later for the same address closes silently
    (``record_success`` on a never-opened breaker reports no
    transition), so the owner must lift the quarantine NOW or the
    member strands deprioritized forever."""
    if len(breakers) <= cap:
        return
    for a in [a for a, br in breakers.items()
              if not br.is_open and br.failures == 0]:
        del breakers[a]
    if len(breakers) <= cap:
        return
    for a in [a for a, br in breakers.items() if not br.is_open]:
        del breakers[a]
        if len(breakers) <= cap:
            return
    by_age = sorted(
        (a for a, br in breakers.items() if br.is_open),
        key=lambda a: breakers[a].opened_at or 0.0,
    )
    for a in by_age[: len(breakers) - cap]:
        del breakers[a]
        if on_evict is not None:
            on_evict(a)


class UniConnection:
    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.lock = asyncio.Lock()

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass


class Transport:
    """Connection-caching sender.  All methods are loop-affine.

    With ``mux=True`` (default) both reliable channel classes share
    ONE cached connection per peer (``agent/mux.py``: framed uni + bi
    channels — the reference's single-QUIC-connection shape), and
    peers spread over ``LANES`` hashed lanes, each with its own
    connect semaphore (the 8-client-endpoint spread,
    transport.rs:55-93).  ``mux=False`` keeps the round-4 wiring: a
    cached uni connection per peer + a fresh connection per sync
    session."""

    def __init__(self, metrics=None, connect_timeout: float = 2.0,
                 on_rtt=None, max_cached: int = 512, ssl_context=None,
                 mux: bool = True,
                 redial_retries: int = 2,
                 redial_base: float = 0.05,
                 redial_cap: float = 0.5,
                 breaker_threshold: int = 5,
                 breaker_cooldown: float = 3.0,
                 on_breaker: Optional[Callable[[Addr, bool], None]] = None,
                 rng: Optional[random.Random] = None,
                 clock=None):
        from corrosion_tpu.clock import SYSTEM_CLOCK

        self._uni: Dict[Addr, UniConnection] = {}
        self.metrics = metrics
        # the injectable time source behind cooldowns, RTT stamps,
        # fault delays and redial backoff sleeps
        self._clock = clock or SYSTEM_CLOCK
        self.connect_timeout = connect_timeout
        self.on_rtt = on_rtt  # callback(addr, rtt_seconds)
        self.ssl_context = ssl_context  # TLS for uni/bi streams (or None)
        self.stats: Dict[Addr, ConnStats] = {}
        # fault-injection hook: callable(channel, addr) -> FaultAction
        # (corrosion_tpu.faults) consulted on every send_uni/open_bi;
        # None = no faults (production default)
        self.fault_filter = None
        # bounded redial policy for dead cached connections: retries
        # ride utils.backoff (decorrelated jitter) off a seedable rng so
        # det-mode runs replay the same sleep schedule
        self.redial_retries = redial_retries
        self.redial_base = redial_base
        self.redial_cap = redial_cap
        self._rng = rng or random.Random()
        # per-peer circuit breakers: a persistently-failing address is
        # quarantined so one dead node cannot stall a flush round
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self.breakers: Dict[Addr, CircuitBreaker] = {}
        # registry mutation is NOT loop-affine: the apply workers'
        # verified-hostile convictions (runtime._trip_breaker) insert
        # from their pool threads while the loop's own _breaker does —
        # prune's iteration must never race an insert
        self.breakers_lock = threading.Lock()
        self.on_breaker = on_breaker  # callback(addr, opened: bool)
        # LRU cap on cached uni connections (the reference's QUIC conns
        # close on idle timeout; an unbounded TCP cache leaks fds in
        # large in-process clusters)
        self.max_cached = max_cached
        self.mux = mux
        self._muxes: Dict[Addr, "MuxConnection"] = {}
        # per-lane connect semaphores: a connect storm to many peers
        # fans across lanes instead of one queue
        self._lane_sems: Optional[list] = None
        # per-peer open lock: concurrent first sends to one peer must
        # share ONE connection, not race N opens
        self._open_locks: Dict[Addr, asyncio.Lock] = {}

    def _stat(self, addr: Addr) -> ConnStats:
        s = self.stats.get(addr)
        if s is None:
            s = self.stats[addr] = ConnStats()
            # bound the map like the conn cache (dead peers age out)
            if len(self.stats) > 4 * self.max_cached:
                oldest = sorted(self.stats, key=lambda a: self.stats[a].last_used)
                for a in oldest[: len(self.stats) - 2 * self.max_cached]:
                    del self.stats[a]
        s.last_used = self._clock.monotonic()
        return s

    def _record_rtt_stat(self, addr: Addr, rtt_s: float) -> None:
        s = self._stat(addr)
        ms = rtt_s * 1000.0
        s.rtt_last_ms = ms
        s.rtt_min_ms = ms if s.rtt_min_ms is None else min(s.rtt_min_ms, ms)

    # -- degraded-mode plumbing -----------------------------------------

    def _breaker(self, addr: Addr) -> CircuitBreaker:
        with self.breakers_lock:
            b = self.breakers.get(addr)
            if b is None:
                prune_breakers(
                    self.breakers, 4 * self.max_cached,
                    on_evict=(
                        None if self.on_breaker is None
                        else lambda a: self.on_breaker(a, False)
                    ),
                )
                b = self.breakers[addr] = CircuitBreaker(
                    self.breaker_threshold, self.breaker_cooldown,
                    now=self._clock.monotonic,
                )
        return b

    def _breaker_success(self, addr: Addr) -> None:
        b = self.breakers.get(addr)
        if b is not None and b.record_success():
            if self.metrics is not None:
                self.metrics.counter("corro_transport_breaker_closes_total")
            if self.on_breaker is not None:
                self.on_breaker(addr, False)

    def _breaker_failure(self, addr: Addr) -> None:
        if self._breaker(addr).record_failure():
            self._stat(addr).breaker_opens += 1
            if self.metrics is not None:
                self.metrics.counter("corro_transport_breaker_opens_total")
            if self.on_breaker is not None:
                self.on_breaker(addr, True)

    def _fault(self, channel: str, addr: Addr):
        """Consult the fault-injection hook; returns the action or None.
        Injected drops are the SENDER-INVISIBLE kind (in-flight loss,
        matching the sim's ``loss``): callers treat them as successful
        sends that the receiver never sees."""
        if self.fault_filter is None:
            return None
        act = self.fault_filter(channel, addr)
        if act is None or (not act.drop and not act.delay):
            return None
        return act

    def breaker_states(self) -> Dict[Addr, str]:
        with self.breakers_lock:
            snapshot = list(self.breakers.items())
        return {a: b.state() for a, b in snapshot}

    async def _open(self, addr: Addr, header: bytes) -> UniConnection:
        t0 = self._clock.monotonic()
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(
                addr[0], addr[1], ssl=self.ssl_context
            ),
            timeout=self.connect_timeout,
        )
        rtt = self._clock.monotonic() - t0
        self._stat(addr).connects += 1
        self._record_rtt_stat(addr, rtt)
        if self.on_rtt is not None:
            self.on_rtt(addr, rtt)
        if self.metrics is not None:
            self.metrics.histogram("corro_transport_connect_seconds", rtt)
        writer.write(header)
        await writer.drain()
        return UniConnection(reader, writer)

    # -- multiplexed path ------------------------------------------------

    def _lane_sem(self, addr: Addr):
        from corrosion_tpu.agent.mux import LANES, lane_of

        if self._lane_sems is None:
            self._lane_sems = [asyncio.Semaphore(32) for _ in range(LANES)]
        return self._lane_sems[lane_of(addr)]

    async def _get_mux(self, addr: Addr) -> "MuxConnection":
        from corrosion_tpu.agent.mux import STREAM_MUX, MuxConnection

        m = self._muxes.get(addr)
        if m is not None and not m.closed:
            # LRU touch
            self._muxes.pop(addr, None)
            self._muxes[addr] = m
            return m
        if len(self._open_locks) > 4 * self.max_cached:
            self._open_locks = {
                a: lk for a, lk in self._open_locks.items() if lk.locked()
            }
        open_lock = self._open_locks.setdefault(addr, asyncio.Lock())
        async with open_lock, self._lane_sem(addr):
            m = self._muxes.get(addr)
            if m is not None and not m.closed:
                return m
            t0 = self._clock.monotonic()
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(
                    addr[0], addr[1], ssl=self.ssl_context
                ),
                timeout=self.connect_timeout,
            )
            rtt = self._clock.monotonic() - t0
            self._stat(addr).connects += 1
            self._record_rtt_stat(addr, rtt)
            if self.on_rtt is not None:
                self.on_rtt(addr, rtt)
            if self.metrics is not None:
                self.metrics.histogram(
                    "corro_transport_connect_seconds", rtt)
            writer.write(STREAM_MUX)
            await writer.drain()
            m = MuxConnection(reader, writer, metrics=self.metrics,
                              clock=self._clock)
            self._muxes[addr] = m
            excess = len(self._muxes) - self.max_cached
            if excess > 0:
                for old_addr in list(self._muxes):
                    if excess <= 0:
                        break
                    old = self._muxes[old_addr]
                    if old is m or old._channels:
                        continue  # never evict one with live sessions
                    self._muxes.pop(old_addr)
                    old.close()
                    excess -= 1
            return m

    def _drop_mux(self, addr: Addr) -> None:
        m = self._muxes.pop(addr, None)
        if m is not None:
            m.close()

    async def send_uni(self, addr: Addr, frames: bytes,
                       header: bytes) -> bool:
        """Write pre-framed bytes on the cached uni channel to addr;
        a dead cached connection is dropped and redialed with bounded
        backoff.  An open circuit breaker fails fast (no connect, no
        timeout); injected faults drop in flight (sender sees success)."""
        act = self._fault("uni", addr)
        if act is not None:
            if act.delay:
                await self._clock.sleep(act.delay)
            if act.drop:
                self._stat(addr).faults_dropped += 1
                if self.metrics is not None:
                    self.metrics.counter(
                        "corro_transport_faults_injected_total", kind="uni"
                    )
                return True  # in-flight loss: the sender believes it sent
        if not self._breaker(addr).allow():
            # a fast-fail skip is not a new failure — `failures` counts
            # real exhausted send attempts (open_bi accounts the same
            # way); the skip volume has its own counter
            if self.metrics is not None:
                self.metrics.counter(
                    "corro_transport_breaker_skips_total")
            return False
        if self.mux:
            from corrosion_tpu.utils.backoff import Backoff, retry

            attempts = 0

            async def _attempt():
                nonlocal attempts
                attempts += 1
                try:
                    m = await self._get_mux(addr)
                    await m.send_uni(frames)
                except (OSError, ConnectionError, asyncio.TimeoutError):
                    # the cached mux is dead: drop it so the retry (and
                    # any concurrent sender) redials a fresh connection
                    self._drop_mux(addr)
                    if attempts > 1:
                        self._stat(addr).redials += 1
                    raise

            try:
                await retry(
                    _attempt,
                    Backoff(self.redial_base, self.redial_cap,
                            max_retries=self.redial_retries,
                            rng=self._rng),
                    sleep=self._clock.sleep,
                )
            except (OSError, ConnectionError, asyncio.TimeoutError):
                self._stat(addr).failures += 1
                self._breaker_failure(addr)
                if self.metrics is not None:
                    self.metrics.counter(
                        "corro_transport_uni_failures_total"
                    )
                return False
            st = self._stat(addr)
            st.bytes_sent += len(frames)
            st.frames_sent += 1
            self._breaker_success(addr)
            return True
        for attempt in (0, 1):
            conn = self._uni.get(addr)
            try:
                if conn is None:
                    conn = await self._open(addr, header)
                    self._uni[addr] = conn
                    excess = len(self._uni) - self.max_cached
                    for old_addr in list(self._uni):
                        if excess <= 0:
                            break
                        old = self._uni[old_addr]
                        # never close a connection a concurrent sender
                        # holds (its write would die mid-frame)
                        if old is conn or old.lock.locked():
                            continue
                        self._uni.pop(old_addr)
                        old.close()
                        excess -= 1
                else:
                    # LRU touch
                    self._uni.pop(addr, None)
                    self._uni[addr] = conn
                async with conn.lock:
                    conn.writer.write(frames)
                    await conn.writer.drain()
                st = self._stat(addr)
                st.bytes_sent += len(frames)
                st.frames_sent += 1
                if self.metrics is not None:
                    self.metrics.counter(
                        "corro_transport_uni_bytes_total", len(frames)
                    )
                self._breaker_success(addr)
                return True
            except (OSError, ConnectionError, asyncio.TimeoutError):
                if addr in self._uni:
                    self._uni.pop(addr).close()
                if attempt == 0:
                    self._stat(addr).redials += 1
                if attempt == 1:
                    self._stat(addr).failures += 1
                    self._breaker_failure(addr)
                    if self.metrics is not None:
                        self.metrics.counter(
                            "corro_transport_uni_failures_total"
                        )
                    return False
        return False

    async def open_bi(self, addr: Addr):
        """(reader, writer) for a sync session.  Multiplexed: a fresh
        bi CHANNEL on the peer's shared mux connection (dead cache
        entries dropped and redialed with bounded backoff); legacy: a
        fresh connection per session like the reference's open_bi.
        An open breaker or an injected partition/drop raises OSError —
        the retryable shape the sync client already handles."""
        act = self._fault("bi", addr)
        if act is not None:
            if act.delay:
                await self._clock.sleep(act.delay)
            if act.drop:
                self._stat(addr).faults_dropped += 1
                if self.metrics is not None:
                    self.metrics.counter(
                        "corro_transport_faults_injected_total", kind="bi"
                    )
                raise OSError("fault injected: bi stream dropped")
        if not self._breaker(addr).allow():
            if self.metrics is not None:
                self.metrics.counter("corro_transport_breaker_skips_total")
            raise OSError("circuit breaker open")
        if self.mux:
            from corrosion_tpu.utils.backoff import Backoff, retry

            attempts = 0

            async def _attempt():
                nonlocal attempts
                attempts += 1
                try:
                    m = await self._get_mux(addr)
                    return m.open_channel()
                except (OSError, ConnectionError, asyncio.TimeoutError):
                    self._drop_mux(addr)
                    if attempts > 1:
                        self._stat(addr).redials += 1
                    raise

            try:
                chan = await retry(
                    _attempt,
                    Backoff(self.redial_base, self.redial_cap,
                            max_retries=self.redial_retries,
                            rng=self._rng),
                    sleep=self._clock.sleep,
                )
            except (OSError, ConnectionError, asyncio.TimeoutError):
                self._stat(addr).failures += 1
                self._breaker_failure(addr)
                raise
            # re-check the PARTITION after the connect awaits: a
            # partition arming while open_connection was suspended
            # (TOCTOU) must not hand back a live channel — the whole
            # session it gates would then legally stream across the
            # "partition".  The probe consumes no seeded loss draw.
            act = self._fault("partition_check", addr)
            if act is not None and act.drop:
                self._drop_mux(addr)
                self._stat(addr).faults_dropped += 1
                # the breaker must see an outcome: allow() may have
                # admitted this call as THE half-open trial, and bailing
                # without recording one would leave half_open_inflight
                # latched and the breaker wedged open forever.  A
                # partitioned connect IS a failure to reach the peer.
                self._breaker_failure(addr)
                raise OSError("fault injected: bi stream dropped")
            self._breaker_success(addr)
            return chan
        t0 = self._clock.monotonic()
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(
                    addr[0], addr[1], ssl=self.ssl_context
                ),
                timeout=self.connect_timeout,
            )
        except (OSError, ConnectionError, asyncio.TimeoutError):
            self._stat(addr).failures += 1
            self._breaker_failure(addr)
            raise
        rtt = self._clock.monotonic() - t0
        self._stat(addr).connects += 1
        self._record_rtt_stat(addr, rtt)
        if self.on_rtt is not None:
            self.on_rtt(addr, rtt)
        self._breaker_success(addr)
        writer.write(b"B")  # STREAM_BI prelude (runtime dispatch)
        return reader, writer

    async def aclose(self) -> None:
        """Graceful close: waits for cached connections to fully close so
        no worker touches a half-torn-down socket during agent stop."""
        for m in list(self._muxes.values()):
            m.close()
        self._muxes.clear()
        conns = list(self._uni.values())
        self._uni.clear()
        for conn in conns:
            conn.close()
        async def _wait(conn):
            try:
                # a dead peer's unflushed send buffer can defer teardown
                # until the kernel's TCP retransmission timeout; don't let
                # that hold up agent shutdown
                await asyncio.wait_for(conn.writer.wait_closed(), timeout=2.0)
            except (OSError, ConnectionError, asyncio.TimeoutError):
                pass

        if conns:
            await asyncio.gather(*(_wait(c) for c in conns))

    def drop(self, addr: Addr) -> None:
        conn = self._uni.pop(addr, None)
        if conn is not None:
            conn.close()
        self._drop_mux(addr)

    def close(self) -> None:
        for conn in self._uni.values():
            conn.close()
        self._uni.clear()
        for m in self._muxes.values():
            m.close()
        self._muxes.clear()
