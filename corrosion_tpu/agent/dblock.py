"""Whole-database file locking for offline maintenance.

Parity: ``sqlite3-restore``'s ``lock_all`` (byte-level locks on every
range SQLite's unix VFS uses, ``sqlite3-restore/src/lib.rs:51-151``)
and the ``corrosion db lock <cmd>`` command (``main.rs:493-525``): grab
every lock, run a shell command (copy, fsck, restore) while holding
them, release on exit.

SQLite's unix VFS uses POSIX advisory record locks at fixed offsets, so
``fcntl.lockf`` on the same bytes genuinely excludes live SQLite
connections in other processes — this is interop, not imitation:

* main db file: PENDING (0x40000000), RESERVED (+1), and the SHARED
  range (+2 .. +511);
* ``-shm`` file (WAL mode): the 8 WAL-index lock bytes at offset 120.
"""

from __future__ import annotations

import fcntl
import os
import time
from typing import List

PENDING_BYTE = 0x40000000
RESERVED_BYTE = PENDING_BYTE + 1
SHARED_FIRST = PENDING_BYTE + 2
SHARED_SIZE = 510
WAL_LOCK_OFFSET = 120  # unixShmLock region in the -shm file
WAL_LOCK_COUNT = 8


class DbLock:
    """Holds every SQLite file lock; release with :meth:`close` (or use
    as a context manager)."""

    def __init__(self, files: List):
        self._files = files

    def close(self) -> None:
        for f in self._files:
            try:
                f.close()  # closing drops this process's POSIX locks
            except OSError:
                pass
        self._files = []

    def __enter__(self) -> "DbLock":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _lock_range(f, start: int, length: int, deadline: float) -> None:
    import errno

    while True:
        try:
            fcntl.lockf(f, fcntl.LOCK_EX | fcntl.LOCK_NB, length, start)
            return
        except OSError as e:
            # only CONTENTION retries; a filesystem that cannot lock at
            # all (e.g. ENOLCK on NFS) must fail immediately and say why
            if e.errno not in (errno.EACCES, errno.EAGAIN):
                raise
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"could not lock bytes {start}+{length} of "
                    f"{f.name} (database in use?)"
                ) from None
            time.sleep(0.05)


def lock_all(db_path: str, timeout_s: float = 30.0) -> DbLock:
    """Acquire every SQLite lock on ``db_path`` (and its ``-shm`` WAL
    index when present), retrying until ``timeout_s``.  While the
    returned handle is open, no other process's SQLite connection can
    read or write the database."""
    deadline = time.monotonic() + timeout_s
    files = []
    try:
        # r+b: a typo'd path must fail loudly, not silently lock (and
        # later "back up") a freshly created empty file
        db = open(db_path, "r+b")
        files.append(db)
        _lock_range(db, PENDING_BYTE, 1, deadline)
        _lock_range(db, RESERVED_BYTE, 1, deadline)
        _lock_range(db, SHARED_FIRST, SHARED_SIZE, deadline)
        shm_path = db_path + "-shm"
        if os.path.exists(shm_path):
            shm = open(shm_path, "r+b")
            files.append(shm)
            _lock_range(shm, WAL_LOCK_OFFSET, WAL_LOCK_COUNT, deadline)
        return DbLock(files)
    except BaseException:
        for f in files:
            try:
                f.close()
            except OSError:
                pass
        raise


def run_locked(db_path: str, cmd: str,
               timeout_s: float = 30.0) -> int:
    """``corrosion db lock <cmd>``: hold every lock while ``cmd`` runs;
    returns the command's exit code.

    ``cmd`` is argv-split (shlex) and executed WITHOUT a shell, exactly
    like the reference's shell_words::split + Command::new — pipe/
    redirect metacharacters are literal arguments, not shell syntax.
    """
    import shlex
    import subprocess

    with lock_all(db_path, timeout_s):
        proc = subprocess.run(shlex.split(cmd))
        return proc.returncode
