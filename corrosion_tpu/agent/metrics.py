"""Metrics registry + Prometheus text exposition.

Parity: the reference instruments ~150 series through the ``metrics``
crate facade and exposes them via a Prometheus HTTP exporter
(``config.rs:69-80``, ``agent/metrics.rs:18-108``): gossip/broadcast
counters, sync counters, channel depths, pool timings, per-table row
counts, db/WAL size gauges.  Ours is a small thread-safe registry the
agent exposes at ``GET /metrics`` on the API listener.
"""

from __future__ import annotations

import re
import threading
import time
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

LabelKV = Tuple[Tuple[str, str], ...]


def percentile_sorted(s, q: float):
    """Nearest-rank quantile of an ALREADY-SORTED non-empty sequence —
    the one indexing rule shared by exposition, health snapshots, and
    the cluster observer, so the telemetry-vs-ground-truth comparisons
    stay apples-to-apples."""
    return s[min(len(s) - 1, int(len(s) * q))]


class Metrics:
    def __init__(self):
        self._counters: Dict[str, Dict[LabelKV, float]] = defaultdict(dict)
        self._gauges: Dict[str, Dict[LabelKV, float]] = defaultdict(dict)
        self._histos: Dict[str, Dict[LabelKV, List[float]]] = defaultdict(dict)
        # cumulative (count, sum) per histogram series: the quantile ring
        # above trims to its last 1024 samples, so exposition's _count /
        # _sum must NOT be computed from it — they would silently reset
        # at the trim boundary and undercount forever after
        self._histo_agg: Dict[str, Dict[LabelKV, Tuple[int, float]]] = (
            defaultdict(dict)
        )
        self._lock = threading.Lock()

    def counter(self, name: str, value: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._counters[name][key] = self._counters[name].get(key, 0.0) + value

    def gauge(self, name: str, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._gauges[name][key] = value

    def histogram(self, name: str, value: float, **labels) -> None:
        self.histogram_keyed(name, value, tuple(sorted(labels.items())))

    def histogram_keyed(self, name: str, value: float, key: LabelKV) -> None:
        """Hot-path histogram insert with a caller-PRECOMPUTED label
        key (skips kwargs packing + sort — provenance records one of
        these per version on the ingest path)."""
        self.histogram_keyed_many(name, ((key, value),))

    def histogram_keyed_many(
        self, name: str, pairs: Iterable[Tuple[LabelKV, float]]
    ) -> None:
        """Batched keyed inserts under ONE lock hold — the ingest
        pipeline records a whole apply batch's provenance lags at
        once (PRs 3-5 batching discipline applied to telemetry)."""
        with self._lock:
            histos = self._histos[name]
            agg = self._histo_agg[name]
            for key, value in pairs:
                buf = histos.setdefault(key, [])
                buf.append(value)
                if len(buf) >= 1280:
                    # block trim: deleting ONE sample per insert once
                    # past the window is an O(window) memmove per
                    # observation — a measurable ingest tax; trimming
                    # 256 at a time amortizes it to O(1) (the ring
                    # holds 1024..1279 samples; quantiles read
                    # whatever is present)
                    del buf[: len(buf) - 1024]
                n, s = agg.get(key, (0, 0.0))
                agg[key] = (n + 1, s + value)

    def timed(self, name: str, **labels):
        return _Timer(self, name, labels)

    def get_counter(self, name: str, **labels) -> float:
        """Current value of one counter series (0.0 if never incremented)."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._counters.get(name, {}).get(key, 0.0)

    def get_counter_sum(self, name: str) -> float:
        """Sum of a counter across ALL its label variants."""
        with self._lock:
            return sum(self._counters.get(name, {}).values())

    def histogram_samples(self, name: str) -> Dict[LabelKV, List[float]]:
        """Snapshot of one histogram's windowed sample rings per label
        variant (the last ~1024-1279 observations each).  Harness surface:
        the in-process ClusterObserver computes exact cross-node
        percentiles from raw samples where exposition only carries
        per-node quantiles."""
        with self._lock:
            return {k: list(v) for k, v in self._histos.get(name, {}).items()}

    def histogram_stats(self, name: str, **labels) -> Tuple[int, float]:
        """Cumulative ``(count, sum)`` of one histogram series — never
        resets, unlike the windowed quantile ring."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._histo_agg.get(name, {}).get(key, (0, 0.0))

    @staticmethod
    def _series_key(name: str, key: LabelKV) -> str:
        """Flat ``name{k=v,...}`` identity for one labeled series — the
        flight recorder's snapshot/delta key (JSON-safe, stable)."""
        if not key:
            return name
        return name + "{" + ",".join(f"{k}={v}" for k, v in key) + "}"

    def snapshot_state(self) -> Tuple[Dict[str, float], Dict[str, float],
                                      Dict[str, Tuple[float, float]]]:
        """One-lock flight snapshot (``agent/recorder.py``): every
        counter value, every gauge, and each histogram series' windowed
        (p50, p99) — flattened to ``name{labels}`` keys.  One lock hold
        per snapshot interval, per the PR 3-6 batching discipline."""
        with self._lock:
            counters = {
                self._series_key(name, key): v
                for name, series in self._counters.items()
                for key, v in series.items()
            }
            gauges = {
                self._series_key(name, key): v
                for name, series in self._gauges.items()
                for key, v in series.items()
            }
            quantiles = {}
            for name, series in self._histos.items():
                for key, buf in series.items():
                    if not buf:
                        continue
                    s = sorted(buf)
                    quantiles[self._series_key(name, key)] = (
                        percentile_sorted(s, 0.5),
                        percentile_sorted(s, 0.99),
                    )
        return counters, gauges, quantiles

    # -- exposition ------------------------------------------------------

    def render(self, extra_gauges: Iterable[Tuple[str, float, dict]] = ()) -> str:
        out: List[str] = []

        def esc(val) -> str:
            # Prometheus label-value escaping: backslash, quote, newline
            return (
                str(val)
                .replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        def fmt(name: str, key: LabelKV, v: float, suffix: str = "") -> str:
            if key:
                lbl = ",".join(f'{k}="{esc(val)}"' for k, val in key)
                return f"{name}{suffix}{{{lbl}}} {v}"
            return f"{name}{suffix} {v}"

        # group extras by name up front and MERGE them into the gauge
        # registry families: a scrape-time gauge sharing a name with a
        # registered one (e.g. corro_members_ring0) must render under a
        # single "# TYPE" line — strict parsers reject a repeated TYPE
        grouped: Dict[str, Dict[LabelKV, float]] = {}
        for name, v, labels in extra_gauges:
            grouped.setdefault(name, {})[tuple(sorted(labels.items()))] = v
        with self._lock:
            for name, series in sorted(self._counters.items()):
                out.append(f"# TYPE {name} counter")
                for key, v in series.items():
                    out.append(fmt(name, key, v))
            gauges: Dict[str, Dict[LabelKV, float]] = {
                name: dict(series) for name, series in self._gauges.items()
            }
            for name, series in grouped.items():
                # scrape-time values win: they are current, the
                # registered value is the last push
                gauges.setdefault(name, {}).update(series)
            for name, series in sorted(gauges.items()):
                out.append(f"# TYPE {name} gauge")
                for key, v in series.items():
                    out.append(fmt(name, key, v))
            for name, series in sorted(self._histos.items()):
                out.append(f"# TYPE {name} summary")
                for key, buf in series.items():
                    if not buf:
                        continue
                    s = sorted(buf)
                    out.append(fmt(
                        name, key + (("quantile", "0.5"),),
                        percentile_sorted(s, 0.5),
                    ))
                    out.append(fmt(
                        name, key + (("quantile", "0.99"),),
                        percentile_sorted(s, 0.99),
                    ))
                    # quantiles come from the windowed ring; count/sum
                    # are the CUMULATIVE aggregates (a summary's _count
                    # must be monotone — the ring trims at 1024)
                    n, total = self._histo_agg[name].get(key, (0, 0.0))
                    out.append(fmt(name, key, float(n), "_count"))
                    out.append(fmt(name, key, float(total), "_sum"))
        return "\n".join(out) + "\n"


# -- strict exposition parsing ----------------------------------------
#
# The consumer half of the exposition contract: ClusterObserver scrapes
# every node's /metrics text through this parser, and the hostile-input
# exposition tests assert adversarial table names / label values still
# produce text it accepts.  Deliberately STRICT — any malformed line is
# an error, not a skip — so an escaping regression in render() fails
# loudly instead of silently corrupting a scrape.

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_TYPES = frozenset({"counter", "gauge", "summary", "histogram", "untyped"})


class ExpositionError(ValueError):
    """Prometheus text exposition violating the format."""


def _parse_labels(body: str) -> Dict[str, str]:
    """Parse the inside of ``{...}`` strictly: ``name="value"`` pairs,
    comma-separated, values escaped with ``\\\\``, ``\\"``, ``\\n``
    only."""
    labels: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        j = body.find("=", i)
        if j < 0:
            raise ExpositionError(f"label without '=': {body[i:]!r}")
        name = body[i:j]
        if not _NAME_RE.match(name):
            raise ExpositionError(f"bad label name {name!r}")
        if j + 1 >= n or body[j + 1] != '"':
            raise ExpositionError(f"unquoted label value after {name!r}")
        i = j + 2
        out: List[str] = []
        while True:
            if i >= n:
                raise ExpositionError(f"unterminated label value for {name!r}")
            c = body[i]
            if c == "\\":
                if i + 1 >= n:
                    raise ExpositionError("dangling escape")
                e = body[i + 1]
                if e == "\\":
                    out.append("\\")
                elif e == '"':
                    out.append('"')
                elif e == "n":
                    out.append("\n")
                else:
                    raise ExpositionError(f"bad escape \\{e}")
                i += 2
            elif c == '"':
                i += 1
                break
            elif c == "\n":
                raise ExpositionError("raw newline in label value")
            else:
                out.append(c)
                i += 1
        labels[name] = "".join(out)
        if i < n:
            if body[i] != ",":
                raise ExpositionError(f"junk after label value: {body[i:]!r}")
            i += 1
    return labels


def parse_prometheus_text(text: str) -> Dict[str, dict]:
    """Strictly parse Prometheus text exposition into
    ``{family: {"type": str, "samples": [(name, labels, value), ...]}}``.

    Summary ``_count``/``_sum`` suffix lines file under their base
    family.  Raises :class:`ExpositionError` on any malformed line,
    repeated ``# TYPE`` for one family, or a sample without a
    preceding TYPE declaration."""
    families: Dict[str, dict] = {}
    for lineno, line in enumerate(text.split("\n"), 1):
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 2 or parts[1] not in ("TYPE", "HELP"):
                raise ExpositionError(f"line {lineno}: bad comment {line!r}")
            if parts[1] == "HELP":
                continue
            if len(parts) != 4:
                raise ExpositionError(f"line {lineno}: bad TYPE line {line!r}")
            _, _, fam, typ = parts
            if not _NAME_RE.match(fam):
                raise ExpositionError(f"line {lineno}: bad family name {fam!r}")
            if typ not in _TYPES:
                raise ExpositionError(f"line {lineno}: unknown type {typ!r}")
            if fam in families:
                raise ExpositionError(
                    f"line {lineno}: repeated TYPE for {fam!r}"
                )
            families[fam] = {"type": typ, "samples": []}
            continue
        # sample line: name[{labels}] value
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                raise ExpositionError(f"line {lineno}: unbalanced braces")
            name = line[:brace]
            labels = _parse_labels(line[brace + 1 : close])
            rest = line[close + 1 :]
        else:
            sp = line.find(" ")
            if sp < 0:
                raise ExpositionError(f"line {lineno}: no value in {line!r}")
            name = line[:sp]
            labels = {}
            rest = line[sp:]
        if not _NAME_RE.match(name):
            raise ExpositionError(f"line {lineno}: bad metric name {name!r}")
        if not rest.startswith(" ") or " " in rest[1:].strip():
            raise ExpositionError(f"line {lineno}: bad value field {rest!r}")
        try:
            value = float(rest.strip())
        except ValueError as e:
            raise ExpositionError(f"line {lineno}: bad value: {e}") from None
        fam = name
        for suffix in ("_count", "_sum"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and base in families and families[base]["type"] in (
                "summary", "histogram",
            ):
                fam = base
                break
        if fam not in families:
            raise ExpositionError(
                f"line {lineno}: sample {name!r} without a TYPE declaration"
            )
        families[fam]["samples"].append((name, labels, value))
    return families


class _Timer:
    def __init__(self, metrics: Metrics, name: str, labels: dict):
        self.metrics = metrics
        self.name = name
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.metrics.histogram(
            self.name, time.perf_counter() - self.t0, **self.labels
        )
        return False
