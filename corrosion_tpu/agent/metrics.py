"""Metrics registry + Prometheus text exposition.

Parity: the reference instruments ~150 series through the ``metrics``
crate facade and exposes them via a Prometheus HTTP exporter
(``config.rs:69-80``, ``agent/metrics.rs:18-108``): gossip/broadcast
counters, sync counters, channel depths, pool timings, per-table row
counts, db/WAL size gauges.  Ours is a small thread-safe registry the
agent exposes at ``GET /metrics`` on the API listener.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, Iterable, List, Tuple

LabelKV = Tuple[Tuple[str, str], ...]


class Metrics:
    def __init__(self):
        self._counters: Dict[str, Dict[LabelKV, float]] = defaultdict(dict)
        self._gauges: Dict[str, Dict[LabelKV, float]] = defaultdict(dict)
        self._histos: Dict[str, Dict[LabelKV, List[float]]] = defaultdict(dict)
        self._lock = threading.Lock()

    def counter(self, name: str, value: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._counters[name][key] = self._counters[name].get(key, 0.0) + value

    def gauge(self, name: str, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._gauges[name][key] = value

    def histogram(self, name: str, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            buf = self._histos[name].setdefault(key, [])
            buf.append(value)
            if len(buf) > 1024:
                del buf[: len(buf) - 1024]

    def timed(self, name: str, **labels):
        return _Timer(self, name, labels)

    def get_counter(self, name: str, **labels) -> float:
        """Current value of one counter series (0.0 if never incremented)."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._counters.get(name, {}).get(key, 0.0)

    def get_counter_sum(self, name: str) -> float:
        """Sum of a counter across ALL its label variants."""
        with self._lock:
            return sum(self._counters.get(name, {}).values())

    # -- exposition ------------------------------------------------------

    def render(self, extra_gauges: Iterable[Tuple[str, float, dict]] = ()) -> str:
        out: List[str] = []

        def esc(val) -> str:
            # Prometheus label-value escaping: backslash, quote, newline
            return (
                str(val)
                .replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        def fmt(name: str, key: LabelKV, v: float, suffix: str = "") -> str:
            if key:
                lbl = ",".join(f'{k}="{esc(val)}"' for k, val in key)
                return f"{name}{suffix}{{{lbl}}} {v}"
            return f"{name}{suffix} {v}"

        with self._lock:
            for name, series in sorted(self._counters.items()):
                out.append(f"# TYPE {name} counter")
                for key, v in series.items():
                    out.append(fmt(name, key, v))
            for name, series in sorted(self._gauges.items()):
                out.append(f"# TYPE {name} gauge")
                for key, v in series.items():
                    out.append(fmt(name, key, v))
            for name, series in sorted(self._histos.items()):
                out.append(f"# TYPE {name} summary")
                for key, buf in series.items():
                    if not buf:
                        continue
                    s = sorted(buf)
                    out.append(fmt(name, key + (("quantile", "0.5"),), s[len(s) // 2]))
                    out.append(
                        fmt(name, key + (("quantile", "0.99"),), s[int(len(s) * 0.99)])
                    )
                    out.append(fmt(name, key, float(len(buf)), "_count"))
                    out.append(fmt(name, key, float(sum(buf)), "_sum"))
        # group extras by name: strict parsers reject a repeated
        # "# TYPE" line (one per label-variant would be one per table)
        grouped: Dict[str, List[Tuple[LabelKV, float]]] = {}
        for name, v, labels in extra_gauges:
            grouped.setdefault(name, []).append(
                (tuple(sorted(labels.items())), v)
            )
        for name in sorted(grouped):
            out.append(f"# TYPE {name} gauge")
            for key, v in grouped[name]:
                out.append(fmt(name, key, v))
        return "\n".join(out) + "\n"


class _Timer:
    def __init__(self, metrics: Metrics, name: str, labels: dict):
        self.metrics = metrics
        self.name = name
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.metrics.histogram(
            self.name, time.perf_counter() - self.t0, **self.labels
        )
        return False
