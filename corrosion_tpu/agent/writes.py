"""Group-commit write combining for the local client write path.

The third leg of the batching trilogy (apply: PR 3, serve: PR 4): the
local write front door.  Concurrent callers of
``Agent.execute_transaction`` enqueue ``WriteRequest``s here; one of
them — the **leader** — claims the queue and drains it in groups.  Each
group takes the storage lock ONCE, runs every client batch under its
own SAVEPOINT inside one outer transaction (a failing batch rolls back
to its savepoint and fails only its caller), assigns version/db_version
spans in submission order, persists bookkeeping with one ``executemany``
pass, commits once, and triggers ONE change collection for the whole
group's db_version span (see ``Agent._execute_write_group`` /
``docs/writes.md``).

Flat-combining leadership: the leader is always a caller thread — no
dedicated drainer thread, no event-loop dependency — so the combiner
works identically for HTTP handler threads, pg-wire sessions, offline
agents, and the deterministic scheduler.  Leadership HANDS OFF rather
than monopolizing: a leader drains groups only until its own request
resolves, then releases the claim and wakes a parked waiter to take
over — under sustained open-system load no caller is stuck serving
other clients' groups forever after its own write committed.

The per-transaction path (``Agent._execute_transaction_single``) stays
as the parity oracle: converged DB state, bookkeeping, broadcast
changesets, and subscription events must be equivalent (pinned by
tests/test_write_combiner.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence

from corrosion_tpu.agent.storage import unpack_stmt

# Statements that can escape a SAVEPOINT's blast radius (transaction
# control, schema/file-level commands): a "COMMIT" inside a client batch
# would commit half a group, a "ROLLBACK" would destroy the other
# callers' work.  Batches leading with any of these take the
# per-transaction oracle path instead (counted as a "stmt" fallback).
_TX_CONTROL = frozenset({
    "BEGIN", "COMMIT", "END", "ROLLBACK", "SAVEPOINT", "RELEASE",
    "ATTACH", "DETACH", "VACUUM", "PRAGMA",
})


def _leading_keyword(sql: str) -> str:
    """First keyword of ``sql``, with leading whitespace and SQL
    comments (``-- line`` and ``/* block */``) stripped — a comment
    prefix must not smuggle transaction control past the screen
    (``'/* x */ COMMIT'`` would otherwise commit half a group)."""
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
        elif sql.startswith("--", i):
            j = sql.find("\n", i)
            if j < 0:
                return ""
            i = j + 1
        elif sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                return ""
            i = j + 2
        else:
            break
    head = sql[i:].split(None, 1)
    return head[0].upper().rstrip(";") if head else ""


def has_tx_control(statements: Sequence) -> bool:
    """Does any statement open with a transaction-control / file-level
    keyword that must not run inside a shared group transaction?"""
    for stmt in statements:
        try:
            sql, _ = unpack_stmt(stmt)
        except Exception:
            return True  # malformed: let the oracle path raise its error
        if _leading_keyword(sql) in _TX_CONTROL:
            return True
    return False


class GroupAborted(Exception):
    """The group's OUTER transaction died (interrupt, disk error, a
    statement that terminated the transaction): savepoint-level
    recovery is impossible.  ``index`` is the batch whose statement
    surfaced the abort (None when the failure wasn't attributable to
    one batch); its caller gets ``error``.

    Usually the termination was a rollback — nothing committed — and
    every other batch is replayed through the per-transaction oracle
    path.  But a statement that COMMITTED the outer transaction
    mid-group (screening should prevent this; belt-and-braces) leaves
    the already-processed batches durable: those are finished in place
    (``Agent._recover_committed_group``) and listed in ``recovered`` as
    ``(version, db_version, last_seq, ts)`` entries so the abort path
    can still broadcast them — replaying them would double-apply."""

    def __init__(self, index: Optional[int], error: BaseException):
        super().__init__(f"write group aborted at batch {index}: {error!r}")
        self.index = index
        self.error = error
        self.recovered: List[tuple] = []


class WriteRequest:
    """One caller's buffered transaction: statements in, result or
    error out, ``done`` set exactly once by the group leader."""

    __slots__ = ("statements", "on_conn", "done", "result", "error",
                 "enqueued")

    def __init__(self, statements: Sequence, on_conn=None):
        self.statements = statements
        self.on_conn = on_conn
        self.done = threading.Event()
        self.result: Optional[dict] = None
        self.error: Optional[BaseException] = None
        # combiner queueing delay (corro_write_group_wait_seconds):
        # the front-door half of a change's end-to-end provenance lag
        self.enqueued = time.perf_counter()

    def finish(self) -> dict:
        """Block until the leader resolves this request; raise its
        error or return its result (the oracle's return shape)."""
        self.done.wait()
        if self.error is not None:
            raise self.error
        return self.result


class WriteCombiner:
    """Flat-combining queue in front of ``Agent._execute_write_group``."""

    def __init__(self, agent, max_group: int = 64):
        self._agent = agent
        self._cv = threading.Condition()
        self._q: "deque[WriteRequest]" = deque()
        self._draining = False
        self.max_group = max(1, int(max_group))

    def depth(self) -> int:
        """Requests queued but not yet claimed by a leader (the
        ``corro_write_queue_depth`` gauge)."""
        with self._cv:
            return len(self._q)

    def submit(self, statements: Sequence, on_conn=None) -> dict:
        """Enqueue one client transaction and wait for its group to
        commit.  The calling thread becomes the leader when no drain is
        in flight; otherwise it parks on the combiner's condition until
        a leader resolves its request — or until leadership frees up
        with its request still queued, in which case it takes over."""
        req = WriteRequest(statements, on_conn)
        with self._cv:
            self._q.append(req)
            while True:
                if req.done.is_set():
                    return req.finish()
                if not self._draining:
                    self._draining = True
                    break  # this thread leads
                # the timeout is pure paranoia: every done-setting path
                # notifies, so this only bounds the damage of a lost
                # wakeup to 1 s of latency instead of a hang
                self._cv.wait(timeout=1.0)
        group: List[WriteRequest] = []
        try:
            while True:
                with self._cv:
                    if not self._q:
                        break
                    group = [
                        self._q.popleft()
                        for _ in range(min(len(self._q), self.max_group))
                    ]
                now = time.perf_counter()
                # time parked awaiting a leader: the local queuing half
                # of a change's end-to-end convergence lag — recorded
                # for the whole group under ONE metrics-lock hold
                self._agent.metrics.histogram_keyed_many(
                    "corro_write_group_wait_seconds",
                    [((), max(0.0, now - r.enqueued)) for r in group],
                )
                self._agent._execute_write_group(group)
                group = []
                with self._cv:
                    self._cv.notify_all()
                if req.done.is_set():
                    # leadership hand-off: own write is durable — stop
                    # serving other clients' groups; the release below
                    # wakes a parked waiter to take over the remainder
                    break
        except BaseException:
            # _execute_write_group routes every failure into its
            # requests and never raises; this is the belt-and-braces
            # path for a truly unexpected error (e.g. interpreter
            # shutdown).  The in-flight group was already popped — no
            # future leader can reach it — so fail its unresolved
            # members (and our own request) before re-raising; requests
            # still queued are left for the next leader the release
            # below elects.
            for r in [*group, req]:
                if not r.done.is_set():
                    r.error = RuntimeError("write combiner leader died")
                    r.done.set()
            raise
        finally:
            with self._cv:
                self._draining = False
                self._cv.notify_all()
        return req.finish()
