"""Stable binary codec for primary keys and a total order over SQL values.

Parity targets:

* the reference packs multi-column primary keys into a single blob for
  wire transport and subscription bookkeeping
  (``crates/corro-types/src/pubsub.rs:2302-2449``);
* cr-sqlite's merge tie-break needs a total order over SQLite values
  ("biggest value wins", ``doc/crdts.md:13-16``).  Empirically (pinned by
  tests/test_crsqlite_golden.py against the vendored extension), cr-sqlite
  does NOT use SQLite's ORDER BY cross-type order: it compares the
  ``sqlite3_value_type`` enum first, where a *smaller* enum wins —
  INTEGER > FLOAT > TEXT > BLOB > NULL — and only compares
  numerically/bytewise within one type.

The codec here is our own format (tag byte + big-endian payload) chosen so
that packed blobs are self-describing and roundtrip exactly.
"""

from __future__ import annotations

import struct
from typing import Iterable, List

SqlValue = object  # None | int | float | str | bytes

_T_NULL = 0
_T_INT = 1
_T_REAL = 2
_T_TEXT = 3
_T_BLOB = 4


def _type_rank(v: SqlValue) -> int:
    """cr-sqlite tie-break rank: NULL < BLOB < TEXT < REAL < INTEGER.

    This is the inverse of the ``sqlite3_value_type`` enum (INTEGER=1,
    FLOAT=2, TEXT=3, BLOB=4, NULL=5): cr-sqlite's merge treats the value
    with the smaller type enum as "bigger".  Pinned empirically against
    the vendored extension in tests/test_crsqlite_golden.py — note this
    differs from SQLite's ORDER BY order (NULL < numeric < text < blob).
    """
    if v is None:
        return 0
    if isinstance(v, bool):
        return 4  # bools bind as INTEGER
    if isinstance(v, int):
        return 4
    if isinstance(v, float):
        return 3
    if isinstance(v, str):
        return 2
    if isinstance(v, (bytes, bytearray, memoryview)):
        return 1
    raise TypeError(f"unsupported SQL value: {type(v)!r}")


def value_cmp(a: SqlValue, b: SqlValue) -> int:
    """cr-sqlite merge-tie-break comparison (see :func:`_type_rank`)."""
    ra, rb = _type_rank(a), _type_rank(b)
    if ra != rb:
        return -1 if ra < rb else 1
    if ra == 0:
        return 0
    if ra in (3, 4):
        return (a > b) - (a < b)
    if ra == 2:
        ab, bb = a.encode("utf-8"), b.encode("utf-8")
        return (ab > bb) - (ab < bb)
    ab, bb = bytes(a), bytes(b)
    return (ab > bb) - (ab < bb)


def jsonable_row(row: Iterable) -> List:
    """Coerce a SQL result row for JSON transport (bytes -> hex)."""
    out: List = []
    for v in row:
        if isinstance(v, (bytes, bytearray, memoryview)):
            out.append(bytes(v).hex())
        else:
            out.append(v)
    return out


def pack_values(values: Iterable[SqlValue]) -> bytes:
    """Pack a tuple of SQL values into one self-describing blob."""
    out = bytearray()
    for v in values:
        if v is None:
            out.append(_T_NULL)
        elif isinstance(v, bool):
            out.append(_T_INT)
            out += struct.pack(">q", int(v))
        elif isinstance(v, int):
            out.append(_T_INT)
            try:
                out += struct.pack(">q", v)
            except struct.error:
                # same exception type as the native kernel
                raise OverflowError("int too large for packed i64") from None
        elif isinstance(v, float):
            out.append(_T_REAL)
            out += struct.pack(">d", v)
        elif isinstance(v, str):
            b = v.encode("utf-8")
            out.append(_T_TEXT)
            out += struct.pack(">I", len(b)) + b
        elif isinstance(v, (bytes, bytearray, memoryview)):
            b = bytes(v)
            out.append(_T_BLOB)
            out += struct.pack(">I", len(b)) + b
        else:
            raise TypeError(f"unsupported SQL value: {type(v)!r}")
    return bytes(out)


def unpack_values(blob: bytes) -> List[SqlValue]:
    """Inverse of :func:`pack_values`."""
    out: List[SqlValue] = []
    i = 0
    n = len(blob)
    while i < n:
        tag = blob[i]
        i += 1
        if tag == _T_NULL:
            out.append(None)
        elif tag == _T_INT:
            if i + 8 > n:
                raise ValueError("truncated packed value")
            (v,) = struct.unpack_from(">q", blob, i)
            i += 8
            out.append(v)
        elif tag == _T_REAL:
            if i + 8 > n:
                raise ValueError("truncated packed value")
            (v,) = struct.unpack_from(">d", blob, i)
            i += 8
            out.append(v)
        elif tag in (_T_TEXT, _T_BLOB):
            if i + 4 > n:
                raise ValueError("truncated packed value")
            (ln,) = struct.unpack_from(">I", blob, i)
            i += 4
            raw = blob[i : i + ln]
            if len(raw) != ln:
                raise ValueError("truncated packed value")
            i += ln
            out.append(raw.decode("utf-8") if tag == _T_TEXT else raw)
        else:
            raise ValueError(f"bad tag {tag} at offset {i-1}")
    return out


# keep the Python twins importable for cross-checking, then prefer the
# native kernels (corrosion_tpu/native) — these run inside the CRR
# triggers on every row write, so the constant factor matters
_py_pack_values = pack_values
_py_unpack_values = unpack_values
_py_value_cmp = value_cmp

from corrosion_tpu.native import load_or_none as _load_native

_native = _load_native()
if _native is not None:
    pack_values = _native.pack_values  # type: ignore[assignment]
    unpack_values = _native.unpack_values  # type: ignore[assignment]
    value_cmp = _native.value_cmp  # type: ignore[assignment]
