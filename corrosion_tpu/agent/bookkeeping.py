"""Per-actor version bookkeeping: what we have, what's missing, what's
partially buffered, what's been cleared.

Parity: ``crates/corro-types/src/agent.rs`` — ``BookedVersions`` (needed
gaps as a range set, ``partials`` map, ``max``, ``last_cleared_ts``;
``agent.rs:1393-1578``), the ``VersionsSnapshot::insert_db`` gap-collapse
algorithm (``agent.rs:1231-1367``), ``store_empty_changeset`` cleared-range
merging (``corro-types/src/change.rs:314-436``), and the
``__corro_bookkeeping`` / ``__corro_seq_bookkeeping`` /
``__corro_buffered_changes`` / ``__corro_bookkeeping_gaps`` tables
(``agent.rs:430-512``).

Design: one ``Bookie`` owns a map actor → ``BookedVersions``; each
``BookedVersions`` keeps exact in-memory range sets (our
:class:`corrosion_tpu.utils.ranges.RangeSet`) and persists through the
same sqlite connection as the storage engine, so a bookkeeping update
commits atomically with the change application that caused it.  Restart =
resume: everything rebuilds from the tables.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from corrosion_tpu.types.hlc import Timestamp
from corrosion_tpu.utils.ranges import RangeSet


@dataclass
class PartialVersion:
    """A version whose seq-chunks are still being assembled."""

    seqs: RangeSet = field(default_factory=RangeSet)
    last_seq: int = 0
    ts: Optional[Timestamp] = None

    def is_complete(self) -> bool:
        return self.seqs.contains_span(0, self.last_seq)

    def gaps(self) -> List[Tuple[int, int]]:
        return self.seqs.gaps(0, self.last_seq)


class BookedVersions:
    """One remote (or local) actor's version ledger."""

    def __init__(self, actor_id: bytes, on_mutate=None):
        self.actor_id = actor_id
        self.needed = RangeSet()  # versions we know exist but don't have
        self.partials: Dict[int, PartialVersion] = {}
        self.cleared = RangeSet()  # versions cleared/overwritten (empty)
        # version -> (db_version, last_seq) for locally-applied versions
        self.versions: Dict[int, Tuple[int, int]] = {}
        self.max_version: int = 0
        self.last_cleared_ts: Optional[Timestamp] = None
        # snapshot floor (docs/sync.md): versions 1..=snap_floor are
        # fully reflected in current table state, and their per-version
        # bookkeeping has been COMPACTED away — they can only be
        # obtained from this node via snapshot install, never
        # change-by-change.  Advanced by maintenance-driven history
        # compaction (runtime._advance_snapshot_floors)
        self.snap_floor: int = 0
        # dirty-flag hook (Bookie.gen): every mutation that can change a
        # generate_sync snapshot reports upward so the runtime can cache
        # the snapshot between bookkeeping changes
        self._on_mutate = on_mutate

    def _touch(self) -> None:
        if self._on_mutate is not None:
            self._on_mutate()

    # -- queries ---------------------------------------------------------

    def last(self) -> int:
        return self.max_version

    def contains_version(self, v: int) -> bool:
        """Do we fully have v (applied, known-cleared, or below the
        compacted snapshot floor)?"""
        if v > self.max_version:
            return False
        if v <= self.snap_floor:
            # the floor only ever advances over a fully-contained
            # prefix, so everything at or below it is held by contract
            return True
        if self.needed.contains(v):
            return False
        if v in self.partials:
            return False
        return True

    def contains_range(self, start: int, end: int) -> bool:
        return all(self.contains_version(v) for v in range(start, end + 1))

    def db_version_for(self, v: int) -> Optional[int]:
        entry = self.versions.get(v)
        return entry[0] if entry else None

    # -- mutation --------------------------------------------------------

    def _extend_max(self, version: int) -> None:
        """Seeing version v implies 1..v exist; anything between our old
        max and v that we didn't just get becomes a gap (insert_db
        semantics)."""
        if version > self.max_version:
            if version > self.max_version + 1:
                self.needed.insert(self.max_version + 1, version - 1)
            self.max_version = version

    def apply_version(
        self,
        version: int,
        db_version: int,
        last_seq: int,
        ts: Optional[Timestamp] = None,
    ) -> None:
        """A complete version has been applied to storage."""
        self._extend_max(version)
        self.needed.remove(version, version)
        self.partials.pop(version, None)
        self.versions[version] = (db_version, last_seq)
        self._touch()

    def mark_cleared(self, start: int, end: int) -> None:
        """Versions [start, end] are empty (overwritten or compacted).

        Does NOT advance ``last_cleared_ts``: the watermark moves only on
        *complete* information — our own compaction, or a whole sync
        EmptySet group — via :meth:`update_cleared_ts`.  A single
        broadcast empty changeset may be one of several ranges stamped
        with the same ts, so advancing here would make the sync
        Empty-need gate skip the rest forever (ref ``agent.rs:1541-1545``
        — the reference's watermark is likewise separate from clearing)."""
        self._extend_max(end)
        self.needed.remove(start, end)
        # iterate entries present, never the (remote-supplied) span width
        for v in [v for v in self.partials if start <= v <= end]:
            del self.partials[v]
        for v in [v for v in self.versions if start <= v <= end]:
            del self.versions[v]
        self.cleared.insert(start, end)
        self._touch()

    def update_cleared_ts(self, ts: Timestamp) -> None:
        """Advance the cleared watermark (``agent.rs:1541-1545``)."""
        if self.last_cleared_ts is None or int(ts) > int(self.last_cleared_ts):
            self.last_cleared_ts = ts
            self._touch()

    def insert_partial(
        self,
        version: int,
        seqs: Tuple[int, int],
        last_seq: int,
        ts: Optional[Timestamp] = None,
    ) -> PartialVersion:
        """Buffer a seq-range chunk of a large version; returns the
        partial (check ``is_complete`` to promote)."""
        self._extend_max(version)
        self.needed.remove(version, version)
        partial = self.partials.get(version)
        if partial is None:
            partial = self.partials[version] = PartialVersion(
                last_seq=last_seq, ts=ts
            )
        partial.last_seq = max(partial.last_seq, last_seq)
        if ts is not None:
            partial.ts = ts
        partial.seqs.insert(seqs[0], seqs[1])
        self._touch()
        return partial

    def contained_prefix(self) -> int:
        """The largest F with versions 1..=F all fully held (applied or
        cleared; no gaps, no partials) — the ceiling a snapshot floor
        may advance to."""
        hi = self.max_version
        spans = self.needed.spans()
        if spans:
            hi = min(hi, spans[0][0] - 1)
        for v in self.partials:
            if v <= hi:
                hi = v - 1
        return max(hi, 0)

    def set_snap_floor(self, floor: int) -> None:
        """Advance the snapshot floor, dropping the per-version
        in-memory ledger it compacts (the persisted rows go in the
        same transaction via ``Bookie.compact_below_floor``)."""
        if floor <= self.snap_floor:
            return
        self.snap_floor = floor
        if floor > self.max_version:
            self.max_version = floor
        for v in [v for v in self.versions if v <= floor]:
            del self.versions[v]
        for v in [v for v in self.partials if v <= floor]:
            del self.partials[v]
        # the floor only advances over a contained prefix, so this is
        # belt-and-braces against a reloaded inconsistent ledger
        self.needed.remove(1, floor)
        self._touch()

    # -- sync handshake feed ---------------------------------------------

    def needed_spans(self) -> List[Tuple[int, int]]:
        return self.needed.spans()

    def partial_needs(self) -> Dict[int, List[Tuple[int, int]]]:
        return {
            v: p.gaps() for v, p in self.partials.items() if not p.is_complete()
        }


class Bookie:
    """actor → BookedVersions, with sqlite persistence."""

    TABLES = """
CREATE TABLE IF NOT EXISTS __corro_bookkeeping (
  actor_id BLOB NOT NULL,
  start_version INTEGER NOT NULL,
  end_version INTEGER,          -- set => cleared range [start, end]
  db_version INTEGER,           -- set => concrete applied version
  last_seq INTEGER,
  ts INTEGER,
  PRIMARY KEY (actor_id, start_version)
);
CREATE TABLE IF NOT EXISTS __corro_seq_bookkeeping (
  actor_id BLOB NOT NULL,
  version INTEGER NOT NULL,
  start_seq INTEGER NOT NULL,
  end_seq INTEGER NOT NULL,
  last_seq INTEGER NOT NULL,
  ts INTEGER,
  PRIMARY KEY (actor_id, version, start_seq)
);
CREATE TABLE IF NOT EXISTS __corro_buffered_changes (
  actor_id BLOB NOT NULL,
  version INTEGER NOT NULL,
  seq INTEGER NOT NULL,
  change BLOB NOT NULL,
  PRIMARY KEY (actor_id, version, seq)
);
CREATE TABLE IF NOT EXISTS __corro_bookkeeping_gaps (
  actor_id BLOB NOT NULL,
  start INTEGER NOT NULL,
  end INTEGER NOT NULL,
  PRIMARY KEY (actor_id, start)
);
CREATE TABLE IF NOT EXISTS __corro_sync_state (
  actor_id BLOB PRIMARY KEY NOT NULL,
  last_cleared_ts INTEGER
);
CREATE TABLE IF NOT EXISTS __corro_snap_floors (
  actor_id BLOB PRIMARY KEY NOT NULL,
  floor INTEGER NOT NULL,
  ts INTEGER
);
"""

    def __init__(self, conn, lock: Optional[threading.RLock] = None):
        """conn: a sqlite3 connection (shared with the storage engine so
        commits are atomic with change application)."""
        self.conn = conn
        self._lock = lock or threading.RLock()
        with self._lock:
            conn.executescript(self.TABLES)
        self._actors: Dict[bytes, BookedVersions] = {}
        self._persisted_gaps: Dict[bytes, set] = {}
        # bookkeeping generation: bumped by every in-memory mutation
        # (any BookedVersions change, new actors, restores).  The
        # runtime caches its generate_sync snapshot against this, so
        # inbound sync handshakes stop re-walking every actor's
        # RangeSets when nothing changed.  Mutations happen under the
        # storage lock; readers compare under the same lock.
        self.gen = 0
        self._load()

    def _bump_gen(self) -> None:
        self.gen += 1

    # -- persistence -----------------------------------------------------

    def _load(self) -> None:
        with self._lock:
            for actor, start, end, dbv, last_seq, ts in self.conn.execute(
                "SELECT actor_id, start_version, end_version, db_version,"
                " last_seq, ts FROM __corro_bookkeeping"
            ):
                bv = self.for_actor(bytes(actor))
                if end is not None:
                    bv.mark_cleared(start, end)
                else:
                    bv.apply_version(
                        start, dbv or 0, last_seq or 0,
                        Timestamp(ts) if ts else None,
                    )
            for actor, version, s, e, last_seq, ts in self.conn.execute(
                "SELECT actor_id, version, start_seq, end_seq, last_seq, ts"
                " FROM __corro_seq_bookkeeping"
            ):
                bv = self.for_actor(bytes(actor))
                bv.insert_partial(
                    version, (s, e), last_seq, Timestamp(ts) if ts else None
                )
            for actor, start, end in self.conn.execute(
                "SELECT actor_id, start, end FROM __corro_bookkeeping_gaps"
            ):
                bv = self.for_actor(bytes(actor))
                bv.needed.insert(start, end)
                bv.max_version = max(bv.max_version, end)
            for actor, ts in self.conn.execute(
                "SELECT actor_id, last_cleared_ts FROM __corro_sync_state"
            ):
                if ts is not None:
                    self.for_actor(bytes(actor)).update_cleared_ts(
                        Timestamp(ts)
                    )
            for actor, floor in self.conn.execute(
                "SELECT actor_id, floor FROM __corro_snap_floors"
            ):
                # the floor record re-extends max_version: the concrete
                # rows below it were compacted away, so without it a
                # reloaded ledger would under-report the actor's head
                self.for_actor(bytes(actor)).set_snap_floor(int(floor))

    def backfill_own_sync_state(self, actor_id: bytes) -> None:
        """Restore OUR OWN cleared watermark from cleared-row timestamps
        when ``__corro_sync_state`` has no row (a DB written before the
        table existed).  Sound only for our own actor: our persisted
        cleared set is always complete information, while a remote
        actor's rows may be a subset of a ts group."""
        bv = self.for_actor(actor_id)
        if bv.last_cleared_ts is not None:
            return
        with self._lock:
            row = self.conn.execute(
                "SELECT MAX(ts) FROM __corro_bookkeeping "
                "WHERE actor_id=? AND end_version IS NOT NULL",
                (actor_id,),
            ).fetchone()
            if row and row[0] is not None:
                bv.update_cleared_ts(Timestamp(row[0]))
                self.persist_sync_state(actor_id, int(row[0]))

    def persist_version(
        self, actor_id: bytes, version: int, db_version: int, last_seq: int,
        ts: Optional[int] = None,
    ) -> None:
        """Write-through for apply_version (call inside the storage tx)."""
        self.conn.execute(
            "INSERT OR REPLACE INTO __corro_bookkeeping "
            "(actor_id, start_version, end_version, db_version, last_seq, ts)"
            " VALUES (?, ?, NULL, ?, ?, ?)",
            (actor_id, version, db_version, last_seq, ts),
        )
        self._persist_gaps(actor_id)

    def persist_versions(
        self, actor_id: bytes,
        rows: List[Tuple[int, int, int, Optional[int]]],
    ) -> None:
        """Batch write-through for several applied versions of one actor
        (the merged apply-transaction AND group-commit write paths): one
        executemany + ONE gap diff instead of a per-version
        write-through.  ``rows`` is ``(version, db_version, last_seq,
        ts)`` tuples; call inside the storage tx.  The gap diff reads
        the current in-memory needed set — for the LOCAL actor's
        group-commit writes that set is untouched by sequential version
        assignment, so calling before the in-memory ``apply_version``
        (which a commit-after-persist ordering requires) is sound; for
        merged remote applies call after them, as before."""
        if not rows:
            return
        self.conn.executemany(
            "INSERT OR REPLACE INTO __corro_bookkeeping "
            "(actor_id, start_version, end_version, db_version, last_seq, ts)"
            " VALUES (?, ?, NULL, ?, ?, ?)",
            [(actor_id, v, dbv, last_seq, ts)
             for v, dbv, last_seq, ts in rows],
        )
        self._persist_gaps(actor_id)

    def persist_cleared(self, actor_id: bytes, start: int, end: int,
                        ts: Optional[int] = None) -> None:
        """store_empty_changeset: merge with overlapping/adjacent cleared
        ranges instead of stacking rows."""
        rows = self.conn.execute(
            "SELECT start_version, end_version, ts FROM __corro_bookkeeping "
            "WHERE actor_id=? AND end_version IS NOT NULL "
            "AND start_version <= ? AND end_version >= ?",
            (actor_id, end + 1, start - 1),
        ).fetchall()
        lo, hi = start, end
        keep_ts = ts
        for s, e, row_ts in rows:
            lo, hi = min(lo, s), max(hi, e)
            if row_ts is not None and (keep_ts is None or row_ts > keep_ts):
                keep_ts = row_ts
            self.conn.execute(
                "DELETE FROM __corro_bookkeeping WHERE actor_id=? "
                "AND start_version=?",
                (actor_id, s),
            )
        # concrete rows swallowed by the cleared range go away too
        self.conn.execute(
            "DELETE FROM __corro_bookkeeping WHERE actor_id=? "
            "AND end_version IS NULL AND start_version BETWEEN ? AND ?",
            (actor_id, lo, hi),
        )
        self.conn.execute(
            "INSERT OR REPLACE INTO __corro_bookkeeping "
            "(actor_id, start_version, end_version, db_version, last_seq, ts)"
            " VALUES (?, ?, ?, NULL, NULL, ?)",
            (actor_id, lo, hi, keep_ts),
        )
        self._persist_gaps(actor_id)

    def persist_partial(
        self, actor_id: bytes, version: int, seqs: Tuple[int, int],
        last_seq: int, ts: Optional[int] = None,
    ) -> None:
        self.conn.execute(
            "INSERT OR REPLACE INTO __corro_seq_bookkeeping "
            "(actor_id, version, start_seq, end_seq, last_seq, ts) "
            "VALUES (?, ?, ?, ?, ?, ?)",
            (actor_id, version, seqs[0], seqs[1], last_seq, ts),
        )
        self._persist_gaps(actor_id)

    def clear_partial(self, actor_id: bytes, version: int) -> None:
        self.clear_partials(actor_id, [version])

    def clear_partials(self, actor_id: bytes, versions: List[int]) -> None:
        """Batch variant of :meth:`clear_partial` (merged apply path)."""
        rows = [(actor_id, v) for v in versions]
        self.conn.executemany(
            "DELETE FROM __corro_seq_bookkeeping WHERE actor_id=? AND version=?",
            rows,
        )
        self.conn.executemany(
            "DELETE FROM __corro_buffered_changes WHERE actor_id=? AND version=?",
            rows,
        )

    def _persist_gaps(self, actor_id: bytes) -> None:
        """Differential write-through: only spans that changed are touched
        (a naive delete-all/rewrite amplifies every sync catch-up step)."""
        bv = self.for_actor(actor_id)
        new = set(bv.needed.spans())
        old = self._persisted_gaps.get(actor_id)
        if old is None:
            old = {
                (s, e)
                for s, e in self.conn.execute(
                    "SELECT start, end FROM __corro_bookkeeping_gaps "
                    "WHERE actor_id=?",
                    (actor_id,),
                )
            }
        if new == old:
            self._persisted_gaps[actor_id] = new
            return
        self.conn.executemany(
            "DELETE FROM __corro_bookkeeping_gaps WHERE actor_id=? AND start=?",
            [(actor_id, s) for s, e in old - new],
        )
        self.conn.executemany(
            "INSERT OR REPLACE INTO __corro_bookkeeping_gaps "
            "(actor_id, start, end) VALUES (?, ?, ?)",
            [(actor_id, s, e) for s, e in new - old],
        )
        self._persisted_gaps[actor_id] = new

    def persist_sync_state(self, actor_id: bytes, ts: int) -> None:
        """Write-through for ``update_cleared_ts`` (``agent.rs:1292-1300``
        — the watermark lives in its own ``__corro_sync_state`` table, it
        is never inferred from cleared-range row timestamps)."""
        self.conn.execute(
            "INSERT INTO __corro_sync_state (actor_id, last_cleared_ts) "
            "VALUES (?, ?) ON CONFLICT (actor_id) DO UPDATE SET "
            "last_cleared_ts = MAX(COALESCE(last_cleared_ts, 0),"
            " excluded.last_cleared_ts)",
            (actor_id, int(ts)),
        )

    def persist_floor(self, actor_id: bytes, floor: int,
                      ts: Optional[int] = None) -> None:
        """Write-through for a snapshot-floor advance (call inside the
        same transaction as :meth:`compact_below_floor`)."""
        self.conn.execute(
            "INSERT INTO __corro_snap_floors (actor_id, floor, ts) "
            "VALUES (?, ?, ?) ON CONFLICT (actor_id) DO UPDATE SET "
            "floor = MAX(floor, excluded.floor), ts = excluded.ts",
            (actor_id, int(floor), ts),
        )

    def compact_below_floor(self, actor_id: bytes, floor: int) -> int:
        """History compaction: delete the per-version bookkeeping this
        floor advance subsumes — concrete applied rows, partial seq
        rows, and buffered chunks at or below ``floor``.  Cleared-range
        rows are KEPT (they are already compact spans, and the
        EmptySet/watermark serving path still reads them).  Returns
        rows deleted; call inside the floor-advance transaction."""
        deleted = 0
        cur = self.conn.execute(
            "DELETE FROM __corro_bookkeeping WHERE actor_id=? "
            "AND end_version IS NULL AND start_version <= ?",
            (actor_id, int(floor)),
        )
        deleted += cur.rowcount
        for table in ("__corro_seq_bookkeeping", "__corro_buffered_changes"):
            cur = self.conn.execute(
                f"DELETE FROM {table} WHERE actor_id=? AND version <= ?",
                (actor_id, int(floor)),
            )
            deleted += cur.rowcount
        return deleted

    def reload(self, conn) -> None:
        """Rebuild the whole in-memory ledger from ``conn`` — the
        post-snapshot-install path: the database file was atomically
        swapped, so every actor's state re-derives from the installed
        tables.  The Bookie OBJECT survives (everything holding a
        reference keeps working); only its contents change."""
        self.conn = conn
        with self._lock:
            conn.executescript(self.TABLES)
            self._actors.clear()
            self._persisted_gaps.clear()
            self._bump_gen()
            self._load()

    def version_ts(self, actor_id: bytes, version: int) -> Optional[int]:
        """The HLC ts recorded when ``version`` was applied (the sync
        server stamps re-served Full changesets with it, like the
        reference reads ts back from ``__corro_bookkeeping``)."""
        with self._lock:
            row = self.conn.execute(
                "SELECT ts FROM __corro_bookkeeping WHERE actor_id=? "
                "AND start_version=? AND end_version IS NULL",
                (actor_id, version),
            ).fetchone()
        return row[0] if row else None

    _TS_CHUNK = 500  # bound parameters per IN (...) query

    def version_ts_many(
        self, actor_id: bytes, versions: List[int], conn=None,
    ) -> Dict[int, int]:
        """Batch variant of :meth:`version_ts`: one chunked ``IN (...)``
        query for a whole serve-range's versions instead of a point
        query each.  ``conn`` (e.g. a read-only pool connection) skips
        the storage lock — bookkeeping rows are committed data."""
        out: Dict[int, int] = {}

        def _run(c) -> None:
            for i in range(0, len(versions), self._TS_CHUNK):
                chunk = versions[i : i + self._TS_CHUNK]
                qs = ",".join("?" * len(chunk))
                for v, ts in c.execute(
                    "SELECT start_version, ts FROM __corro_bookkeeping "
                    "WHERE actor_id=? AND end_version IS NULL "
                    f"AND start_version IN ({qs})",
                    [actor_id, *chunk],
                ):
                    if ts is not None:
                        out[v] = ts

        if conn is not None:
            _run(conn)
        else:
            with self._lock:
                _run(self.conn)
        return out

    def cleared_since(
        self, actor_id: bytes, since_ts: Optional[int] = None
    ) -> List[Tuple[int, List[Tuple[int, int]]]]:
        """Cleared ranges strictly newer than ``since_ts``, grouped by
        the timestamp that stamped them, oldest group first (the sync
        Empty-need serving shape — ``peer.rs:715-762`` sends one EmptySet
        per distinct ts so the requester can advance its watermark one
        *complete* group at a time)."""
        with self._lock:
            sql = (
                "SELECT ts, start_version, end_version "
                "FROM __corro_bookkeeping "
                "WHERE actor_id=? AND end_version IS NOT NULL"
            )
            args: List = [actor_id]
            if since_ts is not None:
                sql += " AND ts > ?"
                args.append(int(since_ts))
            sql += " ORDER BY ts"
            groups: List[Tuple[int, List[Tuple[int, int]]]] = []
            for ts, s, e in self.conn.execute(sql, args).fetchall():
                ts = ts or 0
                if groups and groups[-1][0] == ts:
                    groups[-1][1].append((s, e))
                else:
                    groups.append((ts, [(s, e)]))
            return groups

    # -- buffered changes (partial version assembly) ---------------------

    def buffer_change(self, actor_id: bytes, version: int, seq: int,
                      blob: bytes) -> None:
        self.conn.execute(
            "INSERT OR REPLACE INTO __corro_buffered_changes "
            "(actor_id, version, seq, change) VALUES (?, ?, ?, ?)",
            (actor_id, version, seq, blob),
        )

    def buffer_changes(
        self, actor_id: bytes, version: int,
        rows: List[Tuple[int, bytes]],
    ) -> None:
        """Batch variant of :meth:`buffer_change`: one executemany for a
        whole partial chunk's ``(seq, blob)`` rows."""
        self.conn.executemany(
            "INSERT OR REPLACE INTO __corro_buffered_changes "
            "(actor_id, version, seq, change) VALUES (?, ?, ?, ?)",
            [(actor_id, version, seq, blob) for seq, blob in rows],
        )

    def buffered_changes(self, actor_id: bytes, version: int,
                         conn=None) -> List[Tuple[int, bytes]]:
        """Buffered seq chunks of a partial version.  ``conn`` lets the
        off-loop sync server read through a pooled RO connection."""
        c = conn if conn is not None else self.conn
        return [
            (seq, bytes(blob))
            for seq, blob in c.execute(
                "SELECT seq, change FROM __corro_buffered_changes "
                "WHERE actor_id=? AND version=? ORDER BY seq",
                (actor_id, version),
            )
        ]

    # -- transactional snapshot (merged-apply failure recovery) ----------

    def snapshot_actor(self, actor_id: bytes) -> tuple:
        """Copy one actor's in-memory version state.  Paired with
        :meth:`restore_actor` around a multi-changeset transaction: if
        the tx rolls back after ``apply_version`` calls, memory must be
        rolled back too, or the lost versions read as already-applied
        and are never re-fetched until restart."""
        bv = self.for_actor(actor_id)
        needed = RangeSet()
        for s, e in bv.needed.spans():
            needed.insert(s, e)
        return (needed, dict(bv.partials), dict(bv.versions), bv.max_version)

    def restore_actor(self, actor_id: bytes, snapshot: tuple) -> None:
        bv = self.for_actor(actor_id)
        bv.needed, bv.partials, bv.versions, bv.max_version = snapshot
        # the gap write-through cache may now disagree with the rolled-
        # back DB rows: drop it so the next diff re-reads the table
        self._persisted_gaps.pop(actor_id, None)
        self._bump_gen()

    # -- access ----------------------------------------------------------

    def for_actor(self, actor_id: bytes) -> BookedVersions:
        bv = self._actors.get(actor_id)
        if bv is None:
            bv = self._actors[actor_id] = BookedVersions(
                actor_id, on_mutate=self._bump_gen
            )
            self._bump_gen()
        return bv

    def actors(self) -> Dict[bytes, BookedVersions]:
        return dict(self._actors)
