"""Deterministic discrete-event mode for the agent runtime.

The north star (BASELINE.json) has two halves: the 100k-node epidemic
under 60 s (performance — ``bench.py``) and **bit-matching** the real
agent cluster at N≤256.  The distributional calibration
(``sim/simdiff.py``) compares percentiles; this module makes the
comparison *exact*: the real agents run under a seeded PRNG and a
discrete-event **tick scheduler** instead of wall-clock timers, so the
epidemic's delivery schedule is a pure function of (seed, parameters) —
and the simulator's deterministic replay (``sim/bitmatch.py``) must
reproduce the per-tick infected sets and per-node message counts
**exactly**, tick for tick.

What is real here (the whole point): agents are full ``Agent`` objects —
real SQLite storage with CRR triggers, real bookkeeping, real speedy
wire bytes (``encode_broadcast_frame``/``decode_uni_frame``, the same
methods the live socket loops use), real ``handle_change`` ingest with
seen-cache dedup and rebroadcast-on-learn, real ``Members.sample`` peer
selection.  What the scheduler replaces is exactly the *timing layer*:
sockets become synchronous frame hand-offs, and the broadcast loop's
wall-clock arithmetic (``rebroadcast_delay * send_count`` requeues,
``broadcast/mod.rs:745-765``) becomes tick arithmetic
(``det_backoff_gap``), the same mapping the simulator's
``backoff_ticks`` models.

Tick semantics (matching ``models/broadcast.py`` with ``track_sent``):

* a tick has a **send phase** — every agent, in index order, flushes
  its due payloads, sampling fanout targets from its own seeded PRNG
  with per-payload ``sent_to`` exclusion — and a **delivery phase** —
  all frames sent this tick are decoded and applied; deliveries never
  influence sends of the same tick (synchronous rounds);
* a payload learned during tick t's delivery phase is first eligible to
  forward at tick t+1;
* the nth retransmission of a payload waits ``det_backoff_gap(n)``
  ticks; a payload whose eligible-peer set is exhausted retires.

Cited reference behavior: fanout sampling and sent_to exclusion
``crates/corro-agent/src/broadcast/mod.rs:586-702``, retransmit requeue
``:745-765``, rebroadcast-on-learn ``handlers.rs:939-949``.
"""

from __future__ import annotations

import asyncio
import random
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from corrosion_tpu.agent.runtime import Agent, AgentConfig, ChangeSource
from corrosion_tpu.agent.testing import TEST_SCHEMA
from corrosion_tpu.bridge import speedy


def det_seed_for(seed: int, index: int) -> int:
    """Per-node PRNG stream seed — shared with the sim replay so both
    sides draw identical sample sequences."""
    return seed * 1_000_003 + index


def det_backoff_gap(backoff_ticks: float, send_count: int) -> int:
    """Ticks until a payload's next retransmission after its nth send —
    the tick-grid form of the reference's ``100ms * send_count`` requeue
    (and our live loop's ``rebroadcast_delay * send_count``); shared
    with the sim replay."""
    return max(1, round(backoff_ticks * send_count))


class _SyncLoop:
    """Stand-in event loop for un-started agents: callbacks run inline,
    synchronously — the discrete-event scheduler owns all ordering."""

    def call_soon_threadsafe(self, fn, *args):
        fn(*args)

    def time(self) -> float:
        return 0.0


@dataclass
class _Entry:
    """One pending broadcast payload on one agent (the det-mode form of
    the live loop's ``pending`` tuples)."""

    cv: object
    frame: bytes
    remaining: int
    next_due: int
    sent_to: Set[bytes] = field(default_factory=set)


@dataclass(frozen=True)
class DetParams:
    n_nodes: int
    fanout: int = 3
    max_transmissions: int = 5
    backoff_ticks: float = 2.5
    seed: int = 0
    max_ticks: int = 512
    # headline-protocol extensions (all off by default so the base
    # fanout+backoff bit-match keeps its original shape):
    # per-message delivery drop probability; drawn from the SENDER's
    # PRNG stream, one uniform per target in sample order — the sender
    # still records sent_to and counts the message (it cannot know the
    # frame died downstream), so loss is healed by retransmissions and
    # anti-entropy, exactly the live semantics
    loss: float = 0.0
    # >0 enables ring0-first fanout: peers in the same aligned block of
    # this width are the <6ms RTT tier (rtt 1ms vs 50ms elsewhere) —
    # the deterministic form of the sim kernel's contiguous-block ring0
    ring0_size: int = 0
    # >0 runs one anti-entropy round (every agent, index order) at the
    # end of each tick t where t % sync_interval == sync_interval - 1,
    # matching the JAX kernel's cadence (sim/epidemic.py epidemic_tick)
    sync_interval: int = 0
    sync_peers: int = 3


RING0_RTT_MS = 1.0
FAR_RTT_MS = 50.0


class _CollectWriter:
    """Fake StreamWriter: collects the frames _serve_need writes."""

    def __init__(self):
        self.chunks: List[bytes] = []

    def write(self, b: bytes) -> None:
        self.chunks.append(b)

    async def drain(self) -> None:
        pass

    def close(self) -> None:
        pass


class DetCluster:
    """N real agents under the discrete-event tick scheduler."""

    def __init__(self, params: DetParams, base_dir: Optional[str] = None):
        self.params = params
        self._own_dir = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="corro-det-")
        self.agents: List[Agent] = []
        for i in range(params.n_nodes):
            cfg = AgentConfig(
                db_path=f"{self.base_dir}/n{i}.db",
                schema_sql=TEST_SCHEMA,
                fanout=params.fanout,
                max_transmissions=params.max_transmissions,
                sync_peers=params.sync_peers,
                subs_enabled=False,
                ring0_enabled=params.ring0_size > 0,
                debug_hops=False,
            )
            a = Agent(cfg)
            # the deterministic PRNG stream replaces the actor-id seed;
            # _SyncLoop makes queue-or-defer paths run inline
            a._rng = random.Random(det_seed_for(params.seed, i))
            a._loop = _SyncLoop()
            self.agents.append(a)
        # full static membership in index order: every agent's members
        # dict — and therefore Members.sample's population ordering —
        # lists peers in ascending node index (the sim replay mirrors
        # this exact ordering)
        for a in self.agents:
            for b in self.agents:
                if a is not b:
                    a.members.upsert(b.actor_id, ("det", 0))
        if params.ring0_size > 0:
            # deterministic RTT tiers: same aligned block → ring0
            for i, a in enumerate(self.agents):
                for j, b in enumerate(self.agents):
                    if a is b:
                        continue
                    same = i // params.ring0_size == j // params.ring0_size
                    a.members.record_rtt(
                        b.actor_id, RING0_RTT_MS if same else FAR_RTT_MS
                    )
        self._index_of: Dict[bytes, int] = {
            a.actor_id: i for i, a in enumerate(self.agents)
        }
        self._entries: List[Dict[tuple, _Entry]] = [
            {} for _ in range(params.n_nodes)
        ]
        self.msgs = [0] * params.n_nodes
        self.sync_msgs = [0] * params.n_nodes
        self.tick_no = 0

    # -- workload ------------------------------------------------------

    def write(self, origin: int, sql: str, args: tuple = ()) -> int:
        """One local write on ``origin``; returns its version.  The
        broadcast enters origin's queue and first flushes on the next
        ``tick()`` (same next-flush latency the live loop gives a fresh
        payload)."""
        res = self.agents[origin].execute_transaction([(sql, args)])
        return res["version"]

    # -- the scheduler -------------------------------------------------

    def _drain_queues(self) -> None:
        """Queued broadcasts (local writes + rebroadcasts-on-learn from
        the previous delivery phase) become due entries this tick."""
        for i, a in enumerate(self.agents):
            while not a._bcast_queue.empty():
                cv, remaining, hop, tp, sig = a._bcast_queue.get_nowait()
                key = a._seen_key(cv)
                if key in self._entries[i]:
                    continue
                self._entries[i][key] = _Entry(
                    cv=cv,
                    frame=a.encode_broadcast_frame(cv, hop, tp, sig),
                    remaining=remaining,
                    next_due=self.tick_no,
                )

    def tick(self) -> int:
        """One protocol round; returns the number of messages sent."""
        p = self.params
        t = self.tick_no
        self._drain_queues()
        deliveries: List[Tuple[int, bytes]] = []
        for i, a in enumerate(self.agents):
            entries = self._entries[i]
            for key in list(entries):
                e = entries[key]
                if e.next_due > t or e.remaining < 1:
                    continue
                # ring0-first exactly when the live loop does: a LOCAL
                # payload's first transmission (runtime.py flush():
                # ring0_enabled and local and not sent_to)
                local = e.cv.actor_id.bytes == a.actor_id
                targets = a.members.sample(
                    p.fanout, a._rng,
                    ring0_first=(
                        p.ring0_size > 0 and local and not e.sent_to
                    ),
                    exclude=e.sent_to,
                )
                if not targets:
                    # coverage exhausted: every alive peer already got it
                    del entries[key]
                    continue
                for m in targets:
                    e.sent_to.add(m.actor_id)
                    # one loss draw per target, in sample order, from
                    # the sender's stream (the shared-stream invariant)
                    if p.loss > 0.0 and a._rng.random() < p.loss:
                        continue
                    deliveries.append((self._index_of[m.actor_id], e.frame))
                self.msgs[i] += len(targets)
                e.remaining -= 1
                if e.remaining < 1:
                    del entries[key]
                else:
                    send_count = p.max_transmissions - e.remaining
                    e.next_due = t + det_backoff_gap(
                        p.backoff_ticks, send_count
                    )
        # delivery phase: the real wire + ingest path, applied after all
        # sends so same-tick deliveries can't influence same-tick sends
        sent = len(deliveries)
        for dest, frame in deliveries:
            a = self.agents[dest]
            for payload in speedy.FrameReader().feed(frame):
                decoded = a.decode_uni_frame_meta(payload)
                if decoded is not None:
                    cv, tp, hop, sig = decoded
                    a.handle_change(cv, ChangeSource.BROADCAST,
                                    meta=(tp, hop, sig, None))
        # anti-entropy phase on the kernel's cadence
        # (sim/epidemic.py: tick % sync_interval == sync_interval - 1),
        # after deliveries so sync sees this tick's learned state
        if p.sync_interval > 0 and t % p.sync_interval == p.sync_interval - 1:
            for i in range(p.n_nodes):
                self._det_sync_round(i, t)
        self.tick_no += 1
        return sent

    # -- deterministic anti-entropy ------------------------------------

    def _det_sync_round(self, i: int, tick: int) -> None:
        """One client sync round for agent ``i`` — the synchronous form
        of ``parallel_sync`` (runtime.py:1681): REAL ``generate_sync``
        states, REAL ``_choose_sync_peers`` (consuming the agent's det
        PRNG stream), REAL cross-peer ``_allocate_needs``, REAL
        ``_serve_need`` on the server (down to the speedy frame bytes)
        and REAL ``handle_change(…, SYNC)`` ingest on the client.  What
        the scheduler replaces is the socket/timing layer: handshakes
        are direct state reads, ``last_sync_ts`` advances in ticks, and
        clients run sequentially in index order (each fully ingesting
        before the next starts, so one sync tick can chain heals —
        matching the replay's sequential model).

        Message accounting (``sync_msgs``): 2 handshake frames per side
        per session (BiPayload+Clock / State+Clock), plus the client's
        Request frames and the server's served changeset frames — all
        counted from the real frames where frames exist.
        """
        a = self.agents[i]
        ours = a.generate_sync()
        chosen = a._choose_sync_peers(ours)
        if not chosen:
            return
        sessions = []
        for m in chosen:
            j = self._index_of[m.actor_id]
            sessions.append({
                "member": m,
                "theirs": self.agents[j].generate_sync(),
                "j": j,
            })
            self.sync_msgs[i] += 2  # BiPayload + Clock
            self.sync_msgs[j] += 2  # State + Clock
        a._allocate_needs(sessions, ours)
        for s in sessions:
            server = self.agents[s["j"]]
            batches = list(a._request_batches(s["needs"]))
            served: List = []
            if batches:
                w = _CollectWriter()
                sess = {"chunk": server.SYNC_CHUNK_MAX}

                async def serve_all():
                    for batch in batches:
                        for actor, needs in batch:
                            for need in needs:
                                await server._serve_need(
                                    w, actor.bytes, need, sess
                                )

                # one private event loop per session (not per need)
                asyncio.run(serve_all())
                reader = speedy.FrameReader()
                for payload in reader.feed(b"".join(w.chunks)):
                    served.append(speedy.decode_sync_message(payload))
            self.sync_msgs[i] += len(batches)
            self.sync_msgs[s["j"]] += len(served)
            for msg in served:
                if hasattr(msg, "actor_id"):  # ChangeV1
                    a.handle_change(msg, ChangeSource.SYNC)
            a.members.update_sync_ts(s["member"].actor_id, float(tick))

    def quiescent(self) -> bool:
        return all(not e for e in self._entries) and all(
            a._bcast_queue.empty() for a in self.agents
        )

    def infected(self, origin: int, version: int) -> List[int]:
        """Nodes holding ``version`` from ``origin`` (origin included)."""
        origin_actor = self.agents[origin].actor_id
        out = []
        for i, a in enumerate(self.agents):
            if i == origin or a.bookie.for_actor(origin_actor).contains_version(
                version
            ):
                out.append(i)
        return out

    def close(self) -> None:
        for a in self.agents:
            try:
                a.storage.close()  # main conn + RO pool
            except Exception:
                pass
        if self._own_dir:
            shutil.rmtree(self.base_dir, ignore_errors=True)


def run_det_epidemic(
    cluster: DetCluster, origin: int, write_id: int
) -> Dict:
    """One full epidemic on the deterministic cluster: write at
    ``origin``, tick until quiescent, record the per-tick trace.

    Returns {"origin", "version", "ticks": [{"infected": [...],
    "msgs": [...]} per tick], "converged_tick"} — cumulative msgs are
    snapshotted per tick so the trace is diffable tick-for-tick against
    the sim replay."""
    p = cluster.params
    version = cluster.write(
        origin, "INSERT INTO tests (id, text) VALUES (?, ?)",
        (write_id, f"det-{write_id}"),
    )
    base_msgs = list(cluster.msgs)
    base_sync = list(cluster.sync_msgs)
    trace = []
    converged_tick = None
    for _ in range(p.max_ticks):
        cluster.tick()
        infected = cluster.infected(origin, version)
        trace.append({
            "infected": infected,
            "msgs": [m - b for m, b in zip(cluster.msgs, base_msgs)],
            "sync_msgs": [
                m - b for m, b in zip(cluster.sync_msgs, base_sync)
            ],
        })
        if converged_tick is None and len(infected) == p.n_nodes:
            converged_tick = len(trace) - 1  # relative to epidemic start
        # with anti-entropy on, quiescence of the broadcast layer can
        # precede convergence (loss-orphaned nodes heal at the next
        # sync tick) — run until BOTH
        if cluster.quiescent() and (
            p.sync_interval <= 0 or converged_tick is not None
        ):
            break
    return {
        "origin": origin,
        "version": version,
        "ticks": trace,
        "converged_tick": converged_tick,
    }
