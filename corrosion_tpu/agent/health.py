"""Always-on event-loop health: the stall probe, promoted from bench.

The batching PRs (3–5) gate their benches on event-loop stall — a
serve/apply/write path that blocks the loop starves SWIM acks and sync
reads, and the only way those gates caught it was a bench-harness-side
probe (``bench.py _stall_probe``).  This module makes the same
measurement continuous in the agent itself, so a stall regression is
observable in production, not just in a bench run:

* ``corro_loop_stall_ms`` — histogram of per-sample scheduling gaps
  (how late the probe's ``sleep(interval)`` wakeup actually fired);
* ``corro_loop_stall_max_ms`` — lifetime max gauge (the bench gates'
  quantity, continuously maintained);
* ``corro_loop_slow_callbacks_total{site=…}`` — attribution: when a
  stall exceeds the slow threshold, a watchdog *thread* samples the
  loop thread's current Python frame (``sys._current_frames``) and
  counts the innermost in-package frame actually holding the loop.
  The probe coroutine cannot attribute its own starvation — it isn't
  running during the stall; only an out-of-band thread can look.

The probe costs one timer wakeup per ``interval`` (default 50 ms —
20/s) plus one histogram insert; the watchdog thread sleeps except
while a stall is in progress.  ``AgentConfig.stall_probe_interval = 0``
disables the whole thing.
"""

from __future__ import annotations

import asyncio
import sys
import threading
import time
from typing import Dict, Optional

# bounded attribution label set: sites beyond this collapse into
# "other" so a pathological workload cannot mint unbounded series
MAX_ATTRIBUTED_SITES = 32


class LoopHealthProbe:
    """One agent's event-loop stall probe + attribution watchdog."""

    def __init__(self, metrics, interval: float = 0.05,
                 slow_ms: float = 50.0, package: str = "corrosion_tpu",
                 clock=None):
        from corrosion_tpu.clock import SYSTEM_CLOCK

        self.metrics = metrics
        # the probe's wakeup timer rides the injectable clock; the
        # watchdog THREAD stays on real time — its whole job is an
        # out-of-band view of the loop, and thread-side waits are not
        # agent timers (docs/sim.md, virtual-time table)
        self._clock = clock or SYSTEM_CLOCK
        self.interval = max(0.001, float(interval))
        self.slow_ms = float(slow_ms)
        self.package = package
        self.max_stall_ms = 0.0
        self.last_stall_ms = 0.0
        self.samples = 0
        self.slow_sites: Dict[str, int] = {}
        self._beat = time.monotonic()
        self._loop_tid: Optional[int] = None
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None

    # -- the probe task (runs ON the loop) -----------------------------

    async def run(self) -> None:
        """Probe body: measure how late each periodic wakeup fires.
        Cancellation-clean — the agent owns the task's lifecycle."""
        loop = asyncio.get_running_loop()
        self._loop_tid = threading.get_ident()
        self._stop.clear()
        self._watchdog = threading.Thread(
            target=self._watch, name="corro-loop-watchdog", daemon=True
        )
        self._watchdog.start()
        last = loop.time()
        try:
            while True:
                self._beat = time.monotonic()
                await self._clock.sleep(self.interval)
                now = loop.time()
                stall_ms = max(0.0, (now - last - self.interval) * 1e3)
                last = now
                self.samples += 1
                self.last_stall_ms = stall_ms
                self.metrics.histogram("corro_loop_stall_ms", stall_ms)
                if stall_ms > self.max_stall_ms:
                    self.max_stall_ms = stall_ms
                    self.metrics.gauge(
                        "corro_loop_stall_max_ms", self.max_stall_ms
                    )
        finally:
            self._stop.set()

    # -- the watchdog (runs OFF the loop) ------------------------------

    def _watch(self) -> None:
        """Attribution thread: when the probe's heartbeat goes stale
        past the slow threshold, sample what the loop thread is
        executing RIGHT NOW — the only vantage point that can name the
        callback while it is still holding the loop."""
        threshold_s = self.interval + self.slow_ms / 1e3
        while not self._stop.is_set():
            age = time.monotonic() - self._beat
            if age > threshold_s and self._loop_tid is not None:
                site = self._sample_site()
                if site is not None:
                    n = self.slow_sites.get(site)
                    # the overflow bucket counts toward the bound: at
                    # most MAX_ATTRIBUTED_SITES keys INCLUDING "other"
                    if n is None and site != "other" and len(
                        self.slow_sites
                    ) >= MAX_ATTRIBUTED_SITES - 1:
                        site = "other"
                        n = self.slow_sites.get(site)
                    self.slow_sites[site] = (n or 0) + 1
                    self.metrics.counter(
                        "corro_loop_slow_callbacks_total", site=site
                    )
                # one attribution per stall: wait for the heartbeat to
                # move again before sampling anew, so a single long
                # stall counts once instead of once per poll
                beat = self._beat
                while not self._stop.wait(self.interval) \
                        and self._beat == beat:
                    pass
                continue
            self._stop.wait(self.interval)

    def _sample_site(self) -> Optional[str]:
        try:
            frame = sys._current_frames().get(self._loop_tid)
        except Exception:
            return None
        if frame is None:
            return None
        # innermost frame inside our package; an innermost frame in the
        # stdlib (e.g. select/epoll inside the loop itself) with no
        # package frame above it means the loop is idle-polling — skip
        best = None
        f = frame
        while f is not None:
            mod = f.f_globals.get("__name__", "")
            if mod.startswith(self.package):
                best = f"{mod}:{f.f_code.co_name}"
                break  # innermost package frame wins
            f = f.f_back
        return best

    # -- admin surface -------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "interval_s": self.interval,
            "slow_threshold_ms": self.slow_ms,
            "samples": self.samples,
            "max_stall_ms": round(self.max_stall_ms, 3),
            "last_stall_ms": round(self.last_stall_ms, 3),
            "slow_sites": dict(self.slow_sites),
        }
