"""Admin server over a unix domain socket.

Parity: ``crates/corro-admin`` — JSON-framed request/response protocol on
a UDS: ``ping``, ``sync generate`` (dump the sync handshake state),
``sync reconcile-gaps``, ``cluster members`` / ``membership-states``,
``actor version``, ``subs list`` / ``subs info``, ``locks``
(``corro-admin/src/lib.rs:95-619``).
"""

from __future__ import annotations

import asyncio
import os
from typing import TYPE_CHECKING

from corrosion_tpu.agent import wire

if TYPE_CHECKING:
    from corrosion_tpu.agent.runtime import Agent


async def start_admin(agent: "Agent", path: str) -> asyncio.AbstractServer:
    if os.path.exists(path):
        os.unlink(path)
    server = await asyncio.start_unix_server(
        lambda r, w: _serve(agent, r, w), path=path
    )
    return server


async def _serve(agent: "Agent", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter) -> None:
    frames = wire.FrameReader()
    try:
        while True:
            data = await reader.read(65536)
            if not data:
                return
            for msg in frames.feed(data):
                try:
                    resp = _handle(agent, msg)
                except Exception as e:  # bad input -> error frame, not EOF
                    resp = {"error": f"{type(e).__name__}: {e}"}
                writer.write(wire.encode_msg(resp))
                await writer.drain()
    except (ConnectionError, asyncio.IncompleteReadError):
        return
    finally:
        writer.close()


def _handle(agent: "Agent", msg: dict) -> dict:
    cmd = msg.get("cmd")
    if cmd == "ping":
        return {"ok": "pong"}

    if cmd == "sync_generate":
        st = agent.generate_sync()
        from corrosion_tpu.agent.runtime import _sync_state_to_dict

        return {"ok": _sync_state_to_dict(st)}

    if cmd == "sync_reconcile_gaps":
        # collapse gaps that are actually satisfied by cleared ranges
        fixed = 0
        with agent.storage._lock:
            for actor, bv in agent.bookie.actors().items():
                for s, e in list(bv.needed):
                    if bv.cleared.contains_span(s, e):
                        bv.needed.remove(s, e)
                        fixed += 1
                agent.bookie._persist_gaps(actor)
            agent.storage.conn.commit()
            if fixed:
                # direct RangeSet surgery bypasses the BookedVersions
                # mutation hooks: invalidate the cached generate_sync
                # snapshot or handshakes keep advertising the old gaps
                agent.bookie._bump_gen()
        return {"ok": {"reconciled": fixed}}

    if cmd == "cluster_members":
        # per-member transport view (ConnStats + breaker state): the
        # debuggability surface for chaos runs — injected drops,
        # redials, and breaker opens are visible per peer address
        tstats = agent.transport.stats if agent.transport else {}
        breakers = (
            agent.transport.breaker_states() if agent.transport else {}
        )
        out = []
        for m in agent.members.all():
            addr = tuple(m.addr)
            st = tstats.get(addr)
            out.append({
                "actor": m.actor_id.hex(),
                "addr": list(m.addr),
                "state": m.state.value,
                "incarnation": m.incarnation,
                "rtt_ms": m.rtt_ms,
                "ring0": m.is_ring0,
                "quarantined": m.quarantined,
                # evidence class behind the quarantine: "breaker"
                # (transport failures) or "equivocation" (hostile
                # changesets — never cleared by transport success)
                "quarantine_reason": m.quarantine_reason,
                "breaker": breakers.get(addr, "closed"),
                "transport": st.as_dict() if st is not None else None,
            })
        return {"ok": out}

    if cmd == "rtt_dump":
        # measured-topology export: the Members RTT-ring tier
        # distribution as topology JSON consumable by
        # ``bench.py --frontier --topology measured_ring``
        from corrosion_tpu.agent.members import (
            DEFAULT_RTT_TIER_EDGES_MS,
            rtt_topology,
        )

        edges = msg.get("tier_edges_ms")
        if edges is not None:
            try:
                edges = tuple(float(e) for e in edges)
                if not edges or any(
                    b <= a for a, b in zip(edges, edges[1:])
                ):
                    raise ValueError("edges must strictly increase")
            except (TypeError, ValueError) as e:
                return {"error": f"bad tier_edges_ms: {e}"}
        else:
            edges = DEFAULT_RTT_TIER_EDGES_MS
        return {"ok": rtt_topology(agent.members, edges)}

    if cmd == "transport_stats":
        if agent.transport is None:
            return {"ok": {}}
        breakers = agent.transport.breaker_states()
        return {
            "ok": {
                f"{a[0]}:{a[1]}": dict(
                    s.as_dict(), breaker=breakers.get(a, "closed")
                )
                for a, s in agent.transport.stats.items()
            }
        }

    if cmd == "faults":
        if agent.faults is None:
            return {"ok": None}
        return {"ok": agent.faults.as_dict()}

    if cmd == "cluster_rejoin":
        return {"ok": {"announced": agent.rejoin()}}

    if cmd == "cluster_set_id":
        try:
            announced = agent.set_cluster_id(int(msg["cluster_id"]))
        except (KeyError, ValueError) as e:
            return {"error": f"bad cluster_id: {e}"}
        return {
            "ok": {
                "cluster_id": agent.config.cluster_id,
                "announced": announced,
            }
        }

    if cmd == "trace_spans":
        from corrosion_tpu.agent import tracing

        # --trace <id>: assemble one cross-node trace from this node's
        # ring without shipping (and grepping) the whole dump
        trace_id = msg.get("trace")
        if trace_id is not None:
            trace_id = str(trace_id).lower()
        return {
            "ok": [
                {
                    "name": s.name,
                    "trace_id": s.trace_id,
                    "span_id": s.span_id,
                    "parent_id": s.parent_id,
                    "start": s.start,
                    "dur_ms": s.dur_ms,
                    "attrs": {k: str(v) for k, v in s.attrs.items()},
                }
                for s in tracing.recent_spans(
                    int(msg.get("limit", 100)), trace_id=trace_id
                )
            ]
        }

    if cmd == "health":
        # runtime health: loop stall probe, queue depths, the agent's
        # own convergence-lag measurement (docs/telemetry.md)
        return {"ok": agent.health_snapshot()}

    if cmd == "flight_dump":
        # the flight ring: recorder state + every held record
        # (snapshots and events), oldest first
        if agent.flight is None:
            return {"ok": None}
        return {
            "ok": {
                "recorder": agent.flight.snapshot(),
                "entries": agent.flight.entries(
                    limit=int(msg.get("limit", 0))
                ),
            }
        }

    if cmd == "flight_events":
        # the typed event journal alone (the ring minus snapshots)
        if agent.flight is None:
            return {"ok": None}
        return {
            "ok": agent.flight.entries(
                limit=int(msg.get("limit", 0)), kind="event"
            )
        }

    if cmd == "sync_sessions":
        # live sync sessions, both roles: peer, age, needs-remaining,
        # session byte volume (docs/telemetry.md per-session sync
        # observability)
        return {"ok": agent.sync_sessions()}

    if cmd == "actor_version":
        actor = bytes.fromhex(msg.get("actor", agent.actor_id.hex()))
        bv = agent.bookie.for_actor(actor)
        return {
            "ok": {
                "actor": actor.hex(),
                "last": bv.last(),
                "needed": bv.needed_spans(),
                "partials": {
                    str(v): p.gaps() for v, p in bv.partials.items()
                },
                "cleared": bv.cleared.spans(),
            }
        }

    if cmd == "subs_list":
        if agent.subs is None:
            return {"ok": []}
        return {"ok": agent.subs.list()}

    if cmd == "subs_info":
        if agent.subs is None:
            return {"error": "subscriptions disabled"}
        h = agent.subs.get(msg.get("id", ""))
        if h is None:
            return {"error": "no such subscription"}
        return {
            "ok": {
                "id": h.id,
                "sql": h.sql,
                "tables": sorted(h.tables),
                "rows": len(h.rows),
                "last_change_id": h.last_change_id,
                "streams": len(h._streams),
            }
        }

    if cmd == "locks":
        # lock observability (LockRegistry parity): report holders of the
        # storage write lock if instrumented
        return {"ok": agent.lock_registry.snapshot()}

    if cmd == "db_info":
        with agent.storage._lock:
            (page_count,) = agent.storage.conn.execute(
                "PRAGMA page_count"
            ).fetchone()
            (freelist,) = agent.storage.conn.execute(
                "PRAGMA freelist_count"
            ).fetchone()
        return {
            "ok": {
                "db_version": agent.storage.db_version(),
                "page_count": page_count,
                "freelist_count": freelist,
            }
        }

    return {"error": f"unknown command {cmd!r}"}


class AdminClient:
    """Synchronous UDS client for the admin protocol (CLI-side)."""

    def __init__(self, path: str, timeout: float = 5.0):
        import socket

        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(timeout)
        self.sock.connect(path)
        self._frames = wire.FrameReader()

    def call(self, cmd: str, **kwargs) -> dict:
        self.sock.sendall(wire.encode_msg({"cmd": cmd, **kwargs}))
        while True:
            data = self.sock.recv(65536)
            if not data:
                raise ConnectionError("admin socket closed")
            msgs = self._frames.feed(data)
            if msgs:
                resp = msgs[0]
                if "error" in resp:
                    raise RuntimeError(resp["error"])
                return resp["ok"]

    def close(self) -> None:
        self.sock.close()
