"""HTTP client API.

Parity: ``crates/corro-agent/src/api/public/`` + routes assembled at
``agent/util.rs:181-293``:

* ``POST /v1/transactions`` — execute write statements in one version
  (broadcast on commit); concurrent requests coalesce through the
  group-commit write combiner (docs/writes.md) — each handler thread's
  batch keeps its own version and failure isolation;
* ``POST /v1/queries`` — streaming NDJSON query results
  (columns / row / eoq events, like ``TypedQueryEvent``);
* ``POST /v1/migrations`` — merge schema SQL;
* ``GET  /v1/table_stats`` — per-table row counts;
* ``POST /v1/subscriptions`` / ``GET /v1/subscriptions/:id`` — streaming
  incremental query subscriptions (see :mod:`corrosion_tpu.agent.pubsub`);
* ``GET  /v1/updates/:table`` — raw per-table change notifications;
* optional bearer authz.

Implementation: stdlib ``ThreadingHTTPServer`` — each agent runs it on a
thread next to the asyncio gossip loop; handlers call the agent's
thread-safe storage/bookkeeping paths directly.
"""

from __future__ import annotations

import json
import threading

from corrosion_tpu.agent.pack import jsonable_row
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from corrosion_tpu.agent.runtime import Agent


class _ApiServer(ThreadingHTTPServer):
    # socketserver's default listen backlog is 5: under a request burst
    # the kernel RSTs the overflow and clients see connection resets.
    # The reference serves on hyper/tokio with an effectively deep
    # accept queue; match that.
    request_queue_size = 128


def start_http_api(agent: "Agent") -> ThreadingHTTPServer:
    handler = _make_handler(agent)
    server = _ApiServer(
        (agent.config.api_host, agent.config.api_port or 0), handler
    )
    server.daemon_threads = True
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server


def _make_handler(agent: "Agent"):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # quiet
            pass

        # -- helpers ---------------------------------------------------

        def _authorized(self) -> bool:
            token = agent.config.api_authz
            if not token:
                return True
            got = self.headers.get("Authorization", "")
            return got == f"Bearer {token}"

        def _body(self):
            ln = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(ln) if ln else b""
            return json.loads(raw) if raw else None

        def _json(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _stream_start(self, code: int = 200) -> None:
            self.send_response(code)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

        def _stream_line(self, obj) -> None:
            line = (json.dumps(obj) + "\n").encode()
            self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
            self.wfile.flush()

        def _stream_end(self) -> None:
            self.wfile.write(b"0\r\n\r\n")

        # -- routes ----------------------------------------------------

        _ENDPOINTS = (
            "/v1/transactions", "/v1/queries", "/v1/migrations",
            "/v1/subscriptions", "/v1/updates", "/v1/table_stats",
            "/v1/members", "/metrics",
        )

        def _count_request(self) -> None:
            # label values must stay bounded AND server-chosen: raw
            # request paths would let an unauthenticated client mint
            # unlimited series (and inject into the exposition)
            path = self.path.split("?")[0]
            for ep in self._ENDPOINTS:
                if path == ep or path.startswith(ep + "/"):
                    agent.metrics.counter(
                        "corro_http_requests_total", endpoint=ep
                    )
                    return
            agent.metrics.counter(
                "corro_http_requests_total", endpoint="other"
            )

        def do_POST(self):
            self._count_request()
            if not self._authorized():
                return self._json(401, {"error": "unauthorized"})
            try:
                if self.path == "/v1/transactions":
                    return self._transactions()
                if self.path == "/v1/queries":
                    return self._queries()
                if self.path == "/v1/migrations":
                    return self._migrations()
                if self.path == "/v1/subscriptions":
                    return self._subscribe()
                return self._json(404, {"error": "not found"})
            except BrokenPipeError:
                pass
            except Exception as e:  # surface agent errors to the client
                try:
                    self._json(500, {"error": str(e)})
                except Exception:
                    pass

        def do_GET(self):
            self._count_request()
            if not self._authorized():
                return self._json(401, {"error": "unauthorized"})
            try:
                if self.path == "/metrics":
                    return self._metrics()
                if self.path == "/v1/table_stats":
                    return self._table_stats()
                if self.path == "/v1/members":
                    return self._members()
                if self.path.startswith("/v1/subscriptions/"):
                    return self._subscribe_by_id(self.path.rsplit("/", 1)[1])
                if self.path.startswith("/v1/updates/"):
                    return self._updates(self.path.rsplit("/", 1)[1])
                return self._json(404, {"error": "not found"})
            except BrokenPipeError:
                pass
            except Exception as e:
                try:
                    self._json(500, {"error": str(e)})
                except Exception:
                    pass

        def _transactions(self):
            stmts = self._body()
            if not isinstance(stmts, list):
                return self._json(400, {"error": "expected a JSON array"})
            out = agent.execute_transaction(stmts)
            self._json(200, out)

        def _queries(self):
            stmt = self._body()
            if isinstance(stmt, str):
                sql, params = stmt, ()
            elif isinstance(stmt, list):
                sql, params = stmt[0], stmt[1] if len(stmt) > 1 else ()
            else:
                return self._json(400, {"error": "expected statement"})
            cols, rows = agent.storage.read_query(sql, params)
            self._stream_start()
            self._stream_line({"columns": cols})
            for i, row in enumerate(rows):
                self._stream_line({"row": [i + 1, jsonable_row(row)]})
            self._stream_line({"eoq": {"time": 0.0}})
            self._stream_end()

        def _migrations(self):
            body = self._body()
            sql = "\n".join(body) if isinstance(body, list) else str(body)
            self._json(200, {"tables": agent.apply_schema_sql(sql)})

        def _metrics(self):
            body = agent.metrics.render(agent.metric_gauges()).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _table_stats(self):
            stats = {}
            with agent.storage._lock:
                for t in agent.storage.tables:
                    (n,) = agent.storage.conn.execute(
                        f'SELECT COUNT(*) FROM "{t}"'
                    ).fetchone()
                    stats[t] = {"row_count": n}
            self._json(200, {"tables": stats})

        def _members(self):
            transport = getattr(agent, "transport", None)
            conn_stats = transport.stats if transport is not None else {}
            members = []
            for m in agent.members.all():
                # .get is the atomic read: the event loop may evict the
                # entry concurrently with this handler thread
                stats = conn_stats.get(tuple(m.addr))
                members.append({
                    "actor": m.actor_id.hex(),
                    "addr": list(m.addr),
                    "state": m.state.value,
                    "incarnation": m.incarnation,
                    "rtt_ms": m.rtt_ms,
                    # per-peer transport stats (transport.rs
                    # ConnectionStats parity)
                    "conn": stats.as_dict() if stats is not None else None,
                })
            self._json(200, {"members": members})

        def _subscribe(self):
            if agent.subs is None:
                return self._json(501, {"error": "subscriptions disabled"})
            stmt = self._body()
            sql = stmt if isinstance(stmt, str) else stmt[0]
            handle = agent.subs.subscribe(sql)
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("x-corro-query-id", handle.id)
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self._pump_subscription(handle, from_change_id=None)

        def _subscribe_by_id(self, sub_id: str):
            if agent.subs is None:
                return self._json(501, {"error": "subscriptions disabled"})
            query = ""
            from_id = None
            if "?" in sub_id:
                sub_id, query = sub_id.split("?", 1)
                for part in query.split("&"):
                    if part.startswith("from="):
                        from_id = int(part[5:])
            handle = agent.subs.get(sub_id)
            if handle is None:
                return self._json(404, {"error": "no such subscription"})
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("x-corro-query-id", handle.id)
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self._pump_subscription(handle, from_change_id=from_id)

        def _pump_subscription(self, handle, from_change_id):
            try:
                for event in handle.stream(from_change_id=from_change_id):
                    self._stream_line(event)
            except (BrokenPipeError, ConnectionResetError):
                handle.unsubscribe_stream()

        def _updates(self, table: str):
            if agent.subs is None:
                return self._json(501, {"error": "subscriptions disabled"})
            if table not in agent.storage.tables:
                return self._json(404, {"error": f"no such table {table}"})
            self._stream_start()
            try:
                for event in agent.subs.table_updates(table):
                    self._stream_line(event)
            except (BrokenPipeError, ConnectionResetError):
                pass

    return Handler


def jsonable_row(row):
    out = []
    for v in row:
        if isinstance(v, bytes):
            out.append(v.hex())
        else:
            out.append(v)
    return out
