"""CRDT storage engine: conflict-free replicated tables over stock sqlite3.

This is our implementation of the semantics the reference gets from the
vendored cr-sqlite C extension (loaded at
``crates/corro-types/src/sqlite.rs:103-121``; semantics documented in
``doc/crdts.md``):

* ``as_crr(table)`` marks a table as a conflict-free replicated relation:
  a ``<t>__corro_clock`` table tracks a lamport ``col_version`` per
  (row, column) cell, and a ``<t>__corro_cl`` table tracks the row's
  **causal length** (odd = live, even = deleted);
* local writes run through generated AFTER INSERT/UPDATE/DELETE triggers
  that maintain the clock tables with (db_version, seq) stamps — any SQL
  write works, exactly like cr-sqlite's trigger machinery;
* ``collect_changes`` is the ``crsql_changes`` SELECT side: cell-level
  change rows, seq-ordered within a db_version;
* ``apply_changes`` is the ``crsql_changes`` INSERT side — the merge:
  bigger causal length wins the row; within an equal causal length the
  bigger ``col_version`` wins the cell, ties broken by the bigger value
  in cr-sqlite's type-enum order — INTEGER > FLOAT > TEXT > BLOB > NULL,
  numeric/bytewise within a type
  (:func:`corrosion_tpu.agent.pack.value_cmp`, pinned against the real
  extension by tests/test_crsqlite_golden.py);
* ``site_id`` identifies this database (== the agent's ActorId), interned
  remote sites get small ordinals like cr-sqlite's site table.

Design difference from the reference (deliberate): no virtual tables —
change collection and application are plain queries + Python merge logic
(with a C fast path planned), because our hot path for bulk merges is the
TPU kernel in :mod:`corrosion_tpu.ops.merge`, not the sqlite insert path.
"""

from __future__ import annotations

import operator
import re
import sqlite3
import threading
import uuid
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from corrosion_tpu.agent.pack import pack_values, unpack_values, value_cmp
from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq
from corrosion_tpu.types.change import Change, SENTINEL_CID

_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def unpack_stmt(stmt) -> Tuple[str, Sequence]:
    """One buffered statement → (sql, params).  Shared by the commit
    replay (runtime.execute_transaction) and the speculative sandbox so
    the two can never diverge on the statement shape."""
    if isinstance(stmt, str):
        return stmt, ()
    return stmt[0], stmt[1] if len(stmt) > 1 else ()


def _ident(name: str) -> str:
    if not _IDENT_RE.match(name):
        raise ValueError(f"invalid identifier: {name!r}")
    return name


@dataclass(frozen=True)
class TableInfo:
    name: str
    pk_cols: Tuple[str, ...]
    data_cols: Tuple[str, ...]  # non-pk columns
    all_cols: Tuple[str, ...] = ()  # DECLARATION order (RETURNING *)
    # every data column is nullable or has a DEFAULT: a fresh row can be
    # created listing only (pk + written cells) with the exact same
    # outcome as _ensure_row + per-cell UPDATEs.  When False the batched
    # apply keeps the conservative two-step shape, bug-for-bug with the
    # per-change path (whose pk-only INSERT OR IGNORE silently fails on
    # NOT-NULL-without-default columns).
    fused_insert_ok: bool = True


class _WriteBehind:
    """In-memory ledger of merged-but-unflushed table batches (the
    device-resident apply's async flush queue).  Each entry is
    ``(table, states, journal_id)``: the net merged ``states`` the
    flush will consume, and the ``__corro_flush_journal`` row that
    makes it crash-durable.

    Lifecycle: an apply stages entries on ``tx_staged`` (journal row
    inserted in the same transaction); commit moves them to
    ``pending``; a drain moves pending entries being flushed inside an
    open apply transaction to ``draining`` so a rollback can requeue
    them at the FRONT (their journal deletes roll back with the tx).
    ``unflushed`` maps table -> pks with any not-yet-flushed state —
    the overlap guard that forces a flush before SQLite is read for
    those rows."""

    __slots__ = ("pending", "draining", "tx_staged", "unflushed")

    def __init__(self):
        self.pending: List[tuple] = []
        self.draining: List[tuple] = []
        self.tx_staged: List[tuple] = []
        self.unflushed: Dict[str, set] = {}

    def recompute(self) -> None:
        u: Dict[str, set] = {}
        for t, states, _jid in self.pending:
            u.setdefault(t, set()).update(states)
        for t, states, _jid in self.tx_staged:
            u.setdefault(t, set()).update(states)
        self.unflushed = u

    def cells_pending(self) -> int:
        return sum(
            len(st[5]) + 1
            for _t, states, _j in self.pending
            for st in states.values()
        )


def _wb_coalesce(s1: list, s2: list) -> list:
    """Merge two staged net states for the same (table, pk), s2 newer.
    A newer generation (row replaced) supersedes everything earlier;
    otherwise the newer cells overlay the older ones.  Sound because s2
    was merged against a seed view that already included s1 (the cache
    shadow), so s2's decisions account for s1."""
    if s2[2]:  # GEN
        c1, c2 = s1[1], s2[1]
        if c1 is None:
            return s2
        out = list(s2)
        if c2 is None:
            # no cl write in s2: a sequential flush would have left
            # s1's cl row in place
            out[1] = c1
        elif c1[5] and not c2[5]:
            # sequential flushes MAX the sentinel flag across the
            # upsert (s1's row would already be in the DB) — coalescing
            # must not lose s1's flag before the DB ever sees it
            out[1] = c2[:5] + (1,)
        return out
    out = list(s1)
    if s2[1] is not None:
        c1 = s1[1]
        out[1] = (s2[1][:5] + (1,)
                  if c1 is not None and c1[5] and not s2[1][5]
                  else s2[1])
    if s2[0] is not None:
        out[0] = s2[0]
    out[4] = s1[4] or s2[4]  # ENSURE
    cells = dict(s1[5])
    cells.update(s2[5])
    out[5] = cells
    return out


def _wb_encode_states(states: Dict[bytes, list]) -> bytes:
    """Versioned net states for the flush journal.  Net STATES, not
    winner Changes: replaying winners through apply_changes is not
    idempotent for fresh implicit-cl rows (the generation branch of the
    per-change path wipes sibling cells a batched flush preserved), so
    the journal stores exactly what ``_flush_table_states`` consumes.

    pickle, not speedy: the encode runs inside the apply transaction's
    critical section on every device-path batch, and the per-field
    Python writer dominated the whole apply wall (55% in profile) where
    pickle's C encoder is noise.  This is safe ONLY because the journal
    never crosses a trust boundary: payloads are written and read by
    this node alone — boot recovery decodes bytes this process family
    wrote, and ``install_snapshot`` PURGES (never replays) journal rows
    arriving inside a donor's snapshot file."""
    import pickle

    return b"\x01" + pickle.dumps(states, protocol=4)


def _wb_decode_states(payload: bytes) -> Dict[bytes, list]:
    import pickle

    if payload[:1] != b"\x01":
        raise ValueError("unknown flush-journal payload version")
    return pickle.loads(payload[1:])


def register_udfs(conn: sqlite3.Connection) -> None:
    """Register every SQL function the CRR layer depends on.  ANY
    connection touching an agent database needs these: the CRR tables
    carry expression indexes on corro_pack, so even a plain VACUUM
    fails without it."""
    conn.create_function("corro_pack", -1, _udf_pack, deterministic=True)
    conn.create_function(
        "corro_json_contains", 2, _udf_json_contains, deterministic=True
    )
    # PG-compat identity functions: drivers call these in arbitrary
    # expression contexts ("SELECT current_database() AS name"), so they
    # must exist as real functions, not canned string matches (the
    # pgwire front-end routes such queries here; corro-pg parity)
    conn.create_function(
        "current_database", 0, lambda: "corrosion", deterministic=True
    )
    conn.create_function(
        "current_schema", 0, lambda: "public", deterministic=True
    )
    conn.create_function(
        "version", 0,
        lambda: "PostgreSQL 14.9 (corrosion-tpu sqlite CRDT)",
        deterministic=True,
    )


class CrConn:
    """A sqlite3 connection with the CRDT layer installed."""

    RO_POOL_SIZE = 20  # reference: 1 RW + 20 RO (agent.rs:614-765)

    def __init__(self, path: str, site_id: Optional[bytes] = None,
                 lock_registry=None):
        from corrosion_tpu.agent.locks import PriorityLock

        self.path = path
        self.conn = self._connect_rw()
        # single RW connection behind a 3-tier priority mutex: applies
        # of replicated changes go first, API writes next, maintenance
        # last (the scheduling the reference gets from its split write
        # pools, agent.rs:614-765)
        self._lock = PriorityLock(lock_registry, "storage")
        self._init_meta(site_id)
        self._tables: Dict[str, TableInfo] = {}
        self._load_crr_tables()
        # read pool: up to RO_POOL_SIZE read-only connections created
        # lazily; concurrent readers no longer serialize on one conn
        self._ro_free: List[sqlite3.Connection] = []
        self._ro_all: List[sqlite3.Connection] = []
        self._ro_cv = threading.Condition()
        self._ro_closed = False
        # readers checked out across a snapshot install keep serving
        # their (pre-swap) WAL snapshot, then close on return instead
        # of re-pooling — the pool refills lazily against the new file
        self._ro_stale: set = set()
        # slow-disk fault seam (faults.FaultController.io_hook_for):
        # callable(op: "write"|"read") -> delay seconds, consulted once
        # per write batch and per change collection.  The sleep runs on
        # the worker/caller thread holding the storage path — a slow
        # disk stretches lock holds and serve windows, it does not
        # block the event loop directly.  None in production.
        self.io_fault = None
        # columnar merge kernel dispatch (docs/crdts.md): batched
        # applies at/above the threshold resolve winners through
        # ops/merge.py segment reductions; below it (or on encode
        # fallback) the per-change dict replay runs.  The agent mirrors
        # AgentConfig.columnar_merge / columnar_merge_min here.
        self.columnar_merge = True
        self.columnar_merge_min = 256
        # optional Metrics sink (set by the agent): merge-phase timing
        # lands in corro_apply_merge_seconds{kernel=}
        self.metrics = None
        # device-resident apply (docs/crdts.md "Device-resident apply"):
        # when enabled, batched applies seed from the cross-batch clock
        # cache and SQLite becomes the durable sink behind the
        # write-behind flush below.  None == classic prefetch path.
        self.device_cache = None
        self._wb = _WriteBehind()
        # metric-delta snapshot for the cache's monotonic counters
        self._devcache_emitted: Dict = {}
        # flush-journal rows replayed at boot (crash between an apply
        # commit and its async flush); the agent re-emits this as
        # corro_apply_flush_recoveries_total once metrics attach
        self.flush_journal_recovered = 0
        self._recover_flush_journal()

    def _connect_rw(self) -> sqlite3.Connection:
        """The ONE RW-connection recipe, shared by construction and the
        post-snapshot-install reopen — a pragma added here applies to
        both, so a node that installed a snapshot never runs a
        differently-configured connection until restart."""
        conn = sqlite3.connect(self.path, check_same_thread=False)
        conn.isolation_level = None  # manual transactions
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        conn.execute("PRAGMA foreign_keys=OFF")
        # transient SQLITE_BUSY (e.g. a checkpoint of a large WAL racing
        # a snapshot open) should wait, not raise: a raise on the
        # subscription delta path degrades it to a full re-evaluation
        conn.execute("PRAGMA busy_timeout=5000")
        register_udfs(conn)
        return conn

    def _io_delay(self, op: str) -> None:
        hook = self.io_fault
        if hook is None:
            return
        d = hook(op)
        if d and d > 0:
            import time

            time.sleep(d)

    def _new_ro(self) -> sqlite3.Connection:
        conn = sqlite3.connect(
            f"file:{self.path}?mode=ro", uri=True, check_same_thread=False,
        )
        conn.execute("PRAGMA busy_timeout=5000")  # see RW conn note
        # triggers resolve functions at prepare time, so RO conns need
        # them registered even though writes will fail
        register_udfs(conn)
        return conn

    @property
    def _ro_conn(self) -> sqlite3.Connection:
        """The pool's first reader — the instrumentation anchor: tests
        attach progress handlers here, and checkout PREFERS it whenever
        it is free, so single-threaded callers reliably land on it even
        after the pool has grown."""
        with self._ro_cv:
            if not self._ro_all:
                conn = self._new_ro()
                self._ro_all.append(conn)
                self._ro_free.append(conn)
            return self._ro_all[0]

    @contextmanager
    def reader(self):
        """Check a read-only connection out of the pool (split-pool
        parity).  Blocks when all RO_POOL_SIZE readers are in flight."""
        with self._ro_cv:
            while not self._ro_free and len(self._ro_all) >= self.RO_POOL_SIZE:
                if self._ro_closed:
                    raise sqlite3.ProgrammingError("storage is closed")
                self._ro_cv.wait()
            if self._ro_closed:
                raise sqlite3.ProgrammingError("storage is closed")
            if self._ro_free:
                # prefer the instrumented first reader when free
                first = self._ro_all[0] if self._ro_all else None
                if first is not None and first in self._ro_free:
                    self._ro_free.remove(first)
                    conn = first
                else:
                    conn = self._ro_free.pop()
            else:
                conn = self._new_ro()
                self._ro_all.append(conn)
        try:
            yield conn
        finally:
            with self._ro_cv:
                if self._ro_closed or conn in self._ro_stale:
                    conn.close()
                    self._ro_stale.discard(conn)
                    if conn in self._ro_all:
                        self._ro_all.remove(conn)
                    self._ro_cv.notify()
                else:
                    self._ro_free.append(conn)
                    self._ro_cv.notify()

    def read_query(self, sql: str, params: Sequence = (), on_conn=None):
        """Run a query on a pooled read-only connection.  Writes through
        this path fail with a sqlite 'readonly' error instead of
        corrupting version accounting.  ``on_conn`` (called with the
        checked-out connection, then with None on completion) lets a
        caller interrupt a long-running read — the PG front-end's
        CancelRequest path."""
        # write-behind barrier: serve reads (API queries, subscription
        # evaluation, snapshot assembly) must not observe a merged-but-
        # unflushed winner; no-op unless the device path staged state
        self.flush_barrier()
        with self.reader() as conn:
            if on_conn is not None:
                on_conn(conn)
            try:
                cur = conn.execute(sql, params)
                cols = [d[0] for d in cur.description or []]
                return cols, cur.fetchall()
            finally:
                if on_conn is not None:
                    on_conn(None)

    # ------------------------------------------------------------------
    # metadata
    # ------------------------------------------------------------------

    def _init_meta(self, site_id: Optional[bytes]) -> None:
        c = self.conn
        c.execute(
            "CREATE TABLE IF NOT EXISTS __corro_state "
            "(key TEXT PRIMARY KEY, value INTEGER NOT NULL)"
        )
        c.execute(
            "INSERT OR IGNORE INTO __corro_state VALUES "
            "('db_version', 0), ('pending_db_version', 0), ('seq', 0), "
            "('apply_mode', 0)"
        )
        c.execute(
            "CREATE TABLE IF NOT EXISTS __corro_sites "
            "(ordinal INTEGER PRIMARY KEY AUTOINCREMENT, site_id BLOB NOT NULL UNIQUE)"
        )
        c.execute(
            "CREATE TABLE IF NOT EXISTS __corro_crr_tables (name TEXT PRIMARY KEY)"
        )
        c.execute(
            "CREATE TABLE IF NOT EXISTS __corro_backfills "
            "(db_version INTEGER PRIMARY KEY, last_seq INTEGER NOT NULL)"
        )
        # local versions whose clock rows were overwritten/deleted since
        # the last compaction sweep (find_overwritten_versions parity,
        # ref agent.rs:1753-1812; filled by the clock-change triggers)
        c.execute(
            "CREATE TABLE IF NOT EXISTS __corro_versions_impacted "
            "(site_ordinal INTEGER NOT NULL, db_version INTEGER NOT NULL, "
            " PRIMARY KEY (site_ordinal, db_version))"
        )
        # write-behind flush journal (device-resident apply): one row
        # per merged-but-unflushed table batch, inserted in the apply
        # transaction and deleted in the transaction that flushes it —
        # a crash in the window between the two replays at boot
        # (_recover_flush_journal), so no committed winner is ever lost
        c.execute(
            "CREATE TABLE IF NOT EXISTS __corro_flush_journal "
            "(id INTEGER PRIMARY KEY AUTOINCREMENT, "
            " tbl TEXT NOT NULL, payload BLOB NOT NULL)"
        )
        row = c.execute(
            "SELECT site_id FROM __corro_sites WHERE ordinal = 1"
        ).fetchone()
        if row is None:
            sid = site_id or uuid.uuid4().bytes
            c.execute("INSERT INTO __corro_sites (ordinal, site_id) VALUES (1, ?)", (sid,))
            self.site_id = sid
        else:
            self.site_id = bytes(row[0])

    def _load_crr_tables(self) -> None:
        for (name,) in self.conn.execute("SELECT name FROM __corro_crr_tables"):
            info = self._introspect(name)
            self._tables[name] = info
            # idempotent: databases created before the compaction feature
            # need the impact triggers installed on reopen
            self._create_impact_triggers(name)
            # likewise for the packed-pk expression index (without it,
            # change collection degrades to per-clock-row full scans)
            pack_expr = "corro_pack(" + ", ".join(
                f'"{p}"' for p in info.pk_cols
            ) + ")"
            self.conn.execute(
                f'CREATE INDEX IF NOT EXISTS "{name}__corro_packpk" '
                f'ON "{name}" ({pack_expr})'
            )

    def _introspect(self, table: str) -> TableInfo:
        info = self.conn.execute(f'PRAGMA table_info("{_ident(table)}")').fetchall()
        if not info:
            raise ValueError(f"no such table: {table}")
        pk = tuple(r[1] for r in sorted((r for r in info if r[5]), key=lambda r: r[5]))
        data = tuple(r[1] for r in info if not r[5])
        if not pk:
            raise ValueError(f"CRR table {table} must have a primary key")
        return TableInfo(
            name=table, pk_cols=pk, data_cols=data,
            all_cols=tuple(r[1] for r in info),
            fused_insert_ok=all(
                not r[3] or r[4] is not None for r in info if not r[5]
            ),
        )

    @property
    def tables(self) -> Dict[str, TableInfo]:
        return dict(self._tables)

    def declared_columns(self, table: str) -> Tuple[str, ...]:
        """A table's columns in DECLARATION order, cached per sqlite
        ``schema_version`` (which bumps on any DDL — runtime ALTERs
        over the wire invalidate the cache; one scalar PRAGMA per
        call otherwise)."""
        _, rows = self.read_query("PRAGMA schema_version")
        sv = rows[0][0]
        cached_sv, cols_by_table = getattr(
            self, "_declared_cols_cache", (None, {})
        )
        if cached_sv != sv:
            cols_by_table = {}
            self._declared_cols_cache = (sv, cols_by_table)
        if table not in cols_by_table:
            try:
                _, info = self.read_query(
                    f'PRAGMA table_info("{_ident(table)}")'
                )
            except (sqlite3.Error, ValueError):
                return ()
            cols_by_table[table] = tuple(r[1] for r in info)
        return cols_by_table[table]

    # ------------------------------------------------------------------
    # site interning
    # ------------------------------------------------------------------

    def site_ordinal(self, site_id: bytes) -> int:
        with self._lock:
            row = self.conn.execute(
                "SELECT ordinal FROM __corro_sites WHERE site_id = ?", (site_id,)
            ).fetchone()
            if row:
                return row[0]
            cur = self.conn.execute(
                "INSERT INTO __corro_sites (site_id) VALUES (?)", (site_id,)
            )
            return cur.lastrowid

    def site_for_ordinal(self, ordinal: int) -> bytes:
        row = self.conn.execute(
            "SELECT site_id FROM __corro_sites WHERE ordinal = ?", (ordinal,)
        ).fetchone()
        if row is None:
            raise KeyError(f"unknown site ordinal {ordinal}")
        return bytes(row[0])

    # ------------------------------------------------------------------
    # CRR setup (crsql_as_crr)
    # ------------------------------------------------------------------

    def as_crr(self, table: str) -> None:
        t = _ident(table)
        info = self._introspect(t)
        c = self.conn
        c.execute(
            f'CREATE TABLE IF NOT EXISTS "{t}__corro_clock" ('
            " pk BLOB NOT NULL, cid TEXT NOT NULL,"
            " col_version INTEGER NOT NULL, db_version INTEGER NOT NULL,"
            " seq INTEGER NOT NULL, site_ordinal INTEGER NOT NULL,"
            " PRIMARY KEY (pk, cid))"
        )
        c.execute(
            f'CREATE INDEX IF NOT EXISTS "{t}__corro_clock_dbv" '
            f'ON "{t}__corro_clock" (site_ordinal, db_version)'
        )
        # `sentinel`: 1 when the row-level event must ship as a '-1'
        # sentinel change (delete, resurrect, pk move, pk-only insert) —
        # plain inserts of tables with cells carry the row via cell rows
        # alone, matching cr-sqlite's clock contents exactly.
        c.execute(
            f'CREATE TABLE IF NOT EXISTS "{t}__corro_cl" ('
            " pk BLOB NOT NULL PRIMARY KEY, cl INTEGER NOT NULL,"
            " db_version INTEGER NOT NULL, seq INTEGER NOT NULL,"
            " site_ordinal INTEGER NOT NULL,"
            " sentinel INTEGER NOT NULL DEFAULT 0)"
        )
        have_cols = {
            r[1] for r in c.execute(f'PRAGMA table_info("{t}__corro_cl")')
        }
        if "sentinel" not in have_cols:
            c.execute(
                f'ALTER TABLE "{t}__corro_cl" '
                "ADD COLUMN sentinel INTEGER NOT NULL DEFAULT 0"
            )
        c.execute(
            f'CREATE INDEX IF NOT EXISTS "{t}__corro_cl_dbv" '
            f'ON "{t}__corro_cl" (site_ordinal, db_version)'
        )
        # expression index on the packed pk: change collection joins the
        # data table ON corro_pack(pk cols) = clock.pk — without this the
        # join is a per-clock-row full scan (quadratic in table size)
        pack_expr = "corro_pack(" + ", ".join(
            f'"{p}"' for p in info.pk_cols
        ) + ")"
        c.execute(
            f'CREATE INDEX IF NOT EXISTS "{t}__corro_packpk" '
            f'ON "{t}" ({pack_expr})'
        )
        self._create_triggers(info)
        self._create_impact_triggers(t)
        c.execute("INSERT OR IGNORE INTO __corro_crr_tables VALUES (?)", (t,))
        self._tables[t] = info
        if self.device_cache is not None:
            # (re-)declaring a CRR changes the cid ordinal space the
            # cache packs its keys with — drop its view of this table
            self.device_cache.invalidate_table(t)
        self._backfill(info)

    def _create_impact_triggers(self, t: str) -> None:
        """Record local (site_ordinal=1) versions whose change rows get
        overwritten or deleted, for compaction.

        Parity: the reference's clock-change triggers
        (``create_clock_change_trigger``, agent.rs:570-592) watch only
        local rows; cl entries matter only when they ship as sentinels.
        """
        imp = ("INSERT INTO __corro_versions_impacted (site_ordinal, "
               "db_version) VALUES (OLD.site_ordinal, OLD.db_version) "
               "ON CONFLICT (site_ordinal, db_version) DO NOTHING;")
        self.conn.executescript(f"""
CREATE TRIGGER IF NOT EXISTS "{t}__corro_impact_clock_upd"
AFTER UPDATE ON "{t}__corro_clock" FOR EACH ROW
WHEN OLD.site_ordinal = 1 AND (OLD.site_ordinal != NEW.site_ordinal
  OR OLD.db_version != NEW.db_version)
BEGIN
  {imp}
END;
CREATE TRIGGER IF NOT EXISTS "{t}__corro_impact_clock_del"
AFTER DELETE ON "{t}__corro_clock" FOR EACH ROW
WHEN OLD.site_ordinal = 1
BEGIN
  {imp}
END;
CREATE TRIGGER IF NOT EXISTS "{t}__corro_impact_cl_upd"
AFTER UPDATE ON "{t}__corro_cl" FOR EACH ROW
WHEN OLD.site_ordinal = 1 AND OLD.sentinel = 1
  AND (OLD.site_ordinal != NEW.site_ordinal
       OR OLD.db_version != NEW.db_version)
BEGIN
  {imp}
END;
CREATE TRIGGER IF NOT EXISTS "{t}__corro_impact_cl_del"
AFTER DELETE ON "{t}__corro_cl" FOR EACH ROW
WHEN OLD.site_ordinal = 1 AND OLD.sentinel = 1
BEGIN
  {imp}
END;
""")

    def overwritten_local_db_versions(self) -> Tuple[bool, List[int]]:
        """(any_impacted, gone): impacted local db_versions that no longer
        have ANY change row (cell clock or sentinel cl) — fully
        overwritten, compactable.  Read-only; the caller deletes
        __corro_versions_impacted in its transaction
        (``find_overwritten_versions`` parity)."""
        with self._lock:
            impacted = [
                r[0] for r in self.conn.execute(
                    "SELECT db_version FROM __corro_versions_impacted "
                    "WHERE site_ordinal = 1"
                )
            ]
            if not impacted:
                return False, []
            gone = []
            for dbv in impacted:
                exists = False
                for t in self._tables:
                    if self.conn.execute(
                        f'SELECT 1 FROM "{t}__corro_clock" '
                        "WHERE site_ordinal = 1 AND db_version = ? LIMIT 1",
                        (dbv,),
                    ).fetchone() or self.conn.execute(
                        f'SELECT 1 FROM "{t}__corro_cl" '
                        "WHERE site_ordinal = 1 AND sentinel = 1 "
                        "AND db_version = ? LIMIT 1",
                        (dbv,),
                    ).fetchone():
                        exists = True
                        break
                if not exists:
                    gone.append(dbv)
            return True, gone

    def _backfill(self, info: TableInfo) -> None:
        """Stamp rows that predate as_crr (or a new column) into the clock
        tables so they replicate.

        Parity: cr-sqlite's ``crsql_as_crr`` backfills existing rows —
        pinned by the golden probe: every pre-existing cell gets
        col_version=1 stamped with one freshly allocated db_version and
        sequential seqs.  Idempotent: only missing cl rows / clock cells
        are filled, so re-running after ALTER TABLE ADD COLUMN backfills
        just the new column.
        """
        t = info.name
        d_pk = "corro_pack(" + ", ".join(f'd."{p}"' for p in info.pk_cols) + ")"
        with self._lock:
            missing_rows = [
                bytes(r[0]) for r in self.conn.execute(
                    f'SELECT {d_pk} FROM "{t}" d '
                    f'LEFT JOIN "{t}__corro_cl" c ON c.pk = {d_pk} '
                    "WHERE c.pk IS NULL"
                )
            ]
            missing_cells = []  # (pk, cid)
            for col in info.data_cols:
                missing_cells.extend(
                    (bytes(r[0]), col) for r in self.conn.execute(
                        f'SELECT {d_pk} FROM "{t}" d '
                        f'LEFT JOIN "{t}__corro_clock" k '
                        f"ON k.pk = {d_pk} AND k.cid = ? "
                        "WHERE k.pk IS NULL",
                        (col,),
                    )
                )
            if not missing_rows and not missing_cells:
                return
            self.conn.execute("BEGIN IMMEDIATE")
            try:
                pending = self._state("db_version") + 1
                seq = 0
                for pk in missing_rows:
                    # pk-only rows replicate via sentinels (consume a seq
                    # slot); cell-bearing rows ride their cells alone
                    sentinel = 0 if info.data_cols else 1
                    self.conn.execute(
                        f'INSERT OR IGNORE INTO "{t}__corro_cl" '
                        "(pk, cl, db_version, seq, site_ordinal, sentinel) "
                        "VALUES (?, 1, ?, ?, 1, ?)",
                        (pk, pending, seq if sentinel else 0, sentinel),
                    )
                    if sentinel:
                        seq += 1
                for pk, cid in missing_cells:
                    self.conn.execute(
                        f'INSERT OR IGNORE INTO "{t}__corro_clock" '
                        "(pk, cid, col_version, db_version, seq, site_ordinal) "
                        "VALUES (?, ?, 1, ?, ?, 1)",
                        (pk, cid, pending, seq),
                    )
                    seq += 1
                self._set_state("db_version", pending)
                # durable pending-registration record: survives a crash
                # between this COMMIT and the agent registering the
                # version in its bookkeeping (drained transactionally)
                self.conn.execute(
                    "INSERT INTO __corro_backfills (db_version, last_seq) "
                    "VALUES (?, ?)",
                    (pending, seq - 1),
                )
            except BaseException:
                self.conn.execute("ROLLBACK")
                raise
            self.conn.execute("COMMIT")

    def peek_backfills(self) -> List[Tuple[int, int]]:
        with self._lock:
            return [
                (r[0], r[1]) for r in self.conn.execute(
                    "SELECT db_version, last_seq FROM __corro_backfills "
                    "ORDER BY db_version"
                )
            ]

    def clear_backfills(self) -> None:
        """Delete the pending-backfill records (inside the caller's tx)."""
        self.conn.execute("DELETE FROM __corro_backfills")

    def _create_triggers(self, info: TableInfo) -> None:
        t = info.name
        new_pk = "corro_pack(" + ", ".join(f'NEW."{p}"' for p in info.pk_cols) + ")"
        old_pk = "corro_pack(" + ", ".join(f'OLD."{p}"' for p in info.pk_cols) + ")"
        pending = "(SELECT value FROM __corro_state WHERE key='pending_db_version')"
        seq_now = "(SELECT value FROM __corro_state WHERE key='seq') - 1"
        not_applying = "(SELECT value FROM __corro_state WHERE key='apply_mode') = 0"
        bump_seq = "UPDATE __corro_state SET value = value + 1 WHERE key='seq'"

        def cell_upsert(pk_expr: str, col: str, guard: str = "") -> str:
            return (
                f"{bump_seq}{guard};\n"
                f'INSERT INTO "{t}__corro_clock" '
                "(pk, cid, col_version, db_version, seq, site_ordinal) "
                f"SELECT {pk_expr}, '{col}', 1, {pending}, {seq_now}, 1 "
                f"WHERE 1=1{guard} "
                "ON CONFLICT(pk, cid) DO UPDATE SET "
                "col_version = col_version + 1, "
                "db_version = excluded.db_version, "
                "seq = excluded.seq, site_ordinal = 1;"
            )

        ins_cells = "\n".join(cell_upsert(new_pk, c) for c in info.data_cols)
        upd_cells = "\n".join(
            cell_upsert(new_pk, c, f' AND NEW."{c}" IS NOT OLD."{c}"')
            for c in info.data_cols
        )
        cl_tbl = f'"{t}__corro_cl"'

        # Sentinel lifecycle pinned against cr-sqlite's clock contents
        # (tests/test_crsqlite_golden.py probes): a fresh insert of a
        # table WITH cells creates a non-sentinel cl entry that consumes
        # no seq slot (cells alone carry the row, seqs 0..n-1 exactly
        # like the reference); deletes, resurrects, pk moves, and
        # pk-only-table inserts produce sentinel entries that do consume
        # a seq slot and ship as '-1' changes.
        if info.data_cols:
            ins_row = f"""
  UPDATE __corro_state SET value = value + 1 WHERE key='seq'
    AND EXISTS (SELECT 1 FROM {cl_tbl} WHERE pk = {new_pk} AND cl % 2 = 0);
  UPDATE {cl_tbl} SET cl = cl + 1, db_version = {pending},
      seq = {seq_now}, site_ordinal = 1, sentinel = 1
    WHERE pk = {new_pk} AND cl % 2 = 0;
  INSERT OR IGNORE INTO {cl_tbl}
      (pk, cl, db_version, seq, site_ordinal, sentinel)
    VALUES ({new_pk}, 1, {pending}, 0, 1, 0);"""
        else:
            ins_row = f"""
  {bump_seq};
  INSERT INTO {cl_tbl} (pk, cl, db_version, seq, site_ordinal, sentinel)
    VALUES ({new_pk}, 1, {pending}, {seq_now}, 1, 1)
    ON CONFLICT(pk) DO UPDATE SET
      cl = CASE WHEN cl % 2 = 0 THEN cl + 1 ELSE cl END,
      db_version = excluded.db_version,
      seq = excluded.seq, site_ordinal = 1, sentinel = 1;"""

        # Primary-key updates change the row's identity: the old pk gets a
        # delete sentinel (even cl), the new pk an insert sentinel (odd
        # cl), both in the current version, and existing cell clock rows
        # are re-keyed in place keeping their original (db_version, seq)
        # stamps — so a delta-only transfer of the new version carries
        # just the sentinels (and heals fully via anti-entropy), exactly
        # like the reference extension.  The re-key uses an explicit
        # DELETE of conflicting target rows (NOT `UPDATE OR REPLACE`):
        # REPLACE conflict-deletes skip AFTER DELETE triggers, which
        # would lose the compaction impact record for the displaced rows.
        pk_moved = f"{new_pk} IS NOT {old_pk}"
        pk_move = f"""
  UPDATE __corro_state SET value = value + 1 WHERE key='seq' AND {pk_moved};
  INSERT INTO {cl_tbl} (pk, cl, db_version, seq, site_ordinal, sentinel)
    SELECT {old_pk}, 2, {pending}, {seq_now}, 1, 1 WHERE {pk_moved}
    ON CONFLICT(pk) DO UPDATE SET
      cl = CASE WHEN cl % 2 = 1 THEN cl + 1 ELSE cl END,
      db_version = excluded.db_version,
      seq = excluded.seq, site_ordinal = 1, sentinel = 1;
  UPDATE __corro_state SET value = value + 1 WHERE key='seq' AND {pk_moved};
  INSERT INTO {cl_tbl} (pk, cl, db_version, seq, site_ordinal, sentinel)
    SELECT {new_pk}, 1, {pending}, {seq_now}, 1, 1 WHERE {pk_moved}
    ON CONFLICT(pk) DO UPDATE SET
      cl = CASE WHEN cl % 2 = 0 THEN cl + 1 ELSE cl END,
      db_version = excluded.db_version,
      seq = excluded.seq, site_ordinal = 1, sentinel = 1;
  DELETE FROM "{t}__corro_clock" WHERE pk = {new_pk} AND {pk_moved};
  UPDATE "{t}__corro_clock" SET pk = {new_pk}
    WHERE pk = {old_pk} AND {pk_moved};"""

        self.conn.executescript(
            f"""
DROP TRIGGER IF EXISTS "{t}__corro_ins";
CREATE TRIGGER "{t}__corro_ins" AFTER INSERT ON "{t}"
WHEN {not_applying}
BEGIN
  {ins_row}
  {ins_cells}
END;
DROP TRIGGER IF EXISTS "{t}__corro_upd";
CREATE TRIGGER "{t}__corro_upd" AFTER UPDATE ON "{t}"
WHEN {not_applying}
BEGIN
  {pk_move}
  {upd_cells}
END;
DROP TRIGGER IF EXISTS "{t}__corro_del";
CREATE TRIGGER "{t}__corro_del" AFTER DELETE ON "{t}"
WHEN {not_applying}
BEGIN
  {bump_seq};
  INSERT INTO {cl_tbl} (pk, cl, db_version, seq, site_ordinal, sentinel)
    VALUES ({old_pk}, 2, {pending}, {seq_now}, 1, 1)
    ON CONFLICT(pk) DO UPDATE SET
      cl = CASE WHEN cl % 2 = 1 THEN cl + 1 ELSE cl END,
      db_version = excluded.db_version,
      seq = excluded.seq, site_ordinal = 1, sentinel = 1;
  DELETE FROM "{t}__corro_clock" WHERE pk = {old_pk};
END;
"""
        )

    # ------------------------------------------------------------------
    # versions & transactions
    # ------------------------------------------------------------------

    def db_version(self) -> int:
        """Last committed local db_version (crsql: current db version)."""
        with self._lock:
            return self._state("db_version")

    def next_db_version(self) -> int:
        return self.db_version() + 1

    def _state(self, key: str) -> int:
        (v,) = self.conn.execute(
            "SELECT value FROM __corro_state WHERE key=?", (key,)
        ).fetchone()
        return v

    def _set_state(self, key: str, value: int) -> None:
        self.conn.execute(
            "UPDATE __corro_state SET value=? WHERE key=?", (value, key)
        )

    def begin_write_batch(self) -> int:
        """Arm the trigger state for one local write batch inside an
        already-open transaction: allocate the next pending db_version
        and reset the seq counter.  Returns the pending db_version.
        Shared by :meth:`write_tx` (one batch per transaction) and the
        group-commit combiner (one batch per SAVEPOINT inside a shared
        outer transaction — ``runtime._run_write_group_locked``); the
        caller commits the allocation by setting ``db_version`` to the
        returned value iff the batch produced changes."""
        self._io_delay("write")
        pending = self._state("db_version") + 1
        self._set_state("pending_db_version", pending)
        self._set_state("seq", 0)
        return pending

    @contextmanager
    def write_tx(self):
        """One local transaction == at most one allocated db_version.

        Mirrors cr-sqlite: the version is only consumed if the transaction
        actually produced changes.  Client writes take the HIGH tier —
        the reference's API write path acquires ``write_priority()``
        (``api/public/mod.rs:59``) so users aren't queued behind
        replication or maintenance.
        """
        from corrosion_tpu.agent.locks import PRIO_HIGH

        with self._lock.prio(PRIO_HIGH, "write", kind="write"):
            # local-write triggers read the clock tables (col_version
            # continuation), so any staged-but-unflushed winner must
            # land first; after COMMIT the trigger-written clocks make
            # the cache view stale — the write-combiner invalidation
            self._wb_drain_locked()
            self.conn.execute("BEGIN IMMEDIATE")
            pending = self.begin_write_batch()
            try:
                yield self.conn
            except BaseException:
                # an interrupt (CancelRequest) or constraint abort may
                # have rolled the tx back already; a second ROLLBACK
                # would mask the real error with "cannot rollback"
                if self.conn.in_transaction:
                    self.conn.execute("ROLLBACK")
                raise
            wrote = self._state("seq") > 0
            if wrote:
                self._set_state("db_version", pending)
            self.conn.execute("COMMIT")
            if wrote and self.device_cache is not None:
                self.device_cache.invalidate_all("local_write")
                self._emit_cache_metrics()

    def speculative_read(self, writes: Sequence, sql: str,
                         params: Sequence = ()):
        """Evaluate ``sql`` as if ``writes`` had been applied, then roll
        everything back — read-your-writes for a buffered interactive
        transaction (the PG session's BEGIN..COMMIT, which holds no
        lock across client round trips; PG's READ COMMITTED lets later
        committed state show between reads).

        The sandbox mirrors ``write_tx``'s state setup so the CRR
        triggers fire normally; ROLLBACK reverts data, clock tables and
        ``__corro_state`` alike (all same-database rows).  Cost is
        O(buffered writes) per read, bounded by the transaction size.
        """
        from corrosion_tpu.agent.locks import PRIO_HIGH

        with self._lock.prio(PRIO_HIGH, "speculative-read", kind="write"):
            # the sandbox fires the CRR triggers, which read the clock
            # tables — staged-but-unflushed winners must land first
            self._wb_drain_locked()
            self.conn.execute("BEGIN")
            try:
                pending = self._state("db_version") + 1
                self._set_state("pending_db_version", pending)
                self._set_state("seq", 0)
                for stmt in writes:
                    w_sql, w_params = unpack_stmt(stmt)
                    self.conn.execute(w_sql, w_params)
                cur = self.conn.execute(sql, tuple(params))
                cols = [d[0] for d in cur.description or []]
                rows = cur.fetchall()
                return cols, rows
            finally:
                # a constraint abort may have auto-rolled-back already;
                # a second ROLLBACK would mask the real error
                if self.conn.in_transaction:
                    self.conn.execute("ROLLBACK")

    def execute(self, sql: str, params: Sequence = ()):
        """Run one write statement in its own transaction."""
        with self.write_tx() as conn:
            return conn.execute(sql, params)

    # ------------------------------------------------------------------
    # change collection (the SELECT side of crsql_changes)
    # ------------------------------------------------------------------

    def collect_changes(
        self,
        db_version_range: Tuple[int, int],
        site_id: Optional[bytes] = None,
    ) -> List[Change]:
        """All cell changes stamped with a db_version in the inclusive
        range, for one origin site (default: local)."""
        with self._lock:
            # barrier: collection reads the clock tables, which lag the
            # merge while the write-behind queue is non-empty
            self._wb_drain_locked()
            ordinal = 1 if site_id is None else self.site_ordinal(site_id)
            origin = self.site_id if site_id is None else site_id
            return self._collect_changes_on(
                self.conn, ordinal, origin, db_version_range
            )

    def site_ordinal_ro(self, conn, site_id: bytes) -> Optional[int]:
        """Read-only ordinal lookup on an explicit connection (no
        interning, no storage lock); None if the site was never seen."""
        row = conn.execute(
            "SELECT ordinal FROM __corro_sites WHERE site_id = ?",
            (site_id,),
        ).fetchone()
        return row[0] if row else None

    def collect_changes_ro(
        self,
        conn,
        db_version_range: Tuple[int, int],
        site_id: Optional[bytes] = None,
    ) -> List[Change]:
        """:meth:`collect_changes` on an explicit (read-only pool)
        connection, WITHOUT taking the storage lock — the sync serve
        path's off-loop range collection.  The site must already be
        interned (it is for any site we hold versions of); an unknown
        site collects nothing."""
        # write-behind barrier (docs/crdts.md ordering contract): any
        # version announced to a peer was journaled + enqueued inside
        # its apply commit, so draining here guarantees the serve read
        # never observes an unflushed winner for a requested version
        self.flush_barrier()
        if site_id is None:
            ordinal: Optional[int] = 1
            origin = self.site_id
        else:
            ordinal = self.site_ordinal_ro(conn, site_id)
            origin = site_id
        if ordinal is None:
            return []
        return self._collect_changes_on(
            conn, ordinal, origin, db_version_range
        )

    def _collect_changes_on(
        self, conn, ordinal: int, origin: bytes,
        db_version_range: Tuple[int, int],
    ) -> List[Change]:
        """Shared body: one sentinel + one cell query per table over the
        whole inclusive db_version range, sorted (db_version, seq)."""
        self._io_delay("read")
        lo, hi = db_version_range
        out: List[Change] = []
        for t, info in self._tables.items():
            # row-level '-1' sentinel changes: exactly the cl entries
            # flagged sentinel (deletes, resurrects, pk moves, pk-only
            # inserts) — plain inserts of cell-bearing tables ride
            # their cell rows alone, matching cr-sqlite's change
            # streams (pinned in tests/test_crsqlite_golden.py).
            for pk, cl, dbv, seq in conn.execute(
                f'SELECT pk, cl, db_version, seq FROM "{t}__corro_cl" '
                "WHERE site_ordinal=? AND db_version BETWEEN ? AND ? "
                "AND sentinel = 1",
                (ordinal, lo, hi),
            ):
                out.append(
                    Change(
                        table=t,
                        pk=bytes(pk),
                        cid=SENTINEL_CID,
                        val=None,
                        col_version=cl,
                        db_version=CrsqlDbVersion(dbv),
                        seq=CrsqlSeq(seq),
                        site_id=origin,
                        cl=cl,
                    )
                )
            if not info.data_cols:
                continue  # no cells to collect
            # cell-level rows with current values, one JOIN per table:
            # cl from the causal-length table, the live value picked out
            # of the data row by a generated CASE over the column name
            val_case = (
                "CASE k.cid "
                + " ".join(f"WHEN '{c}' THEN d.\"{c}\"" for c in info.data_cols)
                + " END"
            )
            d_pk = "corro_pack(" + ", ".join(f'd."{p}"' for p in info.pk_cols) + ")"
            for pk, cid, colv, dbv, seq, cl, val in conn.execute(
                f"SELECT k.pk, k.cid, k.col_version, k.db_version, k.seq,"
                f" COALESCE(c.cl, 1), {val_case} "
                f'FROM "{t}__corro_clock" k '
                f'LEFT JOIN "{t}__corro_cl" c ON c.pk = k.pk '
                f'LEFT JOIN "{t}" d ON {d_pk} = k.pk '
                "WHERE k.site_ordinal=? AND k.db_version BETWEEN ? AND ?",
                (ordinal, lo, hi),
            ):
                out.append(
                    Change(
                        table=t,
                        pk=bytes(pk),
                        cid=cid,
                        val=val,
                        col_version=colv,
                        db_version=CrsqlDbVersion(dbv),
                        seq=CrsqlSeq(seq),
                        site_id=origin,
                        cl=cl,
                    )
                )
        out.sort(key=lambda ch: (int(ch.db_version), int(ch.seq)))
        return out

    def changes_for_version(self, db_version: int, site_id: Optional[bytes] = None):
        return self.collect_changes((db_version, db_version), site_id)

    def _row_cl(self, table: str, pk: bytes) -> int:
        row = self._row_cl_entry(table, pk)
        return row[0] if row else 1

    def _cell_value(self, info: TableInfo, pk: bytes, cid: str):
        pk_vals = unpack_values(pk)
        where = " AND ".join(f'"{p}" IS ?' for p in info.pk_cols)
        row = self.conn.execute(
            f'SELECT "{_ident(cid)}" FROM "{info.name}" WHERE {where}', pk_vals
        ).fetchone()
        return row[0] if row else None

    # ------------------------------------------------------------------
    # change application (the INSERT side of crsql_changes: the merge)
    # ------------------------------------------------------------------

    @contextmanager
    def apply_tx(self):
        """Open one merge transaction; bookkeeping writes through the same
        connection commit atomically with the applied changes.  Applies
        take the NORMAL write tier — the reference runs
        ``process_multiple_changes`` on ``write_normal()``
        (``agent/util.rs:814``), below client API writes
        (``write_priority()``) and above maintenance (``write_low()``),
        so local writers stay responsive while replication streams in."""
        from corrosion_tpu.agent.locks import PRIO_NORMAL

        with self._lock.prio(PRIO_NORMAL, "apply", kind="apply"):
            self._io_delay("write")
            self.conn.execute("BEGIN IMMEDIATE")
            try:
                self._set_state("apply_mode", 1)
                yield self.conn
            except BaseException:
                try:
                    self._set_state("apply_mode", 0)
                finally:
                    # see write_tx: the tx may have auto-rolled-back
                    if self.conn.in_transaction:
                        self.conn.execute("ROLLBACK")
                    self._tx_finish(False)
                raise
            self._set_state("apply_mode", 0)
            self.conn.execute("COMMIT")
            self._tx_finish(True)

    def apply_changes_in_tx(self, changes: Iterable[Change]) -> int:
        """Merge changes inside an open ``apply_tx``; returns rows impacted.

        Dispatches to the batched pipeline beyond a couple of changes —
        semantics are pinned identical to the per-change path by the
        randomized parity suite (tests/test_apply_batched.py)."""
        changes = list(changes)
        if len(changes) <= 2:
            return self._apply_small_in_tx(changes)
        return self._apply_changes_batched(changes)

    def _apply_small_in_tx(self, changes: List[Change]) -> int:
        """Per-change path with device-cache hygiene: ``_apply_one``
        reads and writes the clock tables directly, so staged state for
        the touched rows must flush first and the cache must forget
        them afterwards (their DB state changed behind its back)."""
        if self.device_cache is not None and changes:
            touched: Dict[str, List[bytes]] = {}
            for ch in changes:
                touched.setdefault(ch.table, []).append(ch.pk)
            for t, t_pks in touched.items():
                self._wb_overlap_flush_in_tx(t, t_pks)
            n = sum(self._apply_one(ch) for ch in changes)
            for t, t_pks in touched.items():
                self.device_cache.invalidate_pks(
                    t, t_pks, reason="small_apply"
                )
            return n
        return sum(self._apply_one(ch) for ch in changes)

    def apply_changes_sequential_in_tx(self, changes: Iterable[Change]) -> int:
        """The per-change reference path (one row-CL lookup + cell write +
        clock upsert per change).  Kept as the parity oracle for the
        batched pipeline and the ``bench.py --apply`` baseline."""
        changes = list(changes)
        if self.device_cache is not None:
            return self._apply_small_in_tx(changes)
        return sum(self._apply_one(ch) for ch in changes)

    def apply_changes(self, changes: Iterable[Change]) -> int:
        """Merge remote changes in their own transaction."""
        with self.apply_tx():
            return self.apply_changes_in_tx(changes)

    def apply_changes_batched(self, changes: Iterable[Change]) -> int:
        """Merge remote changes in their own transaction, always through
        the batched pipeline (no small-batch dispatch)."""
        with self.apply_tx():
            return self._apply_changes_batched(list(changes))

    def _apply_one(self, ch: Change) -> int:
        info = self._tables.get(ch.table)
        if info is None:
            return 0
        t = info.name
        ordinal = self.site_ordinal(ch.site_id)
        local_cl = self._row_cl_entry(t, ch.pk)

        if ch.cid == SENTINEL_CID:
            # row-level: delete (even cl) or bare resurrect marker
            if local_cl is not None and ch.cl <= local_cl[0]:
                return 0
            self._set_row_cl(
                t, ch.pk, ch.cl, ch.db_version, ch.seq, ordinal, sentinel=1
            )
            if ch.is_delete():
                self._delete_row(info, ch.pk)
                self.conn.execute(
                    f'DELETE FROM "{t}__corro_clock" WHERE pk=?', (ch.pk,)
                )
            else:
                # a new row generation: previous-generation cells are gone
                self._reset_row(info, ch.pk)
                self.conn.execute(
                    f'DELETE FROM "{t}__corro_clock" WHERE pk=?', (ch.pk,)
                )
            return 1

        # cell-level change
        have_cl = local_cl[0] if local_cl is not None else None
        if have_cl is not None and ch.cl < have_cl:
            return 0  # stale: our row history is causally ahead
        if have_cl is None or ch.cl > have_cl:
            # the change's row generation is ahead of ours: adopt it, and
            # reset the row so previous-generation cell values (now
            # untracked) can't linger in the data table
            self._set_row_cl(t, ch.pk, ch.cl, ch.db_version, ch.seq, ordinal)
            if ch.cl % 2 == 0:
                self._delete_row(info, ch.pk)
                self.conn.execute(
                    f'DELETE FROM "{t}__corro_clock" WHERE pk=?', (ch.pk,)
                )
                return 1
            self._reset_row(info, ch.pk)
            self.conn.execute(
                f'DELETE FROM "{t}__corro_clock" WHERE pk=?', (ch.pk,)
            )
        elif ch.cl % 2 == 0:
            return 0  # equal even cl: row already deleted
        else:
            self._ensure_row(info, ch.pk)

        # LWW on the cell
        row = self.conn.execute(
            f'SELECT col_version FROM "{t}__corro_clock" WHERE pk=? AND cid=?',
            (ch.pk, ch.cid),
        ).fetchone()
        if row is not None:
            local_ver = row[0]
            if ch.col_version < local_ver:
                return 0
            if ch.col_version == local_ver:
                cur = self._cell_value(info, ch.pk, ch.cid)
                if value_cmp(ch.val, cur) <= 0:
                    return 0
        self._write_cell(info, ch.pk, ch.cid, ch.val)
        self.conn.execute(
            f'INSERT INTO "{t}__corro_clock" '
            "(pk, cid, col_version, db_version, seq, site_ordinal) "
            "VALUES (?, ?, ?, ?, ?, ?) "
            "ON CONFLICT(pk, cid) DO UPDATE SET "
            "col_version=excluded.col_version, db_version=excluded.db_version,"
            "seq=excluded.seq, site_ordinal=excluded.site_ordinal",
            (ch.pk, ch.cid, ch.col_version, int(ch.db_version), int(ch.seq), ordinal),
        )
        return 1

    # -- batched application --------------------------------------------
    #
    # The ingest hot path: same merge as _apply_one, restructured around
    # batches — group per table, intern sites in first-appearance order,
    # prefetch row-CL / clock / current cell values with one IN (...)
    # query per table per kind, merge in memory (superseded cells
    # coalesce to the causally-winning write), then flush the net state
    # with executemany on per-(table, cid) cached SQL strings.  Final DB
    # state (data, clock, cl, impact records, site ordinals) is
    # identical to applying the same stream through _apply_one — pinned
    # by tests/test_apply_batched.py.
    #
    # Contract: change values are AFFINITY-STABLE for their columns —
    # the invariant every collect_changes-produced stream holds, since
    # an origin ships the value it already stored (post-affinity).  A
    # hostile stream writing e.g. an INTEGER into a TEXT column can make
    # this path diverge from _apply_one only on exact-value LWW ties,
    # where _apply_one compares against sqlite's affinity-converted
    # read-back while the in-batch winner here is the raw wire value.

    _PREFETCH_CHUNK = 500  # bound parameters per IN (...) query

    def _apply_sql(self, key: Tuple) -> str:
        """Cached SQL text for the batched flush statements; identical
        strings also let sqlite3's per-connection statement cache reuse
        prepared statements across batches."""
        cache = getattr(self, "_apply_sql_cache", None)
        if cache is None:
            cache = self._apply_sql_cache = {}
        sql = cache.get(key)
        if sql is None:
            kind, t = key[0], key[1]
            info = self._tables[t]
            pk_where = " AND ".join(f'"{p}" IS ?' for p in info.pk_cols)
            if kind == "cell_upd":
                sets = ", ".join(f'"{_ident(c)}" = ?' for c in key[2])
                sql = f'UPDATE "{t}" SET {sets} WHERE {pk_where}'
            elif kind == "row_del":
                sql = f'DELETE FROM "{t}" WHERE {pk_where}'
            elif kind == "row_ins":
                cols = ", ".join(f'"{p}"' for p in info.pk_cols)
                ph = ", ".join("?" for _ in info.pk_cols)
                sql = f'INSERT OR IGNORE INTO "{t}" ({cols}) VALUES ({ph})'
            elif kind == "row_ins_fused":
                names = list(info.pk_cols) + [_ident(c) for c in key[2]]
                cols = ", ".join(f'"{c}"' for c in names)
                ph = ", ".join("?" for _ in names)
                sql = f'INSERT OR IGNORE INTO "{t}" ({cols}) VALUES ({ph})'
            elif kind == "clock_ins":
                # plain INSERT: the caller proved no conflicting row can
                # exist (generation replaced, or absent in the prefetch);
                # a violated invariant fails loud instead of diverging
                sql = (
                    f'INSERT INTO "{t}__corro_clock" '
                    "(pk, cid, col_version, db_version, seq, site_ordinal) "
                    "VALUES (?, ?, ?, ?, ?, ?)"
                )
            elif kind == "clock_del":
                sql = f'DELETE FROM "{t}__corro_clock" WHERE pk=?'
            elif kind == "clock_ups":
                sql = (
                    f'INSERT INTO "{t}__corro_clock" '
                    "(pk, cid, col_version, db_version, seq, site_ordinal) "
                    "VALUES (?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT(pk, cid) DO UPDATE SET "
                    "col_version=excluded.col_version, "
                    "db_version=excluded.db_version,"
                    "seq=excluded.seq, site_ordinal=excluded.site_ordinal"
                )
            elif kind == "cl_ins":
                # plain INSERT: no cl entry existed for this pk at batch
                # start (prefetch-proved), so no conflict is possible
                sql = (
                    f'INSERT INTO "{t}__corro_cl" '
                    "(pk, cl, db_version, seq, site_ordinal, sentinel) "
                    "VALUES (?, ?, ?, ?, ?, ?)"
                )
            elif kind == "cl_ups":
                sql = (
                    f'INSERT INTO "{t}__corro_cl" '
                    "(pk, cl, db_version, seq, site_ordinal, sentinel) "
                    "VALUES (?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT(pk) DO UPDATE SET cl=excluded.cl, "
                    "db_version=excluded.db_version, seq=excluded.seq, "
                    "site_ordinal=excluded.site_ordinal, "
                    "sentinel=MAX(sentinel, excluded.sentinel)"
                )
            else:  # pragma: no cover - programming error
                raise KeyError(kind)
            cache[key] = sql
        return sql

    #: rows per multi-row VALUES statement are sized to stay under
    #: sqlite's default 999 bound-parameter limit
    _MULTIROW_PARAMS = 900

    def _flush_insert(self, key: Tuple, rows: List[Sequence]) -> None:
        """Flush one INSERT-shaped statement kind with multi-row
        ``VALUES (...), (...)`` batching (~30% fewer statement-dispatch
        cycles than per-row executemany at 10k rows; multi-row upserts
        apply per row exactly like their single-row form).  Non-insert
        shapes and small flushes fall through to plain executemany on
        the cached single-row SQL."""
        if not rows:
            return
        sql = self._apply_sql(key)
        cache = getattr(self, "_apply_sql_cache", None)
        i = 0
        head = sql.find(" VALUES (")
        if head >= 0 and len(rows) > 1:
            row_ph = sql[head + 8 : sql.index(")", head) + 1]
            width = row_ph.count("?")
            k = max(1, self._MULTIROW_PARAMS // max(1, width))
            if k > 1 and len(rows) >= k:
                msql = cache.get((key, k))
                if msql is None:
                    msql = cache[(key, k)] = (
                        sql[: head + 8]
                        + ",".join([row_ph] * k)
                        + sql[head + 8 + len(row_ph):]
                    )
                while i + k <= len(rows):
                    self.conn.execute(
                        msql,
                        [x for r in rows[i : i + k] for x in r],
                    )
                    i += k
        if i < len(rows):
            self.conn.executemany(sql, rows[i:])

    def _apply_changes_batched(self, changes: List[Change]) -> int:
        by_table: Dict[str, List[Change]] = {}
        ordinals: Dict[bytes, int] = {}
        for ch in changes:
            if ch.table not in self._tables:
                continue
            by_table.setdefault(ch.table, []).append(ch)
            # intern in first-appearance order: ordinal assignment must
            # match the per-change path byte for byte
            if ch.site_id not in ordinals:
                ordinals[ch.site_id] = self.site_ordinal(ch.site_id)
        impacted = 0
        apply_table = (
            self._apply_table_device if self.device_cache is not None
            else self._apply_table_batched
        )
        for t, t_changes in by_table.items():
            impacted += apply_table(
                self._tables[t], t_changes, ordinals
            )
        return impacted

    def _prefetch_sql(self, sql_head: str, n: int) -> str:
        """Cached full SQL text for one ``IN (...)`` prefetch chunk —
        per (head, chunk-size), like the flush path's per-(table,
        column-set) cached statements.  Chunk sizes are bucketed to
        powers of two (callers pad by repeating a key; ``IN`` is a set,
        duplicates are free) so each head caches O(log CHUNK) strings
        and sqlite3's per-connection statement cache gets identical
        text across batches."""
        cache = getattr(self, "_prefetch_sql_cache", None)
        if cache is None:
            cache = self._prefetch_sql_cache = {}
        sql = cache.get((sql_head, n))
        if sql is None:
            sql = cache[(sql_head, n)] = (
                sql_head + ",".join("?" * n) + ")"
            )
        return sql

    def _prefetch_rows(self, sql_head: str, keys: List[bytes]) -> list:
        """Run ``sql_head`` (ending in ``IN (``) over ``keys`` in bound-
        parameter-sized chunks; returns all rows."""
        out: list = []
        for i in range(0, len(keys), self._PREFETCH_CHUNK):
            chunk = keys[i : i + self._PREFETCH_CHUNK]
            n = 1
            while n < len(chunk):
                n <<= 1
            if n > len(chunk):  # pad to the bucket: IN is a set
                chunk = chunk + [chunk[-1]] * (n - len(chunk))
            out.extend(
                self.conn.execute(
                    self._prefetch_sql(sql_head, n), chunk
                ).fetchall()
            )
        return out

    @staticmethod
    def _batch_pks_cids(
        t_changes: List[Change],
    ) -> Tuple[List[bytes], set]:
        pks: List[bytes] = []
        seen_pk = set()
        ref_cids = set()
        for ch in t_changes:
            if ch.pk not in seen_pk:
                seen_pk.add(ch.pk)
                pks.append(ch.pk)
            if ch.cid != SENTINEL_CID:
                ref_cids.add(ch.cid)
        return pks, ref_cids

    def _prefetch_table_view(
        self, info: TableInfo, pks: List[bytes], ref_cids,
    ) -> Tuple[Dict[bytes, int], Dict[Tuple[bytes, str], int],
               Dict[bytes, dict]]:
        """One IN (...) prefetch per kind: row causal lengths, cell
        clock versions, and current cell values (the LWW tie-break
        operand).  With an empty ``ref_cids`` the value query selects
        only the packed pk — a pure row-existence view, which is all
        the write-behind flush needs."""
        t = info.name
        cl_by_pk: Dict[bytes, int] = {}
        for pk, cl in self._prefetch_rows(
            f'SELECT pk, cl FROM "{t}__corro_cl" WHERE pk IN (', pks
        ):
            cl_by_pk[bytes(pk)] = cl
        clock_by_cell: Dict[Tuple[bytes, str], int] = {}
        for pk, cid, colv in self._prefetch_rows(
            f'SELECT pk, cid, col_version FROM "{t}__corro_clock" '
            "WHERE pk IN (", pks
        ):
            clock_by_cell[(bytes(pk), cid)] = colv
        vals_by_pk: Dict[bytes, dict] = {}
        if info.data_cols:
            pk_expr = "corro_pack(" + ", ".join(
                f'"{p}"' for p in info.pk_cols
            ) + ")"
            # only columns the batch actually references are selected —
            # wide tables stop paying for untouched columns; the
            # pk-only row (still selected) keeps the row-existence view
            sel_cols = tuple(
                c for c in info.data_cols if c in ref_cids
            )
            sel = "".join(f', "{c}"' for c in sel_cols)
            for row in self._prefetch_rows(
                f'SELECT {pk_expr}{sel} FROM "{t}" WHERE {pk_expr} IN (',
                pks,
            ):
                vals_by_pk[bytes(row[0])] = dict(
                    zip(sel_cols, row[1:])
                )
        return cl_by_pk, clock_by_cell, vals_by_pk

    def _merge_table(
        self, info: TableInfo, t_changes: List[Change],
        ordinals: Dict[bytes, int],
        cl_by_pk: Dict[bytes, int],
        clock_by_cell: Optional[Dict[Tuple[bytes, str], int]],
        vals_by_pk,
        seed_cols: Optional[tuple] = None,
    ) -> Tuple[Dict[bytes, list], int]:
        """In-memory merge: the columnar kernel (ops/merge.py segment
        reductions) past the batch-size threshold, the per-change dict
        replay below it — and as the fallback when a hostile batch
        cannot encode.  Identical net state either way, pinned by the
        three-way parity suite (tests/test_apply_batched.py).  The
        merge timing lands in ``corro_apply_merge_seconds{kernel=}`` on
        EVERY path — including the encode-impossible fallback, which
        additionally counts ``corro_apply_columnar_fallbacks_total`` so
        the A/B series stays complete under hostile batches."""
        import time as _time

        t0 = _time.perf_counter()
        kernel = "dict"
        merged = None
        # the kernel's flat winner arrays, for the device cache's
        # vectorized commit promote (consumed by _apply_table_device)
        self._columnar_plan = None
        if (
            self.columnar_merge
            and len(t_changes) >= self.columnar_merge_min
        ):
            merged = self._merge_table_columnar(
                info, t_changes, ordinals, cl_by_pk, clock_by_cell,
                vals_by_pk, seed_cols=seed_cols,
            )
            if merged is not None:
                kernel = "columnar"
            elif self.metrics is not None:
                self.metrics.counter(
                    "corro_apply_columnar_fallbacks_total",
                    table=info.name,
                )
        if merged is None:
            if clock_by_cell is None:
                # device fast path handed us encoder-parallel seed
                # columns and a presence *set*; the dict oracle needs
                # the classic dict views — materialize them here, on
                # the rare fallback only.
                clock_by_cell = {}
                vals_dict: Dict[bytes, dict] = {
                    pk: {} for pk in vals_by_pk
                }
                if seed_cols is not None:
                    for pk, cid, ver, val in zip(*seed_cols):
                        clock_by_cell[(pk, cid)] = ver
                        vd = vals_dict.get(pk)
                        if vd is not None:
                            vd[cid] = val
                vals_by_pk = vals_dict
            merged = self._merge_table_dict(
                t_changes, ordinals, cl_by_pk, clock_by_cell, vals_by_pk
            )
        if self.metrics is not None:
            self.metrics.histogram(
                "corro_apply_merge_seconds",
                _time.perf_counter() - t0, kernel=kernel,
            )
        return merged

    def _apply_table_batched(
        self, info: TableInfo, t_changes: List[Change],
        ordinals: Dict[bytes, int],
    ) -> int:
        pks, ref_cids = self._batch_pks_cids(t_changes)
        cl_by_pk, clock_by_cell, vals_by_pk = self._prefetch_table_view(
            info, pks, ref_cids
        )
        states, impacted = self._merge_table(
            info, t_changes, ordinals, cl_by_pk, clock_by_cell,
            vals_by_pk,
        )
        self._flush_table_states(
            info, states, cl_by_pk, clock_by_cell, vals_by_pk
        )
        return impacted

    def _apply_table_device(
        self, info: TableInfo, t_changes: List[Change],
        ordinals: Dict[bytes, int],
    ) -> int:
        """Device-resident apply: seed the merge from the cross-batch
        clock cache instead of SQLite prefetches, stage the net result
        back into the cache's transaction shadow, and defer the SQL
        flush to the write-behind queue (journaled in this transaction,
        drained on the apply pool).  Cache misses fall back to the
        prefetch path for exactly the missed pks and install the
        fetched seeds."""
        dc = self.device_cache
        t = info.name
        pks, ref_cids = self._batch_pks_cids(t_changes)
        if not ref_cids <= set(info.data_cols):
            # junk cid outside the schema: uncacheable batch — flush
            # any staged state for these rows, then run the classic
            # prefetch path against consistent SQLite
            self._wb_overlap_flush_in_tx(t, pks)
            return self._apply_table_batched(info, t_changes, ordinals)
        # hot path: the seed view comes back in the columnar encoder's
        # native parallel-sequence form (plus a row-presence set) and
        # the per-cell dicts are never built; a live same-tx overlay
        # returns None and takes the dict route below
        seed_cols = None
        fast = dc.lookup_seed(info, pks, ref_cids)
        if fast is not None:
            miss, cl_by_pk, seed_cols, vals_by_pk = fast
            clock_by_cell = None
        else:
            miss, cl_by_pk, clock_by_cell, vals_by_pk = dc.lookup(
                info, pks, ref_cids
            )
        if miss:
            # a missed pk may carry unflushed staged state (rare:
            # value-unknown re-miss) — SQLite must be consistent for
            # those rows before the prefetch reads it
            self._wb_overlap_flush_in_tx(t, miss)
            p_cl, p_clock, p_vals = self._prefetch_table_view(
                info, miss, ref_cids
            )
            dc.install(info, miss, p_cl, p_clock, p_vals, ref_cids)
            # hit pks keep the cache view (it includes staged state the
            # DB may not have yet); miss pks come from the prefetch
            p_cl.update(cl_by_pk)
            cl_by_pk = p_cl
            if seed_cols is not None:
                s_pks, s_cids, s_vers, s_vals = seed_cols
                for (pk, cid), ver in p_clock.items():
                    s_pks.append(pk)
                    s_cids.append(cid)
                    s_vers.append(ver)
                    s_vals.append(p_vals.get(pk, {}).get(cid))
                vals_by_pk.update(p_vals)
            else:
                p_clock.update(clock_by_cell)
                clock_by_cell = p_clock
                p_vals.update(vals_by_pk)
                vals_by_pk = p_vals
        states, impacted = self._merge_table(
            info, t_changes, ordinals, cl_by_pk, clock_by_cell,
            vals_by_pk, seed_cols=seed_cols,
        )
        dc.stage_states(info, states, cl_by_pk, vals_by_pk,
                        columnar=self._columnar_plan)
        self._columnar_plan = None
        cur = self.conn.execute(
            "INSERT INTO __corro_flush_journal (tbl, payload) "
            "VALUES (?, ?)",
            (t, _wb_encode_states(states)),
        )
        self._wb.tx_staged.append((t, states, cur.lastrowid))
        self._wb.unflushed.setdefault(t, set()).update(states)
        return impacted

    def _merge_table_dict(
        self, t_changes: List[Change], ordinals: Dict[bytes, int],
        cl_by_pk: Dict[bytes, int],
        clock_by_cell: Dict[Tuple[bytes, str], int],
        vals_by_pk: Dict[bytes, dict],
    ) -> Tuple[Dict[bytes, list], int]:
        """The per-change decision replay against dict state —
        superseded same-(pk, cid) writes coalesce to the causal winner
        before any SQL runs.  Kept verbatim as the columnar kernel's
        parity oracle (PR 3–5 discipline) and the small-batch path."""
        CL, CLROW, GEN, ALIVE, ENSURE, CELLS, DBOK = range(7)
        states: Dict[bytes, list] = {}
        impacted = 0
        sentinel_cid = SENTINEL_CID
        cl_get = cl_by_pk.get
        clock_get = clock_by_cell.get
        for ch in t_changes:
            pk = ch.pk
            st = states.get(pk)
            if st is None:
                st = states[pk] = [
                    cl_get(pk), None, False, None, False, {}, True,
                ]
            cl = ch.cl

            if ch.cid == sentinel_cid:
                if st[CL] is not None and cl <= st[CL]:
                    continue
                # sentinel flag only ever upgrades; 1 is its maximum
                st[CLROW] = (pk, cl, int(ch.db_version), int(ch.seq),
                             ordinals[ch.site_id], 1)
                st[CL] = cl
                st[GEN], st[ALIVE], st[DBOK] = True, cl % 2 == 1, False
                st[CELLS] = {}
                impacted += 1
                continue

            have_cl = st[CL]
            if have_cl is not None and cl < have_cl:
                continue
            if have_cl is None or cl > have_cl:
                prev = st[CLROW]
                st[CLROW] = (pk, cl, int(ch.db_version), int(ch.seq),
                             ordinals[ch.site_id],
                             prev[5] if prev else 0)
                st[CL] = cl
                st[GEN], st[ALIVE], st[DBOK] = True, cl % 2 == 1, False
                st[CELLS] = {}
                if cl % 2 == 0:
                    impacted += 1
                    continue
            elif cl % 2 == 0:
                continue
            else:
                st[ENSURE] = True

            # LWW: in-batch winner first, else the (still valid) DB view
            cells = st[CELLS]
            cur = cells.get(ch.cid)
            if cur is not None:
                local_ver, cur_val = cur[1], cur[0]
            elif st[DBOK]:
                local_ver = clock_get((pk, ch.cid))
                cur_val = None
                if local_ver is not None:
                    row_vals = vals_by_pk.get(pk)
                    if row_vals is not None:
                        cur_val = row_vals.get(ch.cid)
            else:
                local_ver = None
            if local_ver is not None:
                if ch.col_version < local_ver:
                    continue
                if ch.col_version == local_ver and \
                        value_cmp(ch.val, cur_val) <= 0:
                    continue
            cells[ch.cid] = (
                ch.val, ch.col_version, int(ch.db_version), int(ch.seq),
                ordinals[ch.site_id],
            )
            impacted += 1
        return states, impacted

    def _merge_table_columnar(
        self, info: TableInfo, t_changes: List[Change],
        ordinals: Dict[bytes, int],
        cl_by_pk: Dict[bytes, int],
        clock_by_cell: Optional[Dict[Tuple[bytes, str], int]],
        vals_by_pk,
        seed_cols: Optional[tuple] = None,
    ) -> Optional[Tuple[Dict[bytes, list], int]]:
        """Columnar winner selection (docs/crdts.md "Columnar merge
        kernel"): encode the batch + the prefetched DB view to flat
        arrays, resolve causal/LWW winners through
        :func:`corrosion_tpu.ops.merge.select_winners`, and decode the
        decision back into the same net ``states`` structure the flush
        consumes.  Returns ``None`` (fall back to the dict oracle) when
        the batch cannot encode — out-of-range hostile fields, unknown
        value types.  ``seed_cols`` — the device cache's native
        encoder-parallel seed columns — skips the dict flatten
        entirely; when absent the classic prefetch dicts are flattened
        here."""
        try:
            from corrosion_tpu.ops import merge as mergeops
        except Exception:  # pragma: no cover - no-numpy deployments
            return None

        if seed_cols is not None:
            if not seed_cols[0]:
                seed_cols = None
        elif clock_by_cell:
            s_pks, s_cids = zip(*clock_by_cell)
            s_vers = list(clock_by_cell.values())
            _empty: dict = {}
            vals_get = vals_by_pk.get
            s_vals = [
                vals_get(pk, _empty).get(cid)
                for pk, cid in clock_by_cell
            ]
            seed_cols = (s_pks, s_cids, s_vers, s_vals)
        plan = mergeops.encode_change_batch(
            t_changes, SENTINEL_CID, cl_by_pk, seed_cols
        )
        if plan is None:
            return None
        dec = mergeops.select_winners(plan)

        states: Dict[bytes, list] = {}
        n_cid = plan.n_cid
        cid_values = plan.cid_values
        # tolist()/C-level maps: the decode loop reads every entry once
        # — plain Python ints and pre-extracted column lists beat
        # per-element numpy boxing and per-winner attribute chains
        gen_l = dec.gen.tolist()
        final_l = dec.final_cl.tolist()
        alive_l = dec.alive.tolist()
        ensure_l = dec.ensure.tolist()
        sentf_l = dec.sent_flag.tolist()
        clrow_l = dec.clrow_idx.tolist()
        win_l = dec.winner_idx.tolist()
        ag = operator.attrgetter
        val_l = plan.vals
        ver_l = plan.vers
        dbv_l = list(map(int, map(ag("db_version"), t_changes)))
        seq_l = list(map(int, map(ag("seq"), t_changes)))
        ord_l = list(map(
            ordinals.__getitem__, map(ag("site_id"), t_changes)
        ))
        # one C-level zip builds every winner cell tuple up front —
        # the decode loop then only indexes, never constructs
        cell_t = list(zip(val_l, ver_l, dbv_l, seq_l, ord_l))
        for p, pk in enumerate(plan.pk_values):
            gen = gen_l[p]
            final_cl = final_l[p]
            clrow = None
            ci = clrow_l[p]
            if ci >= 0:
                clrow = (
                    pk, final_cl, dbv_l[ci], seq_l[ci], ord_l[ci],
                    1 if sentf_l[p] else 0,
                )
            cells: Dict[str, tuple] = {}
            base = p * n_cid
            for c in range(n_cid):
                w = win_l[base + c]
                if w >= 0:
                    cells[cid_values[c]] = cell_t[w]
            states[pk] = [
                final_cl if (gen or pk in cl_by_pk) else None,
                clrow, gen,
                alive_l[p] if gen else None,
                ensure_l[p], cells, not gen,
            ]
        self._columnar_plan = (plan, dec)
        return states, int(dec.impacted)

    def _flush_table_states(
        self, info: TableInfo, states: Dict[bytes, list],
        cl_by_pk: Dict[bytes, int],
        clock_by_cell: Dict[Tuple[bytes, str], int],
        vals_by_pk: Dict[bytes, dict],
    ) -> None:
        """Flush the net merged state, each statement kind one
        executemany on a cached SQL string: cl upserts; row + clock
        deletes for changed generations; then rows/cells — fresh rows
        take a FUSED insert carrying their cell values when the schema
        allows (otherwise the conservative pk-only insert + grouped
        per-row UPDATE, bug-for-bug with the per-change path); clock
        rows split into pure inserts (no existing row possible) vs
        upserts."""
        t = info.name
        CL, CLROW, GEN, ALIVE, ENSURE, CELLS, DBOK = range(7)
        cl_ins = [
            st[CLROW] for pk, st in states.items()
            if st[CLROW] and pk not in cl_by_pk
        ]
        cl_ups = [
            st[CLROW] for pk, st in states.items()
            if st[CLROW] and pk in cl_by_pk
        ]
        self._flush_insert(("cl_ins", t), cl_ins)
        self._flush_insert(("cl_ups", t), cl_ups)
        # generation deletes: skipped for rows that provably have
        # nothing to delete (fresh pks), which is the whole of a cold
        # backfill — the per-change path issues those no-op DELETEs
        clock_pks = {pk for pk, _cid in clock_by_cell}
        know_rows = bool(info.data_cols)  # pk-only tables: no row view
        gen_pks = [pk for pk, st in states.items() if st[GEN]]
        row_dels = [
            unpack_values(pk) for pk in gen_pks
            if not know_rows or pk in vals_by_pk
        ]
        if row_dels:
            self.conn.executemany(self._apply_sql(("row_del", t)), row_dels)
        clock_dels = [(pk,) for pk in gen_pks if pk in clock_pks]
        if clock_dels:
            self.conn.executemany(
                self._apply_sql(("clock_del", t)), clock_dels
            )
        fused_ok = info.fused_insert_ok
        ins_plain: List[Sequence] = []
        ins_by_cids: Dict[tuple, List[list]] = {}
        upd_by_cids: Dict[tuple, List[list]] = {}
        clock_ins: List[tuple] = []
        clock_ups: List[tuple] = []
        for pk, st in states.items():
            cells = st[CELLS]
            gen = st[GEN]
            if cells:
                fresh_clock = not st[DBOK]  # generation replaced: clock
                # rows for this pk were just deleted, inserts can't
                # conflict; otherwise conflict iff the cell existed
                for cid, cell in cells.items():
                    row = (pk, cid, cell[1], cell[2], cell[3], cell[4])
                    if fresh_clock or (pk, cid) not in clock_by_cell:
                        clock_ins.append(row)
                    else:
                        clock_ups.append(row)
            needs_row = (gen and st[ALIVE]) or (not gen and st[ENSURE])
            if not needs_row:
                continue
            row_absent = gen or pk not in vals_by_pk
            if cells and fused_ok and row_absent:
                cids = tuple(cells)
                ins_by_cids.setdefault(cids, []).append(
                    list(unpack_values(pk)) + [cells[c][0] for c in cids]
                )
                continue
            if info.data_cols and not gen and pk in vals_by_pk:
                pass  # row already exists: the OR IGNORE would no-op
            else:
                ins_plain.append(unpack_values(pk))
            if cells:
                cids = tuple(cells)
                upd_by_cids.setdefault(cids, []).append(
                    [cells[c][0] for c in cids] + list(unpack_values(pk))
                )
        self._flush_insert(("row_ins", t), ins_plain)
        for cids, rows in ins_by_cids.items():
            self._flush_insert(("row_ins_fused", t, cids), rows)
        for cids, rows in upd_by_cids.items():
            self.conn.executemany(
                self._apply_sql(("cell_upd", t, cids)), rows
            )
        self._flush_insert(("clock_ins", t), clock_ins)
        self._flush_insert(("clock_ups", t), clock_ups)

    # ------------------------------------------------------------------
    # device-resident apply: cache wiring + write-behind flush
    # ------------------------------------------------------------------

    def enable_device_cache(self, slots: Optional[int] = None,
                            backend: str = "auto") -> None:
        """Switch batched applies to the device-resident path
        (docs/crdts.md "Device-resident apply").  Idempotent; the agent
        calls this from config wiring."""
        from corrosion_tpu.ops.devcache import DEFAULT_SLOTS, \
            DeviceClockCache

        if self.device_cache is not None:
            return
        self.device_cache = DeviceClockCache(
            slots=slots or DEFAULT_SLOTS, backend=backend
        )

    def flush_pending(self) -> None:
        """Drain the write-behind queue to SQLite.  The read-side
        BARRIER: any apply whose commit was observable before this call
        takes the lock has its winners durably in the clock tables when
        it returns (entries are journaled + enqueued inside the apply
        transaction itself).  Cheap no-op when nothing is pending."""
        wb = self._wb
        if not wb.pending and not wb.tx_staged:
            return
        from corrosion_tpu.agent.locks import PRIO_HIGH

        with self._lock.prio(PRIO_HIGH, "flush-barrier", kind="apply"):
            self._wb_drain_locked()

    # the serve/snapshot/subscription read paths call it by this name
    flush_barrier = flush_pending

    def flush_should_drain(self) -> bool:
        """Scheduling hint for the apply pool: drain once enough
        batches (or cells) have accumulated to amortize the flush.
        Thresholds trade journal memory (each pending batch keeps its
        net states alive) against coalescing — crash safety is the
        journal's job either way, so these only bound RAM and the
        worst-case barrier latency for a serve-path read."""
        wb = self._wb
        return len(wb.pending) >= 64 or (
            len(wb.pending) > 0 and wb.cells_pending() >= 131072
        )

    def device_cache_invalidate(self, reason: str) -> None:
        """Whole-cache invalidation hook for out-of-band CRR rewrites
        (compaction floor advance, schema surgery).  Takes the storage
        lock; flushes first so no staged state is stranded."""
        if self.device_cache is None:
            return
        from corrosion_tpu.agent.locks import PRIO_HIGH

        with self._lock.prio(PRIO_HIGH, "devcache-inval", kind="apply"):
            self._wb_drain_locked()
            self.device_cache.invalidate_all(reason)
            self._emit_cache_metrics()

    def _wb_drain_locked(self) -> None:
        """Drain with the storage lock held.  Outside a transaction the
        flush runs in its own BEGIN IMMEDIATE apply-mode transaction;
        inside one (reentrant barrier from an apply/collect path) it
        folds into the open transaction."""
        wb = self._wb
        if self.conn.in_transaction:
            self._wb_flush_all_in_tx()
            return
        if not wb.pending:
            return
        entries, wb.pending = wb.pending, []
        wb.recompute()
        self.conn.execute("BEGIN IMMEDIATE")
        try:
            self._set_state("apply_mode", 1)
            self._wb_flush_entries_in_tx(entries)
            self._set_state("apply_mode", 0)
        except BaseException:
            wb.pending = entries + wb.pending
            wb.recompute()
            if self.conn.in_transaction:
                self.conn.execute("ROLLBACK")
            raise
        self.conn.execute("COMMIT")
        self._emit_cache_metrics()

    def _wb_flush_all_in_tx(self) -> None:
        """Flush pending + current-transaction staged entries inside
        the OPEN transaction.  Pending entries move to ``draining`` so
        a rollback requeues them (their journal deletes roll back with
        the transaction); staged entries simply leave the ledger — on
        rollback their journal inserts and flushed rows vanish with the
        merge itself."""
        wb = self._wb
        entries = wb.pending + wb.tx_staged
        if not entries:
            return
        mode = self._state("apply_mode")
        if not mode:
            self._set_state("apply_mode", 1)
        try:
            self._wb_flush_entries_in_tx(entries)
        finally:
            if not mode:
                self._set_state("apply_mode", 0)
        wb.draining.extend(wb.pending)
        wb.pending = []
        wb.tx_staged = []
        wb.recompute()

    def _wb_overlap_flush_in_tx(self, table: str,
                                pks: Optional[List[bytes]] = None) -> None:
        """Order guard inside an open apply transaction: if any of
        ``pks`` (or any row of ``table`` when None) has unflushed
        staged state, flush everything so the imminent SQLite read sees
        a consistent view."""
        u = self._wb.unflushed.get(table)
        if not u:
            return
        if pks is not None and not any(pk in u for pk in pks):
            return
        self._wb_flush_all_in_tx()

    def _wb_flush_entries_in_tx(self, entries: List[tuple]) -> None:
        """The flush itself: coalesce per (table, pk), re-derive the
        presence views from SQLite (NOT the cache — the flush is the
        one consumer that must see the durable truth), run the ordered
        ``_flush_table_states`` executemany batches, and retire the
        journal rows in the same transaction."""
        by_table: Dict[str, Dict[bytes, list]] = {}
        for t, states, _jid in entries:
            d = by_table.setdefault(t, {})
            for pk, st in states.items():
                prev = d.get(pk)
                d[pk] = st if prev is None else _wb_coalesce(prev, st)
        for t, merged in by_table.items():
            info = self._tables.get(t)
            if info is None:
                continue  # table dropped since staging: nothing to do
            view = self._prefetch_table_view(info, list(merged), ())
            self._flush_table_states(info, merged, *view)
        self.conn.executemany(
            "DELETE FROM __corro_flush_journal WHERE id = ?",
            [(jid,) for _t, _s, jid in entries],
        )

    def _recover_flush_journal(self) -> None:
        """Boot classification of the crash window between a committed
        device-merge and its async flush: replay every surviving
        journal row (in id order, each in its own transaction deleting
        its row) through ``_flush_table_states`` against presence views
        re-derived from the database — exact by construction, because a
        flush transaction deletes its journal row atomically, so a
        surviving row's pre-state is exactly the merge-time view."""
        rows = self.conn.execute(
            "SELECT id, tbl, payload FROM __corro_flush_journal "
            "ORDER BY id"
        ).fetchall()
        for jid, tbl, payload in rows:
            self.conn.execute("BEGIN IMMEDIATE")
            try:
                self._set_state("apply_mode", 1)
                info = self._tables.get(tbl)
                if info is not None:
                    states = _wb_decode_states(bytes(payload))
                    view = self._prefetch_table_view(
                        info, list(states), ()
                    )
                    self._flush_table_states(info, states, *view)
                self.conn.execute(
                    "DELETE FROM __corro_flush_journal WHERE id = ?",
                    (jid,),
                )
                self._set_state("apply_mode", 0)
            except BaseException:
                self._set_state("apply_mode", 0)
                if self.conn.in_transaction:
                    self.conn.execute("ROLLBACK")
                raise
            self.conn.execute("COMMIT")
            self.flush_journal_recovered += 1

    def _tx_finish(self, committed: bool) -> None:
        """Transaction epilogue for the device-resident ledger: promote
        or discard the cache shadow, and move/requeue write-behind
        entries to match what the database actually did."""
        wb = self._wb
        dc = self.device_cache
        if dc is None and not (wb.draining or wb.tx_staged):
            return
        if committed:
            wb.draining = []
            wb.pending.extend(wb.tx_staged)
            wb.tx_staged = []
            if dc is not None:
                dc.commit_tx()
        else:
            wb.pending = wb.draining + wb.pending
            wb.draining = []
            wb.tx_staged = []
            if dc is not None:
                dc.abort_tx()
        wb.recompute()
        self._emit_cache_metrics()

    def _emit_cache_metrics(self) -> None:
        """Emit the cache's monotonic counters as metric deltas, plus
        the flush-queue depth gauge."""
        m = self.metrics
        dc = self.device_cache
        if m is None or dc is None:
            return
        snap = self._devcache_emitted
        for key, series in (
            ("hits", "corro_apply_cache_hits_total"),
            ("misses", "corro_apply_cache_misses_total"),
            ("evictions", "corro_apply_cache_evictions_total"),
        ):
            cur = dc.counters[key]
            d = cur - snap.get(key, 0.0)
            if d:
                m.counter(series, d)
                snap[key] = cur
        for reason, cur in dc.invalidations.items():
            d = cur - snap.get(("inv", reason), 0.0)
            if d:
                m.counter(
                    "corro_apply_cache_invalidations_total", d,
                    reason=reason,
                )
                snap[("inv", reason)] = cur
        m.gauge("corro_apply_flush_pending", float(len(self._wb.pending)))

    # -- row helpers ----------------------------------------------------

    def _row_cl_entry(self, table: str, pk: bytes):
        return self.conn.execute(
            f'SELECT cl FROM "{table}__corro_cl" WHERE pk=?', (pk,)
        ).fetchone()

    def _set_row_cl(self, table, pk, cl, db_version, seq, ordinal,
                    sentinel: int = 0) -> None:
        # sentinel only ever upgrades: a row once shipped as a '-1'
        # change keeps shipping its row-level state (cr-sqlite keeps the
        # sentinel clock row alive the same way)
        self.conn.execute(
            f'INSERT INTO "{table}__corro_cl" '
            "(pk, cl, db_version, seq, site_ordinal, sentinel) "
            "VALUES (?, ?, ?, ?, ?, ?) "
            "ON CONFLICT(pk) DO UPDATE SET cl=excluded.cl, "
            "db_version=excluded.db_version, seq=excluded.seq, "
            "site_ordinal=excluded.site_ordinal, "
            "sentinel=MAX(sentinel, excluded.sentinel)",
            (pk, cl, int(db_version), int(seq), ordinal, sentinel),
        )

    def _reset_row(self, info: TableInfo, pk: bytes) -> None:
        """Start a fresh row generation: drop any old values, re-create
        the row with column defaults (cr-sqlite resurrect semantics)."""
        self._delete_row(info, pk)
        self._ensure_row(info, pk)

    def _ensure_row(self, info: TableInfo, pk: bytes) -> None:
        pk_vals = unpack_values(pk)
        cols = ", ".join(f'"{p}"' for p in info.pk_cols)
        ph = ", ".join("?" for _ in info.pk_cols)
        self.conn.execute(
            f'INSERT OR IGNORE INTO "{info.name}" ({cols}) VALUES ({ph})',
            pk_vals,
        )

    def _delete_row(self, info: TableInfo, pk: bytes) -> None:
        pk_vals = unpack_values(pk)
        where = " AND ".join(f'"{p}" IS ?' for p in info.pk_cols)
        self.conn.execute(f'DELETE FROM "{info.name}" WHERE {where}', pk_vals)

    def _write_cell(self, info: TableInfo, pk: bytes, cid: str, val) -> None:
        pk_vals = unpack_values(pk)
        where = " AND ".join(f'"{p}" IS ?' for p in info.pk_cols)
        self.conn.execute(
            f'UPDATE "{info.name}" SET "{_ident(cid)}" = ? WHERE {where}',
            [val] + pk_vals,
        )

    @contextmanager
    def interruptible(self, budget_s: float):
        """Interrupt the RW connection if the enclosed work overruns its
        budget (InterruptibleTransaction parity,
        ``sqlite-pool/src/lib.rs:116``): a runaway maintenance statement
        surfaces as sqlite3.OperationalError('interrupted') instead of
        stalling high-priority applies behind it.

        The disarm is mutually exclusive with the firing: Timer.cancel()
        cannot stop a timer that already fired, and a stray interrupt
        after block exit would abort the NEXT holder's transaction."""
        guard = threading.Lock()
        state = {"armed": True}

        def fire():
            with guard:
                if state["armed"]:
                    self.conn.interrupt()

        timer = threading.Timer(budget_s, fire)
        timer.daemon = True
        timer.start()
        try:
            yield
        finally:
            with guard:
                state["armed"] = False
            timer.cancel()

    def install_snapshot(self, staged: str) -> None:
        """Atomically swap the database file for a fully-prepared
        staged snapshot (docs/sync.md, install state machine).

        Caller contract: holds ``self._lock``, has verified the staged
        content digest, run ``snapshot.prepare_staged`` (identity
        rewrite) on it, and written the ``installing`` journal marker —
        so a crash anywhere in here classifies at boot
        (``snapshot.recover_pending_install``).

        The RW connection closes first; FREE pool readers close; a
        reader checked out mid-query keeps its fd to the pre-swap
        inode (POSIX ``os.replace`` semantics), finishes its stale
        read, and closes on return instead of re-pooling.  The pool
        condvar is HELD across the swap itself: a ``reader()``
        checkout slipping between the drain and ``os.replace`` would
        open the pre-swap inode and be re-pooled — serving stale data
        forever — so checkouts block for the (brief) swap instead.
        Stale ``-wal``/``-shm`` files are removed AFTER the swap —
        they belong to the replaced inode, and the prepared snapshot
        is a single self-contained file."""
        import os

        from corrosion_tpu.agent.snapshot import fsync_dir

        # device-resident apply: pending flushes target the file being
        # REPLACED — discard them (their journal rows live in the old
        # inode; if the swap fails and we come back up on the previous
        # file, _recover_flush_journal below replays them from there)
        # and drop every cached clock view of the outgoing database
        self._wb = _WriteBehind()
        if self.device_cache is not None:
            self.device_cache.invalidate_all("snapshot_install")
            self._emit_cache_metrics()
        self.conn.close()
        swapped = False
        try:
            with self._ro_cv:
                for conn in self._ro_free:
                    conn.close()
                    if conn in self._ro_all:
                        self._ro_all.remove(conn)
                self._ro_free.clear()
                self._ro_stale.update(self._ro_all)
                self._ro_all = []
                os.replace(staged, self.path)
                swapped = True
                fsync_dir(self.path)
                for ext in ("-wal", "-shm"):
                    p = self.path + ext
                    if os.path.exists(p):
                        os.unlink(p)
        finally:
            # ALWAYS come back up on whatever file now lives at
            # self.path — the previous database if the swap raised, the
            # installed snapshot if it completed.  Without this a
            # failed os.replace (disk full, EXDEV) would leave a LIVE
            # agent holding a closed RW connection, bricking every
            # subsequent write until restart.  (If connecting itself
            # fails the error propagates with the connection closed —
            # there is no file to come up on.)
            self.conn = self._connect_rw()
            # re-derive every cached view of the schema + identity; on
            # the success path the staged prep installed OUR site id at
            # ordinal 1, so _init_meta reads it back unchanged
            self._apply_sql_cache = {}
            self._init_meta(None)
            self._tables = {}
            self._load_crr_tables()
            if swapped:
                # the installed file came from a REMOTE donor: any
                # flush-journal rows it carries are the donor's intents
                # (normally none — the donor drains before building and
                # the snapshot scrub drops the table) and must be
                # purged, never replayed: this node only ever decodes
                # journal payloads it wrote itself
                self.conn.execute("DELETE FROM __corro_flush_journal")
                self.conn.commit()
            else:
                # failed swap: we came back up on OUR previous file —
                # replay our own journal rows before serving from it
                self._recover_flush_journal()

    def close(self) -> None:
        # drain the write-behind queue while the connection is still
        # usable; on failure the journal rows replay at next boot
        try:
            self.flush_pending()
        except Exception:
            pass
        with self._ro_cv:
            self._ro_closed = True
            # close only the FREE readers: a conn mid-query belongs to
            # its checkout and is closed by reader()'s finally; waiters
            # parked in reader() are woken to fail instead of hanging
            for conn in self._ro_free:
                conn.close()
                if conn in self._ro_all:
                    self._ro_all.remove(conn)
            self._ro_free.clear()
            self._ro_cv.notify_all()
        self.conn.close()


# ---------------------------------------------------------------------------
# UDFs
# ---------------------------------------------------------------------------


def _udf_pack(*args):
    return pack_values(args)


def _udf_json_contains(a, b) -> int:
    """corro_json_contains(a, b): does JSON doc a contain doc b?

    Parity: the reference registers this custom SQL function
    (``crates/sqlite-functions/src/lib.rs:5-51``) — recursive containment:
    every key/element of ``b`` must appear in ``a``.
    """
    import json

    def contains(x, y) -> bool:
        if isinstance(y, dict):
            return isinstance(x, dict) and all(
                k in x and contains(x[k], v) for k, v in y.items()
            )
        if isinstance(y, list):
            return isinstance(x, list) and all(
                any(contains(xi, yi) for xi in x) for yi in y
            )
        return x == y

    try:
        return int(contains(json.loads(a), json.loads(b)))
    except (TypeError, ValueError):
        return 0
