"""Recursive-descent PG statement parser + SQLite emitter.

Parity: the reference parses client SQL with ``sqlparser`` into a full
AST and re-emits a SQLite AST (``corro-pg/src/lib.rs:324-330``, the
~6k-line translation walk).  This module is the same architecture over
the ``agent/pgsql.py`` lexer: a recursive-descent grammar for the
statements a SQL client actually sends — SELECT (joins, subqueries,
compounds, CTEs), INSERT (multi-row VALUES, SELECT source, ON
CONFLICT, RETURNING), UPDATE (SET, FROM, RETURNING), DELETE (USING,
RETURNING) — producing typed nodes that downstream code *queries*
instead of regex-probing: statement class (read/write), the referenced
tables (catalog routing), RETURNING column names, and the command tag
all come from the AST.

Expressions are parsed structurally (balanced, clause-bounded, with
embedded sub-SELECTs lifted into real nodes so their table refs are
visible) and carried as token runs; emission re-applies the shared
PG→SQLite token transforms (``pgsql.transform_tokens``: ``$N`` → ``?``
with order, ``::type`` casts, ``E''``/dollar strings, ``now()``,
``ILIKE``) per run — one transform implementation for both pipelines.

Out-of-grammar statements raise :class:`Unsupported`; the session
falls back to the token-pass translation (counted by a metric), so a
parser gap degrades to round-4 behavior instead of an error.
PG-only clauses with no SQLite meaning are *dropped with intent*:
``FOR UPDATE/SHARE`` row locking (single-writer storage) and ``ONLY``
table modifiers (no inheritance).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from corrosion_tpu.agent.pgsql import (
    PgSqlError,
    tokenize,
    transform_tokens,
)


class Unsupported(Exception):
    """Statement shape outside the grammar: caller falls back."""


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass
class QName:
    parts: List[str]  # ["public", "t"] or ["t"]; qidents keep quotes

    @property
    def base(self) -> str:
        return self.parts[-1].strip('"').lower()

    @property
    def schema(self) -> Optional[str]:
        return (
            self.parts[-2].strip('"').lower()
            if len(self.parts) > 1 else None
        )


# an expression is a run of lexer tokens with sub-SELECTs lifted out:
# elements are ("t", kind, text) or ("q", Select)
Expr = List[tuple]


@dataclass
class FromItem:
    name: Optional[QName] = None  # table reference
    select: Optional["Select"] = None  # (subquery)
    alias: Optional[str] = None


@dataclass
class Join:
    jtype: str  # "JOIN" / "LEFT JOIN" / "CROSS JOIN" / "," ...
    item: FromItem = None  # type: ignore[assignment]
    on: Optional[Expr] = None
    using: Optional[List[str]] = None


@dataclass
class SelectCore:
    distinct: bool = False
    items: List[Tuple[Expr, Optional[str]]] = field(default_factory=list)
    from_items: List[FromItem] = field(default_factory=list)
    joins: List[Join] = field(default_factory=list)
    where: Optional[Expr] = None
    group_by: List[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    # VALUES core instead of SELECT core
    values: Optional[List[List[Expr]]] = None


@dataclass
class Select:
    ctes: List[Tuple[str, Optional[List[str]], "Select"]] = field(
        default_factory=list
    )
    recursive: bool = False
    core: SelectCore = None  # type: ignore[assignment]
    compounds: List[Tuple[str, SelectCore]] = field(default_factory=list)
    order_by: List[Expr] = field(default_factory=list)
    limit: Optional[Expr] = None
    offset: Optional[Expr] = None


@dataclass
class Insert:
    ctes: List = field(default_factory=list)
    recursive: bool = False
    table: QName = None  # type: ignore[assignment]
    alias: Optional[str] = None
    columns: Optional[List[str]] = None
    values: Optional[List[List[Expr]]] = None
    select: Optional[Select] = None
    default_values: bool = False
    conflict_target: Optional[List[str]] = None
    conflict_action: Optional[str] = None  # "nothing" | "update"
    conflict_sets: List[Tuple[str, Expr]] = field(default_factory=list)
    conflict_where: Optional[Expr] = None
    returning: Optional[List[Tuple[Expr, Optional[str]]]] = None


@dataclass
class Update:
    ctes: List = field(default_factory=list)
    recursive: bool = False
    table: QName = None  # type: ignore[assignment]
    alias: Optional[str] = None
    sets: List[Tuple[str, Expr]] = field(default_factory=list)
    from_items: List[FromItem] = field(default_factory=list)
    joins: List[Join] = field(default_factory=list)
    where: Optional[Expr] = None
    returning: Optional[List[Tuple[Expr, Optional[str]]]] = None


@dataclass
class Delete:
    ctes: List = field(default_factory=list)
    recursive: bool = False
    table: QName = None  # type: ignore[assignment]
    alias: Optional[str] = None
    using: List[FromItem] = field(default_factory=list)
    where: Optional[Expr] = None
    returning: Optional[List[Tuple[Expr, Optional[str]]]] = None


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

_COMPOUND_OPS = ("UNION", "INTERSECT", "EXCEPT")
_JOIN_WORDS = ("JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS")
# clause heads that end an expression at depth 0
_CLAUSE_STOPS = frozenset((
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET",
    "RETURNING", "ON", "USING", "SET", "VALUES", "UNION", "INTERSECT",
    "EXCEPT", "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS",
    "NATURAL", "WINDOW", "FETCH", "FOR", "AS", "DO",
))


class _P:
    def __init__(self, sql: str):
        try:
            self.toks = [
                t for t in tokenize(sql) if t[0] not in ("ws", "comment")
            ]
        except PgSqlError as e:
            raise Unsupported(str(e))
        self.i = 0

    # -- stream ----------------------------------------------------------

    def peek(self, ahead: int = 0):
        j = self.i + ahead
        return self.toks[j] if j < len(self.toks) else (None, None)

    def at_word(self, *words: str, ahead: int = 0) -> bool:
        k, t = self.peek(ahead)
        return k == "word" and t.upper() in words

    def at_op(self, op: str) -> bool:
        k, t = self.peek()
        return k == "op" and t == op

    def take(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect_word(self, *words: str) -> str:
        if not self.at_word(*words):
            raise Unsupported(f"expected {'/'.join(words)} at {self.peek()}")
        return self.take()[1]

    def expect_op(self, op: str) -> None:
        if not self.at_op(op):
            raise Unsupported(f"expected {op!r} at {self.peek()}")
        self.take()

    def done(self) -> bool:
        return self.i >= len(self.toks) or (
            self.at_op(";") and self.i == len(self.toks) - 1
        )

    # -- terminals -------------------------------------------------------

    def ident(self) -> str:
        k, t = self.peek()
        if k == "word":
            if t.upper() in _CLAUSE_STOPS:
                raise Unsupported(f"identifier expected, got {t!r}")
            return self.take()[1]
        if k == "qident":
            return self.take()[1]
        raise Unsupported(f"identifier expected at {self.peek()}")

    def qname(self) -> QName:
        parts = [self.ident()]
        while self.at_op("."):
            self.take()
            parts.append(self.ident())
        if len(parts) > 3:
            raise Unsupported("name too qualified")
        return QName(parts)

    def opt_alias(self) -> Optional[str]:
        if self.at_word("AS"):
            self.take()
            return self.ident()
        k, t = self.peek()
        if k == "qident":
            return self.take()[1]
        if k == "word" and t.upper() not in _CLAUSE_STOPS and not self.at_word(
            *_COMPOUND_OPS
        ):
            return self.take()[1]
        return None

    def col_list(self) -> List[str]:
        self.expect_op("(")
        cols = [self.ident()]
        while self.at_op(","):
            self.take()
            cols.append(self.ident())
        self.expect_op(")")
        return cols

    # -- expressions -----------------------------------------------------

    def expr(self, stop_commas: bool = False) -> Expr:
        """Collect one expression: balanced token run ending at a
        depth-0 clause head (or comma when ``stop_commas``); descends
        into parens, lifting ``(SELECT ...)`` into Select nodes."""
        out: Expr = []
        started = False
        while True:
            k, t = self.peek()
            if k is None:
                break
            if k == "op" and t == ";":
                break
            if k == "op" and t == ")":
                break
            if stop_commas and k == "op" and t == ",":
                break
            if started and k == "word" and t.upper() in _CLAUSE_STOPS:
                break
            if not started and k == "word" and t.upper() in (
                "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT",
                "RETURNING",
            ):
                break
            if k == "op" and t == "(":
                self.take()
                if self.at_word("SELECT", "VALUES", "WITH"):
                    sub = self.select_stmt()
                    self.expect_op(")")
                    out.append(("q", sub))
                else:
                    out.append(("t", "op", "("))
                    out.extend(self._expr_group())
                    self.expect_op(")")
                    out.append(("t", "op", ")"))
                started = True
                continue
            if k == "word" and t.upper() == "CASE":
                out.extend(self._case_expr())
                started = True
                continue
            out.append(("t", k, t))
            self.take()
            started = True
        if not out:
            raise Unsupported(f"empty expression at {self.peek()}")
        return out

    def _expr_group(self) -> Expr:
        """Tokens inside parens up to the matching close, sub-SELECTs
        lifted, nested parens recursed."""
        out: Expr = []
        while True:
            k, t = self.peek()
            if k is None:
                raise Unsupported("unbalanced parens")
            if k == "op" and t == ")":
                return out
            if k == "op" and t == "(":
                self.take()
                if self.at_word("SELECT", "VALUES", "WITH"):
                    sub = self.select_stmt()
                    self.expect_op(")")
                    out.append(("q", sub))
                else:
                    out.append(("t", "op", "("))
                    out.extend(self._expr_group())
                    self.expect_op(")")
                    out.append(("t", "op", ")"))
                continue
            out.append(("t", k, t))
            self.take()

    def _case_expr(self) -> Expr:
        """CASE ... END consumed whole (WHEN/THEN/ELSE are not clause
        stops inside it)."""
        self.take()  # CASE
        out: Expr = [("t", "word", "CASE")]
        depth = 1
        while depth:
            k, t = self.peek()
            if k is None:
                raise Unsupported("unterminated CASE")
            if k == "word" and t.upper() == "CASE":
                depth += 1
            elif k == "word" and t.upper() == "END":
                depth -= 1
            if k == "op" and t == "(":
                self.take()
                if self.at_word("SELECT", "VALUES", "WITH"):
                    sub = self.select_stmt()
                    self.expect_op(")")
                    out.append(("q", sub))
                else:
                    out.append(("t", "op", "("))
                    out.extend(self._expr_group())
                    self.expect_op(")")
                    out.append(("t", "op", ")"))
                continue
            out.append(("t", k, t))
            self.take()
        return out

    # -- select ----------------------------------------------------------

    def with_clause(self):
        ctes = []
        recursive = False
        if self.at_word("WITH"):
            self.take()
            if self.at_word("RECURSIVE"):
                self.take()
                recursive = True
            while True:
                name = self.ident()
                cols = None
                if self.at_op("("):
                    cols = self.col_list()
                self.expect_word("AS")
                # MATERIALIZED hints: drop (sqlite decides itself)
                if self.at_word("NOT"):
                    self.take()
                    self.expect_word("MATERIALIZED")
                elif self.at_word("MATERIALIZED"):
                    self.take()
                self.expect_op("(")
                if not self.at_word("SELECT", "VALUES", "WITH"):
                    raise Unsupported("non-SELECT CTE body")
                body = self.select_stmt()
                self.expect_op(")")
                ctes.append((name, cols, body))
                if self.at_op(","):
                    self.take()
                    continue
                break
        return ctes, recursive

    def select_stmt(self, ctes=None, recursive=False) -> Select:
        if ctes is None:
            ctes, recursive = self.with_clause()
        node = Select(ctes=ctes, recursive=recursive)
        node.core = self.select_core()
        while self.at_word(*_COMPOUND_OPS):
            op = self.take()[1].upper()
            if self.at_word("ALL", "DISTINCT"):
                op += " " + self.take()[1].upper()
            node.compounds.append((op, self.select_core()))
        if self.at_word("ORDER"):
            self.take()
            self.expect_word("BY")
            node.order_by.append(self.expr(stop_commas=True))
            while self.at_op(","):
                self.take()
                node.order_by.append(self.expr(stop_commas=True))
        if self.at_word("LIMIT"):
            self.take()
            if self.at_word("ALL"):
                self.take()
            else:
                node.limit = self.expr(stop_commas=True)
        if self.at_word("OFFSET"):
            self.take()
            node.offset = self.expr(stop_commas=True)
            if self.at_word("ROW", "ROWS"):
                self.take()
        if self.at_word("FETCH"):
            raise Unsupported("FETCH FIRST")
        if self.at_word("FOR"):
            # FOR UPDATE / FOR SHARE [OF ...] [NOWAIT|SKIP LOCKED]:
            # dropped — storage is single-writer, there are no row locks
            self.take()
            while not self.done() and not self.at_op(")"):
                self.take()
        return node

    def select_core(self) -> SelectCore:
        core = SelectCore()
        if self.at_op("("):
            self.take()
            inner = self.select_stmt()
            self.expect_op(")")
            if inner.ctes or inner.compounds or inner.order_by or \
                    inner.limit or inner.offset:
                raise Unsupported("parenthesized compound member")
            return inner.core
        if self.at_word("VALUES"):
            self.take()
            core.values = [self._values_row()]
            while self.at_op(","):
                self.take()
                core.values.append(self._values_row())
            return core
        self.expect_word("SELECT")
        if self.at_word("ALL"):
            self.take()
        elif self.at_word("DISTINCT"):
            self.take()
            if self.at_word("ON"):
                raise Unsupported("DISTINCT ON")
            core.distinct = True
        while True:
            if self.at_op("*"):
                self.take()
                core.items.append(([("t", "op", "*")], None))
            else:
                e = self.expr(stop_commas=True)
                # tbl.* projections arrive as expr tokens — fine
                core.items.append((e, self._item_alias()))
            if self.at_op(","):
                self.take()
                continue
            break
        if self.at_word("FROM"):
            self.take()
            self._from_clause(core)
        if self.at_word("WHERE"):
            self.take()
            core.where = self.expr()
        if self.at_word("GROUP"):
            self.take()
            self.expect_word("BY")
            core.group_by.append(self.expr(stop_commas=True))
            while self.at_op(","):
                self.take()
                core.group_by.append(self.expr(stop_commas=True))
        if self.at_word("HAVING"):
            self.take()
            core.having = self.expr()
        if self.at_word("WINDOW"):
            raise Unsupported("WINDOW clause")
        return core

    def _values_row(self) -> List[Expr]:
        self.expect_op("(")
        row = [self.expr(stop_commas=True)]
        while self.at_op(","):
            self.take()
            row.append(self.expr(stop_commas=True))
        self.expect_op(")")
        return row

    def _item_alias(self) -> Optional[str]:
        if self.at_word("AS"):
            self.take()
            return self.ident()
        k, t = self.peek()
        if k == "qident":
            return self.take()[1]
        if k == "word" and t.upper() not in _CLAUSE_STOPS:
            return self.take()[1]
        return None

    def _from_item(self) -> FromItem:
        if self.at_op("("):
            self.take()
            if self.at_word("SELECT", "VALUES", "WITH"):
                sub = self.select_stmt()
                self.expect_op(")")
                alias = self.opt_alias()
                if alias and self.at_op("("):
                    raise Unsupported("column aliases on subquery")
                return FromItem(select=sub, alias=alias)
            raise Unsupported("parenthesized join in FROM")
        if self.at_word("ONLY"):
            self.take()  # no table inheritance: ONLY is a no-op
        if self.at_word("LATERAL"):
            raise Unsupported("LATERAL")
        name = self.qname()
        if self.at_op("("):
            raise Unsupported("table function in FROM")
        alias = self.opt_alias()
        return FromItem(name=name, alias=alias)

    def _from_clause(self, core) -> None:
        core.from_items.append(self._from_item())
        while True:
            if self.at_op(","):
                self.take()
                core.joins.append(Join(",", self._from_item()))
                continue
            if self.at_word("NATURAL"):
                raise Unsupported("NATURAL JOIN")
            if self.at_word(*_JOIN_WORDS):
                jt = [self.take()[1].upper()]
                if jt[0] in ("LEFT", "RIGHT", "FULL") and self.at_word(
                    "OUTER"
                ):
                    self.take()
                if jt[0] != "JOIN":
                    jt.append(self.expect_word("JOIN"))
                jtype = " ".join(
                    w if w == "JOIN" else w for w in jt
                )
                item = self._from_item()
                j = Join(jtype, item)
                if self.at_word("ON"):
                    self.take()
                    j.on = self.expr()
                elif self.at_word("USING"):
                    self.take()
                    j.using = self.col_list()
                elif "CROSS" not in jtype:
                    raise Unsupported("JOIN without ON/USING")
                core.joins.append(j)
                continue
            break

    # -- DML -------------------------------------------------------------

    def returning_clause(self):
        if not self.at_word("RETURNING"):
            return None
        self.take()
        items = []
        while True:
            if self.at_op("*"):
                self.take()
                items.append(([("t", "op", "*")], None))
            else:
                e = self.expr(stop_commas=True)
                items.append((e, self._item_alias()))
            if self.at_op(","):
                self.take()
                continue
            break
        return items

    def insert_stmt(self, ctes) -> Insert:
        self.expect_word("INSERT")
        self.expect_word("INTO")
        node = Insert(ctes=ctes, table=self.qname())
        if self.at_word("AS"):
            self.take()
            node.alias = self.ident()
        if self.at_op("("):
            node.columns = self.col_list()
        if self.at_word("DEFAULT"):
            self.take()
            self.expect_word("VALUES")
            node.default_values = True
        elif self.at_word("VALUES"):
            self.take()
            node.values = [self._values_row()]
            while self.at_op(","):
                self.take()
                node.values.append(self._values_row())
        elif self.at_word("SELECT", "WITH") or self.at_op("("):
            node.select = self.select_stmt()
        else:
            raise Unsupported("INSERT source")
        if self.at_word("ON"):
            self.take()
            self.expect_word("CONFLICT")
            if self.at_op("("):
                node.conflict_target = self.col_list()
                if self.at_word("WHERE"):
                    raise Unsupported("partial conflict target")
            elif self.at_word("ON"):
                raise Unsupported("ON CONSTRAINT")
            self.expect_word("DO")
            if self.at_word("NOTHING"):
                self.take()
                node.conflict_action = "nothing"
            else:
                self.expect_word("UPDATE")
                self.expect_word("SET")
                node.conflict_action = "update"
                node.conflict_sets.append(self._set_item())
                while self.at_op(","):
                    self.take()
                    node.conflict_sets.append(self._set_item())
                if self.at_word("WHERE"):
                    self.take()
                    node.conflict_where = self.expr()
        node.returning = self.returning_clause()
        return node

    def _set_item(self):
        if self.at_op("("):
            raise Unsupported("multi-column SET")
        col = self.ident()
        self.expect_op("=")
        return (col, self.expr(stop_commas=True))

    def update_stmt(self, ctes) -> Update:
        self.expect_word("UPDATE")
        if self.at_word("ONLY"):
            self.take()
        node = Update(ctes=ctes, table=self.qname())
        node.alias = None
        if self.at_word("AS"):
            self.take()
            node.alias = self.ident()
        elif not self.at_word("SET"):
            k, t = self.peek()
            if k in ("word", "qident"):
                node.alias = self.ident()
        self.expect_word("SET")
        node.sets.append(self._set_item())
        while self.at_op(","):
            self.take()
            node.sets.append(self._set_item())
        if self.at_word("FROM"):
            self.take()
            self._from_clause(node)
        if self.at_word("WHERE"):
            self.take()
            node.where = self.expr()
        node.returning = self.returning_clause()
        return node

    def delete_stmt(self, ctes) -> Delete:
        self.expect_word("DELETE")
        self.expect_word("FROM")
        if self.at_word("ONLY"):
            self.take()
        node = Delete(ctes=ctes, table=self.qname())
        node.alias = self.opt_alias()
        if self.at_word("USING"):
            self.take()
            node.using.append(self._from_item())
            while self.at_op(","):
                self.take()
                node.using.append(self._from_item())
        if self.at_word("WHERE"):
            self.take()
            node.where = self.expr()
        node.returning = self.returning_clause()
        return node

    def statement(self):
        ctes, recursive = self.with_clause()
        if self.at_word("SELECT", "VALUES") or self.at_op("("):
            node = self.select_stmt(ctes, recursive)
        elif self.at_word("INSERT"):
            node = self.insert_stmt(ctes)
            node.recursive = recursive
        elif self.at_word("UPDATE"):
            node = self.update_stmt(ctes)
            node.recursive = recursive
        elif self.at_word("DELETE"):
            node = self.delete_stmt(ctes)
            node.recursive = recursive
        else:
            raise Unsupported(f"statement head {self.peek()}")
        if not self.done():
            raise Unsupported(f"trailing tokens at {self.peek()}")
        return node


def parse_statement(sql: str):
    """Parse ONE statement into an AST node, or raise Unsupported."""
    return _P(sql).statement()


# ---------------------------------------------------------------------------
# AST queries
# ---------------------------------------------------------------------------


def table_refs(node) -> List[QName]:
    """Every table the statement references (targets, FROM items,
    joins, sub-SELECTs, CTE bodies) — CTE names themselves are NOT
    tables; they shadow same-named tables LEXICALLY (only within the
    statement that defines them and its descendants, never siblings)."""
    out: List[QName] = []

    def walk_expr(e: Optional[Expr], shadow: frozenset):
        for el in e or ():
            if el[0] == "q":
                walk(el[1], shadow)

    def walk_core(core: SelectCore, shadow: frozenset):
        for fi in core.from_items:
            walk_from(fi, shadow)
        for j in core.joins:
            walk_from(j.item, shadow)
            walk_expr(j.on, shadow)
        for e, _a in core.items:
            walk_expr(e, shadow)
        walk_expr(core.where, shadow)
        for e in core.group_by:
            walk_expr(e, shadow)
        walk_expr(core.having, shadow)
        for row in core.values or ():
            for e in row:
                walk_expr(e, shadow)

    def walk_from(fi: FromItem, shadow: frozenset):
        if fi.name is not None:
            if not (len(fi.name.parts) == 1 and fi.name.base in shadow):
                out.append(fi.name)
        if fi.select is not None:
            walk(fi.select, shadow)

    def walk(n, shadow: frozenset):
        rec = getattr(n, "recursive", False)
        for name, _cols, body in getattr(n, "ctes", ()) or ():
            body_shadow = shadow
            if rec:
                # WITH RECURSIVE: the CTE's own name IS visible inside
                # its body (the self-reference is not a table)
                body_shadow = shadow | {name.strip('"').lower()}
            walk(body, body_shadow)
            # earlier CTEs are visible to later ones + the main body
            shadow = shadow | {name.strip('"').lower()}
        if isinstance(n, Select):
            walk_core(n.core, shadow)
            for _op, c in n.compounds:
                walk_core(c, shadow)
            for e in n.order_by:
                walk_expr(e, shadow)
            walk_expr(n.limit, shadow)
            walk_expr(n.offset, shadow)
        elif isinstance(n, Insert):
            out.append(n.table)
            if n.select is not None:
                walk(n.select, shadow)
            for row in n.values or ():
                for e in row:
                    walk_expr(e, shadow)
            for _c, e in n.conflict_sets:
                walk_expr(e, shadow)
            walk_expr(n.conflict_where, shadow)
            for e, _a in n.returning or ():
                walk_expr(e, shadow)
        elif isinstance(n, Update):
            out.append(n.table)
            for _c, e in n.sets:
                walk_expr(e, shadow)
            for fi in n.from_items:
                walk_from(fi, shadow)
            for j in n.joins:
                walk_from(j.item, shadow)
                walk_expr(j.on, shadow)
            walk_expr(n.where, shadow)
            for e, _a in n.returning or ():
                walk_expr(e, shadow)
        elif isinstance(n, Delete):
            out.append(n.table)
            for fi in n.using:
                walk_from(fi, shadow)
            walk_expr(n.where, shadow)
            for e, _a in n.returning or ():
                walk_expr(e, shadow)

    walk(node, frozenset())
    return out


def returning_names(node, star_columns) -> Optional[List[str]]:
    """RETURNING column labels: alias, else the last identifier of the
    expression, else the expression text; ``*`` expands via
    ``star_columns(table_base_name)``."""
    items = getattr(node, "returning", None)
    if items is None:
        return None
    names: List[str] = []
    for e, alias in items:
        if alias:
            names.append(alias.strip('"'))
            continue
        if len(e) == 1 and e[0] == ("t", "op", "*"):
            names.extend(star_columns(node.table.base))
            continue
        label = None
        for el in reversed(e):
            if el[0] == "t" and el[1] in ("word", "qident"):
                label = el[2].strip('"')
                break
        names.append(label if label is not None else _expr_text(e))
    return names


def _expr_text(e: Expr) -> str:
    parts = []
    for el in e:
        parts.append("(...)" if el[0] == "q" else el[2])
    return " ".join(parts)


# ---------------------------------------------------------------------------
# emitter
# ---------------------------------------------------------------------------


class Emitter:
    """AST → SQLite SQL + $N order.  ``strip_schemas`` drops the given
    schema qualifiers from table names (public. for user tables;
    pg_catalog./information_schema. when routing to the catalog)."""

    def __init__(self, strip_schemas=("public",)):
        self.strip = set(strip_schemas)
        self.order: List[int] = []

    # -- pieces ----------------------------------------------------------

    def expr(self, e: Expr) -> str:
        out: List[str] = []
        run: List[tuple] = []

        def flush():
            if run:
                buf: List[str] = []
                transform_tokens(list(run), buf, self.order)
                out.append("".join(buf))
                run.clear()

        for el in e:
            if el[0] == "q":
                flush()
                out.append("(" + self.select(el[1]) + ")")
            else:
                if run:
                    run.append(("ws", " "))
                run.append((el[1], el[2]))
        flush()
        return " ".join(out)

    def qname(self, q: QName) -> str:
        parts = list(q.parts)
        while len(parts) > 1 and parts[0].strip('"').lower() in self.strip:
            parts = parts[1:]
        return ".".join(parts)

    def _items(self, items) -> str:
        return ", ".join(
            self.expr(e) + (f" AS {a}" if a else "")
            for e, a in items
        )

    def from_clause(self, from_items, joins) -> str:
        def item(fi: FromItem) -> str:
            if fi.select is not None:
                s = "(" + self.select(fi.select) + ")"
            else:
                s = self.qname(fi.name)
            return s + (f" AS {fi.alias}" if fi.alias else "")

        s = ", ".join(item(fi) for fi in from_items)
        for j in joins:
            if j.jtype == ",":
                s += ", " + item(j.item)
                continue
            s += f" {j.jtype} {item(j.item)}"
            if j.on is not None:
                s += " ON " + self.expr(j.on)
            elif j.using is not None:
                s += " USING (" + ", ".join(j.using) + ")"
        return s

    def core(self, c: SelectCore) -> str:
        if c.values is not None:
            return "VALUES " + ", ".join(
                "(" + ", ".join(self.expr(e) for e in row) + ")"
                for row in c.values
            )
        s = "SELECT "
        if c.distinct:
            s += "DISTINCT "
        s += self._items(c.items)
        if c.from_items:
            s += " FROM " + self.from_clause(c.from_items, c.joins)
        if c.where is not None:
            s += " WHERE " + self.expr(c.where)
        if c.group_by:
            s += " GROUP BY " + ", ".join(self.expr(e) for e in c.group_by)
        if c.having is not None:
            s += " HAVING " + self.expr(c.having)
        return s

    def select(self, n: Select) -> str:
        s = self._ctes(n)
        s += self.core(n.core)
        for op, c in n.compounds:
            s += f" {op} " + self.core(c)
        if n.order_by:
            s += " ORDER BY " + ", ".join(self.expr(e) for e in n.order_by)
        if n.limit is not None:
            s += " LIMIT " + self.expr(n.limit)
        if n.offset is not None:
            s += " OFFSET " + self.expr(n.offset)
        return s

    def _ctes(self, node) -> str:
        if not node.ctes:
            return ""
        head = "WITH RECURSIVE " if getattr(
            node, "recursive", False
        ) else "WITH "
        return head + ", ".join(
            name
            + (f" ({', '.join(cols)})" if cols else "")
            + " AS (" + self.select(body) + ")"
            for name, cols, body in node.ctes
        ) + " "

    def _returning(self, node) -> str:
        if node.returning is None:
            return ""
        return " RETURNING " + self._items(node.returning)

    def insert(self, n: Insert) -> str:
        s = self._ctes(n) + "INSERT INTO " + self.qname(n.table)
        if n.alias:
            s += f" AS {n.alias}"
        if n.columns:
            s += " (" + ", ".join(n.columns) + ")"
        if n.default_values:
            s += " DEFAULT VALUES"
        elif n.values is not None:
            s += " VALUES " + ", ".join(
                "(" + ", ".join(self.expr(e) for e in row) + ")"
                for row in n.values
            )
        else:
            sel = n.select
            if n.conflict_action and sel.core.values is None:
                # sqlite requires a WHERE on a SELECT source before an
                # upsert clause (documented parsing ambiguity)
                if sel.compounds or sel.ctes:
                    raise Unsupported(
                        "ON CONFLICT after a compound/CTE SELECT source"
                    )
                if sel.core.where is None:
                    sel.core.where = [("t", "word", "true")]
            s += " " + self.select(sel)
        if n.conflict_action:
            s += " ON CONFLICT"
            if n.conflict_target:
                s += " (" + ", ".join(n.conflict_target) + ")"
            if n.conflict_action == "nothing":
                s += " DO NOTHING"
            else:
                s += " DO UPDATE SET " + ", ".join(
                    f"{c} = " + self.expr(e) for c, e in n.conflict_sets
                )
                if n.conflict_where is not None:
                    s += " WHERE " + self.expr(n.conflict_where)
        return s + self._returning(n)

    def update(self, n: Update) -> str:
        s = self._ctes(n) + "UPDATE " + self.qname(n.table)
        if n.alias:
            s += f" AS {n.alias}"
        s += " SET " + ", ".join(
            f"{c} = " + self.expr(e) for c, e in n.sets
        )
        if n.from_items:
            s += " FROM " + self.from_clause(n.from_items, n.joins)
        if n.where is not None:
            s += " WHERE " + self.expr(n.where)
        return s + self._returning(n)

    def delete(self, n: Delete) -> str:
        s = self._ctes(n) + "DELETE FROM " + self.qname(n.table)
        if n.alias:
            s += f" AS {n.alias}"
        if n.using:
            # sqlite has no DELETE..USING: rewrite as a correlated
            # EXISTS would change semantics; refuse instead
            raise Unsupported("DELETE USING")
        if n.where is not None:
            s += " WHERE " + self.expr(n.where)
        return s + self._returning(n)

    def emit(self, node) -> str:
        if isinstance(node, Select):
            return self.select(node)
        if isinstance(node, Insert):
            return self.insert(node)
        if isinstance(node, Update):
            return self.update(node)
        if isinstance(node, Delete):
            return self.delete(node)
        raise Unsupported(f"emit {type(node).__name__}")


def emit(node, strip_schemas=("public",)) -> Tuple[str, List[int]]:
    """AST → (sqlite SQL, $N parameter order)."""
    em = Emitter(strip_schemas)
    sql = em.emit(node)
    return sql, em.order
