"""PostgreSQL wire-protocol (v3) front-end.

Parity: ``crates/corro-pg`` ◆ — a pgwire server that lets any Postgres
client read and write the CRDT database: startup/auth handshake, the
**simple** query protocol, the **extended** protocol
(Parse/Bind/Describe/Execute/Close/Sync with prepared statements and
portals), transaction status tracking, and error responses.  Writes go
through the agent's versioned write path so they broadcast like any HTTP
transaction (``corro-pg/src/lib.rs:545``).

Implementation notes:

* SQL passes through with a light PG→SQLite translation ($N params →
  ?, ``::type`` casts stripped, a few function renames) — the reference
  does a full sqlparser→sqlite3-parser AST translation; ours leans on
  the large shared SQL dialect instead.
* results are sent in text format with OID 25 (TEXT) per column, which
  every driver accepts; ``version()`` and trivial ``pg_catalog`` probes
  get canned answers.
* BEGIN/COMMIT group writes into ONE replication version (buffered until
  COMMIT); reads always see committed state.
"""

from __future__ import annotations

import asyncio
import re
import struct
from typing import Dict, List, Optional, Tuple, TYPE_CHECKING

if TYPE_CHECKING:
    from corrosion_tpu.agent.runtime import Agent

PROTO_V3 = 196608
SSL_REQUEST = 80877103
CANCEL_REQUEST = 80877102

TEXT_OID = 25


def _msg(tag: bytes, payload: bytes = b"") -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class _Buffer:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def int16(self) -> int:
        v = struct.unpack_from(">h", self.data, self.pos)[0]
        self.pos += 2
        return v

    def int32(self) -> int:
        v = struct.unpack_from(">i", self.data, self.pos)[0]
        self.pos += 4
        return v

    def string(self) -> str:
        end = self.data.index(b"\x00", self.pos)
        s = self.data[self.pos : end].decode()
        self.pos = end + 1
        return s

    def read(self, n: int) -> bytes:
        v = self.data[self.pos : self.pos + n]
        self.pos += n
        return v


_CAST_RE = re.compile(r"::[a-zA-Z_][a-zA-Z0-9_]*(\[\])?")
_FUNC_MAP = {
    "now()": "datetime('now')",
    "current_timestamp": "datetime('now')",
}


def translate_query(sql: str) -> Tuple[str, List[int]]:
    """Light PG→SQLite translation, string-literal aware.

    Returns (sql, param_order): each ``$N`` becomes ``?`` and
    ``param_order`` records N per placeholder, so callers can bind
    out-of-order / repeated parameter references correctly.
    """
    out: List[str] = []
    order: List[int] = []
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        if ch == "'":
            j = i + 1
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        j += 2
                        continue
                    break
                j += 1
            out.append(sql[i : j + 1])
            i = j + 1
            continue
        if ch == "$" and i + 1 < n and sql[i + 1].isdigit():
            j = i + 1
            while j < n and sql[j].isdigit():
                j += 1
            order.append(int(sql[i + 1 : j]))
            out.append("?")
            i = j
            continue
        if ch == ":" and i + 1 < n and sql[i + 1] == ":":
            m = _CAST_RE.match(sql, i)
            if m:
                i = m.end()
                continue
        out.append(ch)
        i += 1
    text = "".join(out)
    for k, v in _FUNC_MAP.items():
        text = re.sub(re.escape(k), v, text, flags=re.IGNORECASE)
    return text, order


def translate_sql(sql: str) -> str:
    return translate_query(sql)[0]


def _is_write(sql: str) -> bool:
    head = sql.lstrip().split(None, 1)
    return bool(head) and head[0].upper() in (
        "INSERT", "UPDATE", "DELETE", "REPLACE", "CREATE", "DROP", "ALTER",
    )


def _tag_for(sql: str, rowcount: int, nrows: int) -> str:
    word = sql.lstrip().split(None, 1)[0].upper() if sql.strip() else ""
    if word == "SELECT" or word == "WITH":
        return f"SELECT {nrows}"
    if word == "INSERT":
        return f"INSERT 0 {max(rowcount, 0)}"
    if word in ("UPDATE", "DELETE"):
        return f"{word} {max(rowcount, 0)}"
    return word or "OK"


class _Session:
    def __init__(self, agent: "Agent"):
        self.agent = agent
        self.stmts: Dict[str, Tuple[str, str]] = {}  # name -> (raw, translated)
        self.portals: Dict[str, Tuple[str, List[Optional[bytes]]]] = {}
        self.in_txn = False
        self.txn_failed = False
        self.txn_writes: List[list] = []

    # -- execution -------------------------------------------------------

    def execute(self, sql: str, params: Tuple = ()) -> Tuple[List[str], List[tuple], int, str]:
        """Returns (columns, rows, rowcount, tag)."""
        raw = sql.strip().rstrip(";")
        word = raw.split(None, 1)[0].upper() if raw else ""
        if word == "BEGIN" or word == "START":
            self.in_txn, self.txn_failed, self.txn_writes = True, False, []
            return [], [], 0, "BEGIN"
        if word == "COMMIT" or word == "END":
            writes, self.txn_writes = self.txn_writes, []
            self.in_txn = False
            if self.txn_failed:
                self.txn_failed = False
                return [], [], 0, "ROLLBACK"
            if writes:
                self.agent.execute_transaction(writes)
            return [], [], 0, "COMMIT"
        if word == "ROLLBACK":
            self.in_txn, self.txn_failed, self.txn_writes = False, False, []
            return [], [], 0, "ROLLBACK"
        if not raw:
            return [], [], 0, ""

        canned = self._canned(raw)
        if canned is not None:
            return canned

        tsql = translate_sql(raw)
        if _is_write(tsql):
            stmt = [tsql, list(params)] if params else [tsql]
            if self.in_txn:
                self.txn_writes.append(stmt)
                # rowcount unknown until commit; report optimistically
                return [], [], 1, _tag_for(tsql, 1, 0)
            out = self.agent.execute_transaction([stmt])
            rc = out["results"][0].get("rows_affected", 0)
            return [], [], rc, _tag_for(tsql, rc, 0)
        cols, rows = self.agent.storage.read_query(tsql, params)
        return cols, rows, len(rows), _tag_for(tsql, -1, len(rows))

    def _canned(self, raw: str):
        low = " ".join(raw.lower().split())
        if low in ("select version()", "select version();"):
            return (
                ["version"],
                [("PostgreSQL 14.9 (corrosion-tpu sqlite CRDT)",)],
                1,
                "SELECT 1",
            )
        if low.startswith("set ") or low.startswith("reset "):
            return [], [], 0, "SET"
        if low.startswith("show "):
            return ["setting"], [("",)], 1, "SELECT 1"
        if "pg_catalog" in low or "information_schema" in low:
            # minimal catalog: list CRR tables for pg_class-style probes
            if "pg_class" in low or "tables" in low:
                rows = [(t,) for t in self.agent.storage.tables]
                return ["relname"], rows, len(rows), f"SELECT {len(rows)}"
            return ["?column?"], [], 0, "SELECT 0"
        return None


async def serve_pg(agent: "Agent", host: str = "127.0.0.1", port: int = 0):
    """Start the pgwire listener; returns the asyncio server."""
    return await asyncio.start_server(
        lambda r, w: _handle_conn(agent, r, w), host, port
    )


async def _handle_conn(agent: "Agent", reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
    session = _Session(agent)
    try:
        # --- startup ----------------------------------------------------
        while True:
            head = await reader.readexactly(4)
            (length,) = struct.unpack(">I", head)
            body = await reader.readexactly(length - 4)
            (proto,) = struct.unpack_from(">I", body, 0)
            if proto == SSL_REQUEST:
                writer.write(b"N")  # no TLS
                await writer.drain()
                continue
            if proto == CANCEL_REQUEST:
                return
            if proto != PROTO_V3:
                _error(writer, "08P01", f"unsupported protocol {proto}")
                return
            break
        writer.write(_msg(b"R", struct.pack(">I", 0)))  # AuthenticationOk
        for k, v in (
            ("server_version", "14.9"),
            ("server_encoding", "UTF8"),
            ("client_encoding", "UTF8"),
            ("DateStyle", "ISO"),
        ):
            writer.write(_msg(b"S", _cstr(k) + _cstr(v)))
        writer.write(_msg(b"K", struct.pack(">II", 0, 0)))
        _ready(writer, session)
        await writer.drain()

        # --- message loop -----------------------------------------------
        while True:
            tag = await reader.readexactly(1)
            (length,) = struct.unpack(">I", await reader.readexactly(4))
            body = await reader.readexactly(length - 4)
            if tag == b"X":
                return
            if tag == b"Q":
                await _simple_query(writer, session, _Buffer(body).string())
            elif tag == b"P":
                b = _Buffer(body)
                name, query = b.string(), b.string()
                session.stmts[name] = (query, translate_sql(query))
                writer.write(_msg(b"1"))
            elif tag == b"B":
                _bind(writer, session, _Buffer(body))
            elif tag == b"D":
                _describe(writer, session, _Buffer(body))
            elif tag == b"E":
                await _execute_portal(writer, session, _Buffer(body))
            elif tag == b"C":
                b = _Buffer(body)
                kind, name = b.read(1), b.string()
                (session.stmts if kind == b"S" else session.portals).pop(name, None)
                writer.write(_msg(b"3"))
            elif tag == b"S":
                _ready(writer, session)
            elif tag == b"H":
                pass  # flush: we always flush below
            else:
                _error(writer, "08P01", f"unsupported message {tag!r}")
                _ready(writer, session)
            await writer.drain()
    except (asyncio.IncompleteReadError, ConnectionError):
        return
    finally:
        writer.close()


def _ready(writer, session: _Session) -> None:
    status = b"E" if session.txn_failed else (b"T" if session.in_txn else b"I")
    writer.write(_msg(b"Z", status))


def _error(writer, code: str, message: str) -> None:
    payload = (
        b"S" + _cstr("ERROR") + b"C" + _cstr(code) + b"M" + _cstr(message) + b"\x00"
    )
    writer.write(_msg(b"E", payload))


def _row_description(writer, cols: List[str]) -> None:
    payload = struct.pack(">h", len(cols))
    for c in cols:
        payload += _cstr(c) + struct.pack(">IhIhih", 0, 0, TEXT_OID, -1, -1, 0)
    writer.write(_msg(b"T", payload))


def _data_rows(writer, rows: List[tuple]) -> None:
    for row in rows:
        payload = struct.pack(">h", len(row))
        for v in row:
            if v is None:
                payload += struct.pack(">i", -1)
            else:
                if isinstance(v, bool):
                    s = b"t" if v else b"f"
                elif isinstance(v, (bytes, bytearray, memoryview)):
                    s = b"\\x" + bytes(v).hex().encode()
                else:
                    s = str(v).encode()
                payload += struct.pack(">i", len(s)) + s
        writer.write(_msg(b"D", payload))


async def _simple_query(writer, session: _Session, query: str) -> None:
    parts = [p for p in _split_statements(query) if p.strip()]
    if not parts:
        writer.write(_msg(b"I"))  # EmptyQueryResponse
        _ready(writer, session)
        return
    for part in parts:
        try:
            cols, rows, rc, tag = session.execute(part)
        except Exception as e:
            if session.in_txn:
                session.txn_failed = True
            _error(writer, "42601", str(e))
            break
        if cols:
            _row_description(writer, cols)
            _data_rows(writer, rows)
        writer.write(_msg(b"C", _cstr(tag)))
    _ready(writer, session)


def _bind(writer, session: _Session, b: _Buffer) -> None:
    portal, stmt = b.string(), b.string()
    nfmt = b.int16()
    fmts = [b.int16() for _ in range(nfmt)]
    nparams = b.int16()
    params: List[Optional[bytes]] = []
    for i in range(nparams):
        ln = b.int32()
        params.append(None if ln == -1 else b.read(ln))
    if stmt not in session.stmts:
        _error(writer, "26000", f"unknown prepared statement {stmt!r}")
        return
    # text format assumed (fmt 0); binary params are rejected
    if any(f == 1 for f in fmts):
        _error(writer, "0A000", "binary parameter format not supported")
        return
    session.portals[portal] = (stmt, params)
    writer.write(_msg(b"2"))


def _describe(writer, session: _Session, b: _Buffer) -> None:
    kind, name = b.read(1), b.string()
    # we don't know result columns until execution: report NoData for
    # writes, ParameterDescription+NoData for statements
    if kind == b"S":
        raw = session.stmts.get(name, ("", ""))[0]
        nparams = len(set(re.findall(r"\$(\d+)", raw)))
        writer.write(
            _msg(b"t", struct.pack(">h", nparams) + struct.pack(">I", TEXT_OID) * nparams)
        )
    writer.write(_msg(b"n"))  # NoData; RowDescription arrives with Execute


async def _execute_portal(writer, session: _Session, b: _Buffer) -> None:
    portal = b.string()
    b.int32()  # row limit (0 = all); portals are always drained fully
    entry = session.portals.get(portal)
    if entry is None:
        _error(writer, "34000", f"unknown portal {portal!r}")
        return
    stmt_name, raw_params = entry
    raw, tsql = session.stmts[stmt_name]
    params = tuple(
        None if p is None else p.decode() for p in raw_params
    )
    try:
        cols, rows, rc, tag = session.execute(raw, params)
    except Exception as e:
        if session.in_txn:
            session.txn_failed = True
        _error(writer, "42601", str(e))
        return
    if cols:
        _row_description(writer, cols)
        _data_rows(writer, rows)
    writer.write(_msg(b"C", _cstr(tag)))


def _split_statements(query: str) -> List[str]:
    """Split on top-level semicolons (string-literal aware)."""
    parts: List[str] = []
    buf: List[str] = []
    in_str = False
    i = 0
    while i < len(query):
        ch = query[i]
        if in_str:
            buf.append(ch)
            if ch == "'":
                if i + 1 < len(query) and query[i + 1] == "'":
                    buf.append("'")
                    i += 1
                else:
                    in_str = False
        elif ch == "'":
            in_str = True
            buf.append(ch)
        elif ch == ";":
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
        i += 1
    if buf:
        parts.append("".join(buf))
    return parts
