"""PostgreSQL wire-protocol (v3) front-end.

Parity: ``crates/corro-pg`` ◆ — a pgwire server that lets any Postgres
client read and write the CRDT database: startup/auth handshake, the
**simple** query protocol, the **extended** protocol
(Parse/Bind/Describe/Execute/Close/Sync with prepared statements and
portals), transaction status tracking, and error responses.  Writes go
through the agent's versioned write path so they broadcast like any HTTP
transaction (``corro-pg/src/lib.rs:545``).

Implementation notes:

* SQL is parsed AST-FIRST by the recursive-descent statement parser
  (``agent/pgparse.py`` — the architecture of the reference's
  sqlparser→sqlite3-parser walk): statement class, catalog routing,
  RETURNING names, ON CONFLICT and command tags all come from the
  grammar, and $N order / casts / E'' strings / function mapping are
  applied by the shared token transforms during emission.  Statements
  outside the grammar fall back to the whole-string token pass
  (``agent/pgsql.py``), counted by corro_pg_parse_fallbacks_total.
* errors carry real SQLSTATEs (``agent/sqlstate.py``); SAVEPOINT /
  ROLLBACK TO / RELEASE work against the buffered transaction model;
  SET/SHOW/RESET are session GUCs; CancelRequest with a real
  BackendKeyData key interrupts the in-flight query (57014).
* the extended protocol honors Execute row limits with portal
  suspension (PortalSuspended / resume), and SSLRequest upgrades the
  stream to TLS when the agent has a cert configured (corro-pg TLS
  parity).
* parameters bind TYPED: the Parse message's declared OIDs (and binary
  format codes) decode ints as ints, floats as floats, bytea as bytes —
  so a PG-written row stores the same sqlite value a HTTP-written row
  does and the two merge identically under LWW (``corro-pg/src/lib.rs``
  name_to_type parity; the reference binds by declared OID the same
  way).
* results are sent in text format with per-column OIDs inferred from
  the row values (int8/float8/bool/bytea/text), which typed drivers
  parse back into native values.
* ``pg_catalog`` / ``information_schema`` queries run against a real
  sqlite rendering of the catalog (pg_class, pg_namespace,
  pg_attribute, pg_type + information_schema tables/columns) rebuilt
  from the live schema — the sqlite answer to the reference's
  ``corro-pg/src/vtab/`` virtual tables.
* BEGIN/COMMIT group writes into ONE replication version (buffered
  until COMMIT); reads inside the transaction see its own buffered
  writes (READ COMMITTED read-your-writes, evaluated in a rolled-back
  sandbox — no lock held across client round trips), other sessions
  see committed state only.
"""

from __future__ import annotations

import asyncio
import itertools
import re
import secrets
import struct
import threading
from typing import Dict, List, Optional, Tuple, TYPE_CHECKING

from corrosion_tpu.agent import pgparse
from corrosion_tpu.agent.sqlstate import PgError, SQLSTATE, sqlstate_for

if TYPE_CHECKING:
    from corrosion_tpu.agent.runtime import Agent

PROTO_V3 = 196608
SSL_REQUEST = 80877103
CANCEL_REQUEST = 80877102

BOOL_OID = 16
BYTEA_OID = 17
INT8_OID = 20
INT2_OID = 21
INT4_OID = 23
TEXT_OID = 25
FLOAT4_OID = 700
FLOAT8_OID = 701
VARCHAR_OID = 1043
NUMERIC_OID = 1700

_INT_OIDS = (INT2_OID, INT4_OID, INT8_OID)
_FLOAT_OIDS = (FLOAT4_OID, FLOAT8_OID)


def _decode_param(data: bytes, oid: int, fmt: int):
    """Decode one Bind parameter into the native sqlite value its
    declared OID names (the typed-binding fix: TEXT-decoding everything
    made PG writes diverge from HTTP writes under LWW)."""
    if fmt == 1:  # binary
        if oid in _INT_OIDS:
            return int.from_bytes(data, "big", signed=True)
        if oid == FLOAT8_OID:
            return struct.unpack(">d", data)[0]
        if oid == FLOAT4_OID:
            return struct.unpack(">f", data)[0]
        if oid == BOOL_OID:
            return 1 if data and data[0] else 0
        if oid == BYTEA_OID:
            return data
        if oid in (TEXT_OID, VARCHAR_OID, 0):
            return data.decode()
        raise ValueError(f"binary format for OID {oid} not supported")
    s = data.decode()
    if oid in _INT_OIDS:
        return int(s)
    if oid in _FLOAT_OIDS:
        return float(s)
    if oid == NUMERIC_OID:
        return int(s) if re.fullmatch(r"[+-]?\d+", s) else float(s)
    if oid == BOOL_OID:
        return 1 if s.lower() in ("t", "true", "1", "yes", "on") else 0
    if oid == BYTEA_OID:
        return bytes.fromhex(s[2:]) if s.startswith("\\x") else s.encode()
    # unknown / text: sqlite column affinity does the rest, exactly as
    # it does for an HTTP-written JSON string
    return s


def _infer_oid(values) -> int:
    """Result-column OID from the first non-null value (the schema is
    sqlite's, so value type IS the column's storage class)."""
    for v in values:
        if v is None:
            continue
        if isinstance(v, bool):
            return BOOL_OID
        if isinstance(v, int):
            return INT8_OID
        if isinstance(v, float):
            return FLOAT8_OID
        if isinstance(v, (bytes, bytearray, memoryview)):
            return BYTEA_OID
        return TEXT_OID
    return TEXT_OID


def _msg(tag: bytes, payload: bytes = b"") -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class _Buffer:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def int16(self) -> int:
        v = struct.unpack_from(">h", self.data, self.pos)[0]
        self.pos += 2
        return v

    def int32(self) -> int:
        v = struct.unpack_from(">i", self.data, self.pos)[0]
        self.pos += 4
        return v

    def string(self) -> str:
        end = self.data.index(b"\x00", self.pos)
        s = self.data[self.pos : end].decode()
        self.pos = end + 1
        return s

    def read(self, n: int) -> bytes:
        v = self.data[self.pos : self.pos + n]
        self.pos += n
        return v


# PG→SQLite translation: the tokenizer pass in agent/pgsql.py (the
# token-aware successor of the old regex translation; the reference
# does a full sqlparser→sqlite3-parser AST rewrite)
from corrosion_tpu.agent.pgsql import (  # noqa: E402
    split_statements as _split_statements,
    translate_query,
)


def translate_sql(sql: str) -> str:
    return translate_query(sql)[0]


_WRITE_WORDS = frozenset((
    "INSERT", "UPDATE", "DELETE", "REPLACE", "CREATE", "DROP", "ALTER",
))


def _is_write(sql: str) -> bool:
    head = sql.lstrip().split(None, 1)
    if not head:
        return False
    first = head[0].upper()
    if first in _WRITE_WORDS:
        return True
    if first != "WITH":
        return False
    # CTE-led DML (WITH ... INSERT/UPDATE/DELETE) is a write: the DML
    # head follows the ')' closing the last CTE body at depth 0.  Only
    # INSERT/UPDATE/DELETE can head a statement after a CTE list, and
    # all three are reserved words in PG (unusable as bare aliases) —
    # so a write word in any other position (the function call in
    # WITH x AS (SELECT 1) SELECT replace(a, '1', '2'), or the alias
    # in SELECT (a+b) replace) is never one.  The call-opening check
    # guards the residual insert(...) extension-function shape.
    from corrosion_tpu.agent.pgsql import tokenize

    try:
        depth = 0
        prev = None  # previous significant token text
        toks = [t for t in tokenize(sql) if t[0] not in ("ws", "comment")]
        for j, (k, txt) in enumerate(toks):
            if k == "op" and txt == "(":
                depth += 1
            elif k == "op" and txt == ")":
                depth -= 1
            elif (
                k == "word" and depth == 0
                and txt.upper() in ("INSERT", "UPDATE", "DELETE")
                and prev == ")"
                and not (j + 1 < len(toks) and toks[j + 1][1] == "(")
            ):
                return True
            prev = txt
    except Exception:
        pass
    return False


def _ast_returning_columns(raw: str, agent) -> Optional[List[str]]:
    """RETURNING column names from the AST (grammar-grounded), or None
    when the statement is outside the grammar / not a write / has no
    RETURNING clause.  Used by Describe so drivers see the row shape
    without executing."""
    try:
        node = pgparse.parse_statement(raw)
    except pgparse.Unsupported:
        return None
    if not isinstance(node, (pgparse.Insert, pgparse.Update,
                             pgparse.Delete)):
        return None
    return pgparse.returning_names(
        node, lambda t: _star_columns(agent, t)
    )


def _returning_columns(tsql: str, agent) -> Optional[List[str]]:
    """Column names a write's RETURNING clause will produce, or None
    when there is no RETURNING clause.  Token-derived FALLBACK for
    statements the parser does not cover (never matches inside
    literals): each item contributes its alias, else its last
    identifier; ``*`` expands to the target table's columns."""
    from corrosion_tpu.agent.pgsql import tokenize

    try:
        tokens = [t for t in tokenize(tsql) if t[0] not in ("ws", "comment")]
    except Exception:
        return None
    idx = next(
        (i for i, (k, txt) in enumerate(tokens)
         if k == "word" and txt.upper() == "RETURNING"),
        None,
    )
    if idx is None:
        return None
    # split the tail into comma-separated items at paren depth 0
    # (RETURNING is last in sqlite's grammar, so the tail IS the list;
    # a comma inside coalesce(a, b) is NOT a separator)
    items: List[List[Tuple[str, str]]] = [[]]
    depth = 0
    for k, txt in tokens[idx + 1:]:
        if k == "op" and txt == "(":
            depth += 1
        elif k == "op" and txt == ")":
            depth -= 1
        if k == "op" and txt == "," and depth == 0:
            items.append([])
        else:
            items[-1].append((k, txt))
    cols: List[str] = []
    for item in items:
        if not item:
            continue
        if len(item) == 1 and item[0][1] == "*":
            # expand from the statement's target table (token after
            # INSERT INTO / UPDATE / DELETE FROM; quoted names count)
            names_toks = [
                (k, txt) for k, txt in tokens if k in ("word", "qident")
            ]
            table = None
            for i, (_k, w) in enumerate(names_toks):
                up = w.upper()
                if up in ("INTO", "UPDATE") or (
                    up == "FROM" and i > 0
                    and names_toks[i - 1][1].upper() == "DELETE"
                ):
                    if i + 1 < len(names_toks):
                        table = names_toks[i + 1][1].strip('"')
                    break
            cols.extend(_star_columns(agent, table))
            continue
        # alias (AS name / trailing bare word), else last identifier
        names = [txt for k, txt in item if k in ("word", "qident")]
        cols.append(names[-1].strip('"') if names else "?column?")
    return cols


def _star_columns(agent, table: Optional[str]) -> List[str]:
    """RETURNING * expansion in SQLite's DECLARATION order (pk-first
    reordering would mislabel the DataRow fields).  Served from the
    schema-version-keyed column cache so wire DDL (ALTER TABLE over
    PG) is picked up without a per-statement table_info scan."""
    if table:
        cols = agent.storage.declared_columns(table)
        if cols:
            return list(cols)
    return ["*"]


def _tag_for(sql: str, rowcount: int, nrows: int) -> str:
    word = sql.lstrip().split(None, 1)[0].upper() if sql.strip() else ""
    if word == "SELECT" or word == "WITH":
        return f"SELECT {nrows}"
    if word == "INSERT":
        return f"INSERT 0 {max(rowcount, 0)}"
    if word in ("UPDATE", "DELETE"):
        return f"{word} {max(rowcount, 0)}"
    return word or "OK"


_PG_TYPE_ROWS = [
    (BOOL_OID, "bool"), (BYTEA_OID, "bytea"), (INT8_OID, "int8"),
    (INT2_OID, "int2"), (INT4_OID, "int4"), (TEXT_OID, "text"),
    (FLOAT4_OID, "float4"), (FLOAT8_OID, "float8"),
    (VARCHAR_OID, "varchar"), (NUMERIC_OID, "numeric"),
]


def _decltype_oid(decl: str) -> int:
    d = (decl or "").upper()
    if "INT" in d:
        return INT8_OID
    if any(k in d for k in ("REAL", "FLOA", "DOUB")):
        return FLOAT8_OID
    if "BOOL" in d:
        return BOOL_OID
    if "BLOB" in d or not d:
        return BYTEA_OID
    return TEXT_OID


def _pg_typename(oid: int) -> str:
    return dict(_PG_TYPE_ROWS).get(oid, "text")


def build_catalog(agent: "Agent"):
    """Render the live schema as REAL pg_catalog / information_schema
    tables in a throwaway in-memory sqlite db, so clients can run
    actual catalog SQL (joins over pg_class/pg_attribute, \\d-style
    probes) instead of getting canned one-liners.  The sqlite answer to
    the reference's ``corro-pg/src/vtab/`` (pg_class.rs etc.) virtual
    tables, rebuilt per query from ``PRAGMA table_info``.
    """
    import sqlite3

    from corrosion_tpu.agent.storage import register_udfs

    # sessions execute off-loop (asyncio.to_thread), so the cached
    # catalog connection is used from varying worker threads; access
    # is serialized per statement by the session round-trip
    cat = sqlite3.connect(":memory:", check_same_thread=False)
    register_udfs(cat)  # current_database() etc. inside catalog queries
    cat.executescript(
        """
CREATE TABLE pg_namespace (oid INTEGER PRIMARY KEY, nspname TEXT);
CREATE TABLE pg_class (
  oid INTEGER PRIMARY KEY, relname TEXT, relnamespace INTEGER,
  relkind TEXT, relnatts INTEGER);
CREATE TABLE pg_attribute (
  attrelid INTEGER, attname TEXT, atttypid INTEGER, attnum INTEGER,
  attnotnull INTEGER, attisdropped INTEGER DEFAULT 0);
CREATE TABLE pg_type (oid INTEGER PRIMARY KEY, typname TEXT);
CREATE TABLE pg_index (
  indexrelid INTEGER, indrelid INTEGER, indisprimary INTEGER,
  indkey TEXT);
CREATE TABLE pg_description (objoid INTEGER, description TEXT);
CREATE TABLE pg_database (
  oid INTEGER PRIMARY KEY, datname TEXT, datallowconn INTEGER DEFAULT 1);
CREATE TABLE pg_range (rngtypid INTEGER PRIMARY KEY, rngsubtype INTEGER);
-- information_schema (bare names: this db holds nothing else)
CREATE TABLE tables (
  table_catalog TEXT, table_schema TEXT, table_name TEXT,
  table_type TEXT);
CREATE TABLE columns (
  table_catalog TEXT, table_schema TEXT, table_name TEXT,
  column_name TEXT, ordinal_position INTEGER, data_type TEXT,
  is_nullable TEXT);
"""
    )
    cat.executemany("INSERT INTO pg_type VALUES (?, ?)", _PG_TYPE_ROWS)
    cat.execute("INSERT INTO pg_namespace VALUES (2200, 'public')")
    cat.execute("INSERT INTO pg_namespace VALUES (11, 'pg_catalog')")
    cat.execute("INSERT INTO pg_database VALUES (1, 'corrosion', 1)")
    rel_oid = 16384
    for t in sorted(agent.storage.tables):
        _, info = agent.storage.read_query(f'PRAGMA table_info("{t}")')
        cat.execute(
            "INSERT INTO pg_class VALUES (?, ?, 2200, 'r', ?)",
            (rel_oid, t, len(info)),
        )
        cat.execute(
            "INSERT INTO tables VALUES ('corrosion', 'public', ?, "
            "'BASE TABLE')", (t,),
        )
        pk_nums = []
        for cid, name, decl, notnull, _dflt, pk in info:
            oid = _decltype_oid(decl)
            cat.execute(
                "INSERT INTO pg_attribute VALUES (?, ?, ?, ?, ?, 0)",
                (rel_oid, name, oid, cid + 1, 1 if (notnull or pk) else 0),
            )
            cat.execute(
                "INSERT INTO columns VALUES ('corrosion', 'public', ?, ?, "
                "?, ?, ?)",
                (t, name, cid + 1, _pg_typename(oid),
                 "NO" if (notnull or pk) else "YES"),
            )
            if pk:
                pk_nums.append(str(cid + 1))
        if pk_nums:
            cat.execute(
                "INSERT INTO pg_index VALUES (?, ?, 1, ?)",
                (rel_oid + 1, rel_oid, " ".join(pk_nums)),
            )
        rel_oid += 2
    return cat


_SCHEMA_PREFIX_RE = re.compile(
    r"\b(?:pg_catalog|information_schema)\s*\.\s*", re.IGNORECASE
)

# catalog tables routed even when referenced unqualified — matched only
# in genuine table position (FROM/JOIN items) so a user column or alias
# merely *named* pg_class doesn't reroute the query
_CATALOG_TABLES = frozenset((
    "pg_database", "pg_class", "pg_namespace", "pg_attribute", "pg_type",
    "pg_index", "pg_description", "pg_range",
))
_JOIN_ITEM_RE = re.compile(r"\bjoin\s+(?:only\s+)?\"?(\w+)")
# a FROM clause runs to the keyword that can follow a from-list; commas
# inside it separate table refs (old-style joins)
_FROM_CLAUSE_RE = re.compile(
    r"\bfrom\s+(.*?)(?:\bwhere\b|\bgroup\s+by\b|\border\s+by\b|\bhaving\b"
    r"|\bwindow\b|\blimit\b|\bunion\b|\bexcept\b|\bintersect\b|$)",
    re.S,
)
_FROM_ITEM_RE = re.compile(r"^\(*\s*(?:only\s+)?\"?(\w+)")


def _strip_parens(sql: str) -> str:
    """Blank out parenthesized groups (subqueries, function args) so
    the from-clause scan below sees only top-level table refs — an
    inner subquery's WHERE must not terminate the outer from-list."""
    prev = None
    while prev != sql:
        prev = sql
        sql = re.sub(r"\([^()]*\)", " ", sql)
    return sql


def _unqualified_catalog_table(sql: str) -> Optional[str]:
    """First catalog table referenced in table position, or None.

    Scans with subqueries blanked, so a catalog ref *inside* a
    subquery's from-list is found by scanning each nesting level's
    stripped text via the recursion below.
    """
    for depth_text in _nesting_levels(sql):
        for m in _JOIN_ITEM_RE.finditer(depth_text):
            if m.group(1) in _CATALOG_TABLES:
                return m.group(1)
        for mf in _FROM_CLAUSE_RE.finditer(depth_text):
            for item in mf.group(1).split(","):
                mi = _FROM_ITEM_RE.match(item.strip())
                if mi and mi.group(1) in _CATALOG_TABLES:
                    return mi.group(1)
    return None


def _nesting_levels(sql: str, max_depth: int = 8):
    """The query text at each paren-nesting level, outermost first,
    each with its own inner groups blanked."""
    level = sql
    for _ in range(max_depth):
        yield _strip_parens(level)
        inners = re.findall(r"\(([^()]*(?:\([^()]*\)[^()]*)*)\)", level)
        if not inners:
            return
        level = " ; ".join(inners)

def _catalog_for(agent: "Agent"):
    """Cached rendered catalog (stored on the agent), invalidated by
    sqlite's schema_version counter (bumps on any DDL) — driver/ORM
    startup fires bursts of catalog queries and must not rebuild N
    tables' worth each time."""
    _, rows = agent.storage.read_query("PRAGMA schema_version")
    key = (rows[0][0], tuple(sorted(agent.storage.tables)))
    hit = getattr(agent, "_pg_catalog", None)
    if hit and hit[0] == key:
        return hit[1]
    cat = build_catalog(agent)
    # the stale connection is NOT closed here: another session's
    # off-loop catalog query may still be executing on it (sessions run
    # in worker threads since CancelRequest support); the in-memory db
    # is reclaimed when the last reference drops
    agent._pg_catalog = (key, cat)
    return cat


# guards lazy creation of an agent's catalog lock: a bare
# check-then-set would let two first-catalog-query sessions each
# install their OWN lock and both proceed onto the shared connection
_catalog_lock_init = threading.Lock()


def _agent_catalog_lock(agent: "Agent") -> threading.Lock:
    """The agent's catalog lock.  ``serve_pg`` installs it at server
    startup (single task, no race); this lazy path only serves direct
    callers (tests, tooling) and is made safe by the module-level
    init guard."""
    lock = getattr(agent, "_pg_catalog_lock", None)
    if lock is None:
        with _catalog_lock_init:
            lock = getattr(agent, "_pg_catalog_lock", None)
            if lock is None:
                lock = agent._pg_catalog_lock = threading.Lock()
    return lock


def _catalog_query(agent: "Agent", tsql: str, params,
                   on_conn=None) -> Tuple[list, list]:
    """Run one SELECT against the rendered catalog under the agent's
    catalog lock: sessions execute in worker threads, and one shared
    sqlite connection must not see concurrent cursors (sqlite3's
    serialized mode is a build option, not a guarantee).

    ``on_conn`` (called with the catalog connection while the query
    runs, then with None) makes catalog reads interruptible by a
    concurrent CancelRequest — the lock scope guarantees the tracked
    connection is running OUR statement, never another session's."""
    agent.metrics.counter("corro_pg_statements_total", kind="catalog")
    with _agent_catalog_lock(agent):
        conn = _catalog_for(agent)
        if on_conn is not None:
            on_conn(conn)
        try:
            cur = conn.execute(tsql, params)
            cols = [d[0] for d in cur.description or []]
            return cur.fetchall(), cols
        finally:
            if on_conn is not None:
                on_conn(None)


_GUC_DEFAULTS = {
    "server_version": "14.9",
    "server_encoding": "UTF8",
    "client_encoding": "UTF8",
    "datestyle": "ISO, MDY",
    "timezone": "UTC",
    "standard_conforming_strings": "on",
    "integer_datetimes": "on",
    "search_path": '"$user", public',
    "application_name": "",
    "transaction_isolation": "read committed",
    "statement_timeout": "0",
    "default_transaction_isolation": "read committed",
    "max_identifier_length": "63",
}


class _Session:
    def __init__(self, agent: "Agent"):
        self.agent = agent
        # name -> (raw, translated, declared param OIDs)
        self.stmts: Dict[str, Tuple[str, str, List[int]]] = {}
        # name -> {"stmt", "values", "described", "cached"}
        self.portals: Dict[str, dict] = {}
        self.in_txn = False
        self.txn_failed = False
        self.txn_writes: List[list] = []
        # savepoint stack: (name, buffered-write index at creation) —
        # ROLLBACK TO truncates the buffer back to the mark
        self.savepoints: List[Tuple[str, int]] = []
        # session GUCs (SET/SHOW/RESET); defaults overlay
        self.gucs: Dict[str, str] = {}
        # extended-protocol error recovery: after an error, further
        # Parse/Bind/Describe/Execute are discarded until Sync
        self.skip_until_sync = False
        # CancelRequest support: the read connection currently
        # executing this session's query, interruptible from any
        # thread.  The lock closes the return-to-pool race: read_query
        # clears the slot (under this lock) BEFORE the pooled reader is
        # handed to another session, so cancel() can never interrupt a
        # different session's query.
        self._active_conn = None
        self._cancel_lock = threading.Lock()
        self.backend_pid = 0
        self.backend_secret = 0
        # raw sql -> parsed AST node (None = outside the grammar);
        # bounded FIFO — prepare-once/execute-many must not re-parse
        self._ast_cache: Dict[str, object] = {}

    # -- cancellation ----------------------------------------------------

    def _track_conn(self, conn) -> None:
        with self._cancel_lock:
            self._active_conn = conn

    def cancel(self) -> None:
        """Interrupt the in-flight query, if any (CancelRequest:
        affects only the current statement; a cancel that lands
        between statements is a no-op, same as real PG's race)."""
        with self._cancel_lock:
            conn = self._active_conn
            if conn is not None:
                try:
                    conn.interrupt()
                except Exception:
                    pass

    # -- execution -------------------------------------------------------

    def execute(self, sql: str, params: Tuple = ()) -> Tuple[List[str], List[tuple], int, str]:
        """Returns (columns, rows, rowcount, tag)."""
        raw = sql.strip().rstrip(";")
        word = raw.split(None, 1)[0].upper() if raw else ""
        up_words = raw.upper().split()
        if word == "BEGIN" or word == "START":
            self.in_txn, self.txn_failed = True, False
            self.txn_writes, self.savepoints = [], []
            return [], [], 0, "BEGIN"
        if word == "COMMIT" or word == "END":
            writes, self.txn_writes = self.txn_writes, []
            self.in_txn, self.savepoints = False, []
            if self.txn_failed:
                self.txn_failed = False
                return [], [], 0, "ROLLBACK"
            if writes:
                # tracked: a CancelRequest landing mid-COMMIT interrupts
                # the buffered transaction's replay (57014).  Under
                # group commit the replay runs as one SAVEPOINT batch of
                # a combined group (docs/writes.md); an interrupt aborts
                # the group, the per-batch fallback replays the OTHER
                # sessions' batches, and this session still sees 57014
                self.agent.execute_transaction(
                    writes, on_conn=self._track_conn
                )
            return [], [], 0, "COMMIT"
        if word == "ROLLBACK" and "TO" in up_words[1:3]:
            # ROLLBACK [WORK] TO [SAVEPOINT] name: truncate the write
            # buffer to the mark and CLEAR the failed state (PG lets
            # the transaction continue past the savepoint)
            name = raw.split()[-1].lower()
            for i in range(len(self.savepoints) - 1, -1, -1):
                if self.savepoints[i][0] == name:
                    del self.txn_writes[self.savepoints[i][1]:]
                    del self.savepoints[i + 1:]
                    self.txn_failed = False
                    return [], [], 0, "ROLLBACK"
            raise PgError(
                SQLSTATE["invalid_savepoint_specification"],
                f'savepoint "{name}" does not exist',
            )
        if word == "ROLLBACK":
            self.in_txn, self.txn_failed = False, False
            self.txn_writes, self.savepoints = [], []
            return [], [], 0, "ROLLBACK"
        if self.txn_failed:
            # 25P02: everything except COMMIT/ROLLBACK is refused until
            # the failed transaction block ends (real PG behavior)
            raise PgError(
                SQLSTATE["in_failed_sql_transaction"],
                "current transaction is aborted, commands ignored "
                "until end of transaction block",
            )
        if word == "SAVEPOINT":
            if not self.in_txn:
                raise PgError(
                    SQLSTATE["no_active_sql_transaction"],
                    "SAVEPOINT can only be used in transaction blocks",
                )
            parts = raw.split()
            if len(parts) != 2:
                raise PgError(SQLSTATE["syntax_error"],
                              "syntax error in SAVEPOINT")
            self.savepoints.append((parts[1].lower(), len(self.txn_writes)))
            return [], [], 0, "SAVEPOINT"
        if word == "RELEASE":
            name = raw.split()[-1].lower()
            for i in range(len(self.savepoints) - 1, -1, -1):
                if self.savepoints[i][0] == name:
                    del self.savepoints[i:]
                    return [], [], 0, "RELEASE"
            raise PgError(
                SQLSTATE["invalid_savepoint_specification"],
                f'savepoint "{name}" does not exist',
            )
        if word in ("SET", "RESET", "SHOW"):
            return self._guc_statement(word, raw)
        if not raw:
            return [], [], 0, ""

        # AST-first: the recursive-descent parser (agent/pgparse.py)
        # grounds classification, catalog routing, RETURNING names and
        # command tags in grammar; statements outside its grammar fall
        # back to the token-pass pipeline below (counted).  Parsed
        # nodes are cached per session — prepare-once/execute-many is
        # the extended protocol's hot path
        if raw in self._ast_cache:
            node = self._ast_cache[raw]
        else:
            node = None
            try:
                node = pgparse.parse_statement(raw)
            except pgparse.Unsupported:
                self.agent.metrics.counter(
                    "corro_pg_parse_fallbacks_total"
                )
            if len(self._ast_cache) >= 256:
                self._ast_cache.pop(next(iter(self._ast_cache)))
            self._ast_cache[raw] = node
        if node is not None:
            try:
                return self._execute_ast(node, params)
            except pgparse.Unsupported:
                self.agent.metrics.counter(
                    "corro_pg_parse_fallbacks_total"
                )

        canned = self._canned(raw, params)
        if canned is not None:
            return canned

        tsql, order = translate_query(raw)
        # $N -> ? is positional in ? space: remap the bound values
        # into occurrence order (repeated/out-of-order $N refs)
        if order:
            params = self._remap(params, order)
        if _is_write(tsql):
            return self._run_write(
                tsql, params, lambda n: _tag_for(tsql, n, 0),
                _returning_columns(tsql, self.agent) is not None,
            )
        # the token-pass fallback READ path counts into the same
        # statement-mix metric as the AST pipeline (kind=read), so the
        # mix stays consistent whichever pipeline served the statement
        self.agent.metrics.counter(
            "corro_pg_statements_total", kind="read")
        # classify with leading parens stripped so a parenthesized
        # compound ("(SELECT ...) UNION ...") gets the same visibility
        # as its bare form; _is_write above already claimed CTE-led DML
        head = tsql.lstrip(" (").split(None, 1)
        is_select = bool(head) and head[0].upper() in (
            "SELECT", "WITH", "VALUES",
        )
        if is_select and self.in_txn and self.txn_writes:
            # read-your-writes inside BEGIN..COMMIT: evaluate against a
            # rolled-back sandbox that replays the buffered writes (the
            # ORM insert-then-select-by-pk shape).  Only genuine
            # queries take this path — a PRAGMA on the shared RW
            # connection would outlive the rollback
            cols, rows = self.agent.storage.speculative_read(
                self.txn_writes, tsql, params
            )
        else:
            # the tracked connection makes this read interruptible by a
            # concurrent CancelRequest ("interrupted" maps to 57014)
            cols, rows = self.agent.storage.read_query(
                tsql, params, on_conn=self._track_conn
            )
        return cols, rows, len(rows), _tag_for(tsql, -1, len(rows))

    def _remap(self, params: Tuple, order: List[int]) -> Tuple:
        if not order:
            return ()
        if max(order) > len(params):
            raise PgError(
                SQLSTATE["undefined_parameter"],
                f"there is no parameter ${max(order)}",
            )
        return tuple(params[i - 1] for i in order)

    def _execute_ast(self, node, params: Tuple):
        """Execute a parsed statement: routing, classification, tags
        and RETURNING names all come from the AST."""
        refs = pgparse.table_refs(node)
        # catalog routing: a qualified pg_catalog./information_schema.
        # ref always routes; an unqualified known catalog-table ref
        # routes unless shadowed by a user table of the same name
        user = self._user_tables()
        # (unqualified information_schema names deliberately do NOT
        # route: unlike pg_catalog, that schema is not on PG's default
        # search_path, so bare "columns" must stay a user-table ref)
        route_catalog = any(
            q.schema in ("pg_catalog", "information_schema")
            or (
                q.schema is None
                and q.base in _CATALOG_TABLES
                and q.base not in user
            )
            for q in refs
        )
        is_write = isinstance(
            node, (pgparse.Insert, pgparse.Update, pgparse.Delete)
        )
        if route_catalog:
            if is_write:
                if node.table.base in _CATALOG_TABLES or node.table.schema \
                        in ("pg_catalog", "information_schema"):
                    raise PgError(
                        SQLSTATE["insufficient_privilege"],
                        "catalog tables are read-only",
                    )
                # a user-table write whose SOURCE reads the catalog:
                # the catalog lives in a separate rendered db, so the
                # two cannot join in one statement
                raise PgError(
                    SQLSTATE["feature_not_supported"],
                    "mixing catalog reads into a write statement is "
                    "not supported",
                )
            tsql, order = pgparse.emit(
                node,
                strip_schemas=(
                    "public", "pg_catalog", "information_schema"
                ),
            )
            rows, cols = _catalog_query(
                self.agent, tsql, self._remap(params, order),
                on_conn=self._track_conn,
            )
            return cols, rows, len(rows), f"SELECT {len(rows)}"

        tsql, order = pgparse.emit(node)
        bound = self._remap(params, order)
        if is_write:
            tag_head = type(node).__name__.upper()
            return self._run_write(
                tsql, bound,
                lambda n: (f"INSERT 0 {n}" if tag_head == "INSERT"
                           else f"{tag_head} {n}"),
                node.returning is not None,
            )
        # Select / VALUES
        self.agent.metrics.counter(
            "corro_pg_statements_total", kind="read")
        if self.in_txn and self.txn_writes:
            cols, rows = self.agent.storage.speculative_read(
                self.txn_writes, tsql, bound
            )
        else:
            cols, rows = self.agent.storage.read_query(
                tsql, bound, on_conn=self._track_conn
            )
        return cols, rows, len(rows), f"SELECT {len(rows)}"

    def _run_write(self, tsql: str, bound, tag, has_returning: bool):
        """The shared write path for BOTH pipelines (AST + fallback):
        buffered inside BEGIN, versioned execute_transaction outside;
        ``tag`` maps the affected-row count to the command tag."""
        self.agent.metrics.counter(
            "corro_pg_statements_total", kind="write")
        stmt = [tsql, list(bound)] if bound else [tsql]
        if self.in_txn:
            if has_returning:
                # writes inside BEGIN are buffered until COMMIT, so
                # RETURNING rows don't exist yet — failing fast beats
                # silently returning none (ORMs would read a missing
                # primary key)
                raise PgError(
                    SQLSTATE["feature_not_supported"],
                    "RETURNING inside an explicit transaction is "
                    "not supported (writes are buffered until "
                    "COMMIT); run the statement in autocommit",
                )
            self.txn_writes.append(stmt)
            # rowcount unknown until commit; report optimistically
            return [], [], 1, tag(1)
        # tracked while the storage lock is held: a concurrent
        # CancelRequest interrupts the in-flight WRITE too (57014),
        # not just pooled reads
        out = self.agent.execute_transaction(
            [stmt], on_conn=self._track_conn
        )
        res = out["results"][0]
        rc = res.get("rows_affected", 0)
        if "rows" in res:
            # INSERT/UPDATE/DELETE ... RETURNING (the ORM write
            # shape): the versioned write path surfaces the rows
            cols, rows = res["columns"], res["rows"]
            return cols, rows, rc, tag(max(rc, len(rows)))
        return [], [], rc, tag(rc)

    def _guc_statement(self, word: str, raw: str):
        """SET / RESET / SHOW against the session's GUC store (real
        session state, not a canned reply: SET is visible to later
        SHOWs, RESET restores the default, SHOW ALL lists)."""
        self.agent.metrics.counter(
            "corro_pg_statements_total", kind="utility")
        body = raw.split(None, 1)[1].strip() if " " in raw else ""
        if word == "SET":
            # scope prefixes first, so SET LOCAL TIME ZONE etc. parse
            body = re.sub(r"^(?:SESSION|LOCAL)\s+", "", body,
                          flags=re.IGNORECASE)
            up = body.upper()
            # transaction-characteristics / role forms drivers and
            # poolers send at setup: accepted as no-ops — the storage
            # is single-writer READ COMMITTED with one implicit role
            if up.startswith((
                "TRANSACTION", "CHARACTERISTICS AS", "CONSTRAINTS",
                "ROLE", "AUTHORIZATION",
            )):
                return [], [], 0, "SET"
            m3 = re.match(r"NAMES\s+(.+)$", body, flags=re.IGNORECASE)
            if m3:
                self.gucs["client_encoding"] = m3.group(1).strip().strip("'")
                return [], [], 0, "SET"
            m2 = re.match(r"TIME\s+ZONE\s+(.+)$", body, flags=re.IGNORECASE)
            if m2:
                self.gucs["timezone"] = m2.group(1).strip().strip("'")
                return [], [], 0, "SET"
            # SET name {TO|=} value
            m = re.match(
                r"([A-Za-z_][\w.]*)\s*(?:=|\bTO\b)\s*(.+)$",
                body, flags=re.IGNORECASE | re.DOTALL,
            )
            if not m:
                raise PgError(SQLSTATE["syntax_error"],
                              f"syntax error in SET: {raw!r}")
            name = m.group(1).lower()
            val = m.group(2).strip()
            if val.upper() == "DEFAULT":
                self.gucs.pop(name, None)
            elif name == "search_path":
                # the one comma-LIST parameter clients actually SET:
                # normalize item spacing and quoting per element
                self.gucs[name] = ", ".join(
                    p.strip().strip("'") for p in val.split(",")
                )
            else:
                # scalar: strip one level of quoting whole, so a value
                # containing commas ('svc,primary') survives verbatim
                if len(val) >= 2 and val[0] == val[-1] == "'":
                    val = val[1:-1].replace("''", "'")
                self.gucs[name] = val
            return [], [], 0, "SET"
        if word == "RESET":
            if body.upper() == "ALL":
                self.gucs.clear()
            else:
                self.gucs.pop(body.lower(), None)
            return [], [], 0, "RESET"
        # SHOW
        name = body.lower()
        if name == "time zone":
            name = "timezone"
        if name == "all":
            rows = sorted(
                {**_GUC_DEFAULTS, **self.gucs}.items()
            )
            return (
                ["name", "setting", "description"],
                [(k, v, "") for k, v in rows],
                len(rows),
                f"SELECT {len(rows)}",
            )
        if name in ("transaction isolation level",):
            name = "transaction_isolation"
        val = self.gucs.get(name, _GUC_DEFAULTS.get(name))
        if val is None:
            raise PgError(
                SQLSTATE["undefined_object"],
                f'unrecognized configuration parameter "{name}"',
            )
        return [name], [(val,)], 1, "SELECT 1"

    def _user_tables(self) -> set:
        return {t.lower() for t in self.agent.storage.tables}

    def _canned(self, raw: str, params: Tuple = ()):
        """Catalog routing for the token-pass FALLBACK pipeline only —
        statements the recursive-descent parser handles never get here
        (their routing is AST-based in ``_execute_ast``).  The old
        SET/SHOW regex probes are gone (real GUC statements now); this
        residue keeps catalog queries working for shapes outside the
        grammar."""
        low = " ".join(raw.lower().split())
        # version()/current_database()/current_schema() are real SQL
        # functions (storage.register_udfs), so they work in any
        # expression context through the normal execution path
        # unqualified catalog routing must not fire on string literals
        # ("... WHERE note LIKE '%pg_class%'") and only reroutes reads
        no_literals = re.sub(r"'[^']*'", "''", low)
        unqualified = (
            no_literals.lstrip().startswith("select")
            and (name := _unqualified_catalog_table(no_literals)) is not None
            # a user table legitimately named e.g. pg_class wins over
            # unqualified catalog routing (qualified pg_catalog.* still
            # routes below)
            and name not in self._user_tables()
        )
        if (
            "pg_catalog" in no_literals
            or "information_schema" in no_literals
            or unqualified
        ):
            # run real catalog SQL against the rendered catalog —
            # including unqualified references: pg_catalog is always on
            # a real server's search_path, so drivers routinely write
            # bare "FROM pg_database"
            t, order = translate_query(raw)
            tsql = _SCHEMA_PREFIX_RE.sub("", t)
            if order:
                params = self._remap(params, order)
            rows, cols = _catalog_query(
                self.agent, tsql, params, on_conn=self._track_conn
            )
            return cols, rows, len(rows), f"SELECT {len(rows)}"
        return None


async def serve_pg(agent: "Agent", host: str = "127.0.0.1", port: int = 0):
    """Start the pgwire listener; returns the asyncio server.

    Live session writers are tracked on ``server.corro_conns`` so
    shutdown can abort them: ``Server.wait_closed()`` waits for every
    handler to return, and an idle client would otherwise hold
    ``Agent.stop()`` open indefinitely."""
    # the catalog lock exists BEFORE any session thread can race to
    # create it (sessions run catalog queries in worker threads)
    _agent_catalog_lock(agent)
    conns: set = set()

    async def handler(r, w):
        conns.add(w)
        try:
            await _handle_conn(agent, r, w)
        finally:
            conns.discard(w)

    server = await asyncio.start_server(handler, host, port)
    server.corro_conns = conns
    return server


def _pg_ssl_context(agent: "Agent"):
    """Per-agent cached server SSLContext (cert/key are read from disk
    once, not per connection)."""
    cfg = agent.config
    if not (cfg.tls_cert_file and cfg.tls_key_file):
        return None
    ctx = getattr(agent, "_pg_ssl_ctx", None)
    if ctx is None:
        from corrosion_tpu.agent.tls import server_context

        # client-cert verification is PG's own knob (corro-pg
        # verify_client) — gossip mTLS must not lock SQL clients out
        ctx = server_context(
            cfg.tls_cert_file, cfg.tls_key_file,
            ca_file=cfg.tls_ca_file,
            require_client=cfg.pg_tls_verify_client,
        )
        agent._pg_ssl_ctx = ctx
    return ctx


_pg_pid_counter = itertools.count(1)


def _cancel_registry(agent: "Agent") -> Dict[Tuple[int, int], "_Session"]:
    reg = getattr(agent, "_pg_cancel_registry", None)
    if reg is None:
        reg = agent._pg_cancel_registry = {}
    return reg


async def _handle_conn(agent: "Agent", reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
    session = _Session(agent)
    agent.metrics.counter("corro_pg_connections_total")
    cancel_key = None
    try:
        # --- startup ----------------------------------------------------
        while True:
            head = await reader.readexactly(4)
            (length,) = struct.unpack(">I", head)
            body = await reader.readexactly(length - 4)
            (proto,) = struct.unpack_from(">I", body, 0)
            if proto == SSL_REQUEST:
                ctx = _pg_ssl_context(agent)
                if ctx is not None:
                    # corro-pg TLS parity: accept and upgrade in place
                    # (the agent's cert/key also serve the PG listener)
                    writer.write(b"S")
                    await writer.drain()
                    await writer.start_tls(ctx)
                else:
                    writer.write(b"N")  # no TLS configured
                    await writer.drain()
                continue
            if proto == CANCEL_REQUEST:
                # cancel-key connection (lib.rs:667-747 parity): look
                # up the (pid, secret) pair and interrupt that
                # session's in-flight query; never answer
                pid, secret = struct.unpack_from(">II", body, 4)
                target = _cancel_registry(agent).get((pid, secret))
                if target is not None:
                    target.cancel()
                    agent.metrics.counter("corro_pg_cancels_total")
                return
            if proto != PROTO_V3:
                _error(writer, SQLSTATE["protocol_violation"],
                       f"unsupported protocol {proto}")
                return
            break
        writer.write(_msg(b"R", struct.pack(">I", 0)))  # AuthenticationOk
        # ParameterStatus values come from the ONE GUC table SHOW reads
        for k, key in (
            ("server_version", "server_version"),
            ("server_encoding", "server_encoding"),
            ("client_encoding", "client_encoding"),
            ("DateStyle", "datestyle"),
            ("standard_conforming_strings", "standard_conforming_strings"),
            ("integer_datetimes", "integer_datetimes"),
            ("TimeZone", "timezone"),
        ):
            writer.write(_msg(b"S", _cstr(k) + _cstr(_GUC_DEFAULTS[key])))
        # a REAL cancellation key: a later CancelRequest bearing it
        # interrupts this session's running query
        session.backend_pid = next(_pg_pid_counter)
        session.backend_secret = secrets.randbits(31)
        cancel_key = (session.backend_pid, session.backend_secret)
        _cancel_registry(agent)[cancel_key] = session
        writer.write(_msg(b"K", struct.pack(">II", *cancel_key)))
        _ready(writer, session)
        await writer.drain()

        # --- message loop -----------------------------------------------
        while True:
            tag = await reader.readexactly(1)
            (length,) = struct.unpack(">I", await reader.readexactly(4))
            body = await reader.readexactly(length - 4)
            if tag == b"X":
                return
            if session.skip_until_sync and tag in (b"P", b"B", b"D",
                                                   b"E", b"C", b"H"):
                continue  # discard until Sync (extended-protocol rule)
            if tag == b"Q":
                session.skip_until_sync = False
                await _simple_query(writer, session, _Buffer(body).string())
            elif tag == b"P":
                b = _Buffer(body)
                name, query = b.string(), b.string()
                n_oids = b.int16()
                oids = [b.int32() for _ in range(n_oids)]
                session.stmts[name] = (query, translate_sql(query), oids)
                writer.write(_msg(b"1"))
            elif tag == b"B":
                _bind(writer, session, _Buffer(body))
            elif tag == b"D":
                await _describe(writer, session, _Buffer(body))
            elif tag == b"E":
                await _execute_portal(writer, session, _Buffer(body))
            elif tag == b"C":
                b = _Buffer(body)
                kind, name = b.read(1), b.string()
                (session.stmts if kind == b"S" else session.portals).pop(name, None)
                writer.write(_msg(b"3"))
            elif tag == b"S":
                session.skip_until_sync = False
                _ready(writer, session)
            elif tag == b"H":
                pass  # flush: we always flush below
            else:
                _error(writer, "08P01", f"unsupported message {tag!r}")
                _ready(writer, session)
            await writer.drain()
    except (asyncio.IncompleteReadError, ConnectionError):
        return
    finally:
        if cancel_key is not None:
            _cancel_registry(agent).pop(cancel_key, None)
        writer.close()


def _ready(writer, session: _Session) -> None:
    status = b"E" if session.txn_failed else (b"T" if session.in_txn else b"I")
    writer.write(_msg(b"Z", status))


def _error(writer, code: str, message: str) -> None:
    payload = (
        b"S" + _cstr("ERROR") + b"C" + _cstr(code) + b"M" + _cstr(message) + b"\x00"
    )
    writer.write(_msg(b"E", payload))


def _ext_error(writer, session: _Session, code: str, message: str) -> None:
    """ErrorResponse inside the extended protocol: subsequent messages
    are discarded until the client's Sync."""
    session.skip_until_sync = True
    _error(writer, code, message)


def _row_description(writer, cols: List[str],
                     oids: Optional[List[int]] = None) -> None:
    payload = struct.pack(">h", len(cols))
    for i, c in enumerate(cols):
        oid = oids[i] if oids else TEXT_OID
        payload += _cstr(c) + struct.pack(">IhIhih", 0, 0, oid, -1, -1, 0)
    writer.write(_msg(b"T", payload))


def _result_oids(rows: List[tuple], ncols: int) -> List[int]:
    return [_infer_oid(r[i] for r in rows) for i in range(ncols)]


def _data_rows(writer, rows: List[tuple]) -> None:
    for row in rows:
        payload = struct.pack(">h", len(row))
        for v in row:
            if v is None:
                payload += struct.pack(">i", -1)
            else:
                if isinstance(v, bool):
                    s = b"t" if v else b"f"
                elif isinstance(v, (bytes, bytearray, memoryview)):
                    s = b"\\x" + bytes(v).hex().encode()
                else:
                    s = str(v).encode()
                payload += struct.pack(">i", len(s)) + s
        writer.write(_msg(b"D", payload))


async def _simple_query(writer, session: _Session, query: str) -> None:
    parts = [p for p in _split_statements(query) if p.strip()]
    if not parts:
        writer.write(_msg(b"I"))  # EmptyQueryResponse
        _ready(writer, session)
        return
    for part in parts:
        try:
            # off-loop so a concurrent CancelRequest (its own
            # connection, same event loop) can interrupt this query
            cols, rows, rc, tag = await asyncio.to_thread(
                session.execute, part
            )
        except Exception as e:
            if session.in_txn:
                session.txn_failed = True
            _error(writer, sqlstate_for(e), str(e))
            break
        if cols:
            _row_description(writer, cols, _result_oids(rows, len(cols)))
            _data_rows(writer, rows)
        writer.write(_msg(b"C", _cstr(tag)))
    _ready(writer, session)


def _bind(writer, session: _Session, b: _Buffer) -> None:
    portal, stmt = b.string(), b.string()
    nfmt = b.int16()
    fmts = [b.int16() for _ in range(nfmt)]
    nparams = b.int16()
    raw_params: List[Optional[bytes]] = []
    for i in range(nparams):
        ln = b.int32()
        raw_params.append(None if ln == -1 else b.read(ln))
    nrfmt = b.int16()
    rfmts = [b.int16() for _ in range(nrfmt)]
    if stmt not in session.stmts:
        _ext_error(writer, session, "26000",
                   f"unknown prepared statement {stmt!r}")
        return
    if any(f == 1 for f in rfmts):
        _ext_error(writer, session, "0A000",
                   "binary result format not supported")
        return
    oids = session.stmts[stmt][2]
    values: List = []
    for i, data in enumerate(raw_params):
        # per-protocol: 0 fmts = all text, 1 fmt = applies to all
        fmt = fmts[i] if len(fmts) == nparams else (fmts[0] if fmts else 0)
        oid = oids[i] if i < len(oids) else 0
        if data is None:
            values.append(None)
            continue
        try:
            values.append(_decode_param(data, oid, fmt))
        except (ValueError, struct.error) as e:
            # the stale portal must not survive a failed Bind: a
            # pipelined Execute would silently re-run the old statement
            session.portals.pop(portal, None)
            _ext_error(writer, session, "22P02", f"parameter ${i + 1}: {e}")
            return
    session.portals[portal] = {
        "stmt": stmt, "values": values, "described": False, "cached": None,
    }
    writer.write(_msg(b"2"))


async def _describe(writer, session: _Session, b: _Buffer) -> None:
    kind, name = b.read(1), b.string()
    if kind == b"S":
        if name not in session.stmts:
            _ext_error(writer, session, "26000",
                       f"unknown prepared statement {name!r}")
            return
        raw, tsql, oids = session.stmts[name]
        # real placeholder count (translate_query is literal-aware;
        # counting '?' would also count ones inside strings)
        order = translate_query(raw)[1]
        nparams = len(set(order))
        payload = struct.pack(">h", nparams)
        for i in range(nparams):
            payload += struct.pack(
                ">I", oids[i] if i < len(oids) and oids[i] else TEXT_OID
            )
        writer.write(_msg(b"t", payload))
        # probe result columns without executing: NULL-bound LIMIT 0
        if tsql and not _is_write(tsql) and "pg_catalog" not in tsql.lower():
            try:
                cols, _rows = session.agent.storage.read_query(
                    f"SELECT * FROM ({tsql.rstrip(';')}) LIMIT 0",
                    [None] * len(order),
                )
                if cols:
                    _row_description(writer, cols, [TEXT_OID] * len(cols))
                    return
            except Exception:
                pass
        if tsql and _is_write(tsql):
            ret_cols = (
                _ast_returning_columns(raw, session.agent)
                or _returning_columns(tsql, session.agent)
            )
            if ret_cols:
                _row_description(
                    writer, ret_cols, [TEXT_OID] * len(ret_cols)
                )
                return
        writer.write(_msg(b"n"))
        return
    # Describe(portal): params are bound, so the query can run NOW —
    # the RowDescription carries the real inferred OIDs and Execute
    # replays the cached result instead of emitting a second (protocol-
    # violating) RowDescription.
    entry = session.portals.get(name)
    if entry is None or entry["stmt"] not in session.stmts:
        _ext_error(writer, session, "34000", f"unknown portal {name!r}")
        return
    if entry.get("pending") is not None:
        # describing a SUSPENDED portal must not re-execute (that
        # would emit a RowDescription mid-result-set and strand a
        # duplicate cached copy); answer from the in-flight result
        cols = entry["pending"][0]
        if cols:
            _row_description(writer, cols, _result_oids(
                entry["pending"][1], len(cols)))
        else:
            writer.write(_msg(b"n"))
        return
    raw = session.stmts[entry["stmt"]][0]
    tsql_w = translate_sql(raw)
    if _is_write(tsql_w):
        # a RETURNING write's row shape is derivable from the clause
        # without executing — drivers decide their fetch path from
        # this Describe answer, so it must be RowDescription, not
        # NoData (real PG behaves the same); grammar-derived names
        # first, token heuristic for out-of-grammar statements
        ret_cols = (
            _ast_returning_columns(raw, session.agent)
            or _returning_columns(tsql_w, session.agent)
        )
        if ret_cols:
            _row_description(writer, ret_cols, [TEXT_OID] * len(ret_cols))
            entry["described"] = True
        else:
            writer.write(_msg(b"n"))
        return
    try:
        cols, rows, rc, tag = await asyncio.to_thread(
            session.execute, raw, tuple(entry["values"])
        )
    except Exception as e:
        if session.in_txn:
            session.txn_failed = True
        _ext_error(writer, session, sqlstate_for(e), str(e))
        return
    entry["described"] = True
    entry["cached"] = (cols, rows, rc, tag)
    if cols:
        _row_description(writer, cols, _result_oids(rows, len(cols)))
    else:
        writer.write(_msg(b"n"))


async def _execute_portal(writer, session: _Session, b: _Buffer) -> None:
    portal = b.string()
    max_rows = b.int32()  # 0 = no limit
    entry = session.portals.get(portal)
    if entry is None or entry["stmt"] not in session.stmts:
        _ext_error(writer, session, "34000", f"unknown portal {portal!r}")
        return
    if entry.get("pending") is not None:
        # resuming a suspended portal: continue the SAME result set,
        # no new RowDescription (corro-pg portal max-row suspension)
        cols, rows, rc, tag = entry["pending"]
        entry["pending"] = None
    elif entry["cached"] is not None:
        cols, rows, rc, tag = entry["cached"]
        entry["cached"] = None
    else:
        raw = session.stmts[entry["stmt"]][0]
        try:
            cols, rows, rc, tag = await asyncio.to_thread(
                session.execute, raw, tuple(entry["values"])
            )
        except Exception as e:
            if session.in_txn:
                session.txn_failed = True
            _ext_error(writer, session, sqlstate_for(e), str(e))
            return
    if cols:
        if not entry["described"]:
            _row_description(writer, cols, _result_oids(rows, len(cols)))
            entry["described"] = True  # once per portal result set
        if max_rows > 0 and len(rows) > max_rows:
            _data_rows(writer, rows[:max_rows])
            entry["pending"] = (cols, rows[max_rows:], rc, tag)
            writer.write(_msg(b"s"))  # PortalSuspended
            return
        _data_rows(writer, rows)
    writer.write(_msg(b"C", _cstr(tag)))


