"""TLS for the gossip/sync streams + certificate tooling.

Parity: the reference runs all gossip over QUIC with rustls — server
certs, optional mTLS client-cert auth, and a ``corrosion tls`` CLI that
generates a CA and signs server/client certs
(``crates/corrosion/src/main.rs:707-760``, ``api/peer.rs:128-318``
gossip_server_endpoint/client config).

Ours wraps the existing TCP uni/bi streams in ``ssl.SSLContext``
(python's rustls): when ``tls_cert_file`` is set the agent's gossip TCP
listener serves TLS, outbound stream connects use TLS, and
``tls_client_required`` enforces mutual auth.  SWIM datagrams stay
plaintext UDP (no DTLS in the stdlib) — they carry membership liveness,
not data; the reference's equivalent protection comes from QUIC which we
deliberately do not reimplement.  Plaintext remains the default.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
from typing import List, Optional, Tuple


# -- certificate generation (corrosion tls ... generate parity) --------


def _write_pair(dir_path: str, stem: str, cert_pem: bytes,
                key_pem: bytes) -> Tuple[str, str]:
    os.makedirs(dir_path, exist_ok=True)
    cert_path = os.path.join(dir_path, f"{stem}.crt")
    key_path = os.path.join(dir_path, f"{stem}.key")
    with open(cert_path, "wb") as f:
        f.write(cert_pem)
    with open(key_path, "wb") as f:
        f.write(key_pem)
    os.chmod(key_path, 0o600)
    return cert_path, key_path


def _new_key():
    from cryptography.hazmat.primitives.asymmetric import ec

    return ec.generate_private_key(ec.SECP256R1())


def _key_pem(key) -> bytes:
    from cryptography.hazmat.primitives import serialization

    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def _name(common: str):
    from cryptography import x509
    from cryptography.x509.oid import NameOID

    return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common)])


def _build(subject, issuer, pub, signer, days: int, *, ca: bool,
           sans: Optional[List[str]] = None, client: bool = False):
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes

    now = datetime.datetime.now(datetime.timezone.utc)
    b = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(issuer)
        .public_key(pub)
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.BasicConstraints(ca=ca, path_length=None),
                       critical=True)
    )
    if sans:
        alt = []
        for s in sans:
            try:
                alt.append(x509.IPAddress(ipaddress.ip_address(s)))
            except ValueError:
                alt.append(x509.DNSName(s))
        b = b.add_extension(x509.SubjectAlternativeName(alt), critical=False)
    if not ca:
        from cryptography.x509.oid import ExtendedKeyUsageOID

        # server certs carry BOTH usages: in a gossip mesh every node is
        # simultaneously server and mTLS client on its peers' listeners
        usages = ([ExtendedKeyUsageOID.CLIENT_AUTH] if client else
                  [ExtendedKeyUsageOID.SERVER_AUTH,
                   ExtendedKeyUsageOID.CLIENT_AUTH])
        b = b.add_extension(x509.ExtendedKeyUsage(usages), critical=False)
    return b.sign(signer, hashes.SHA256())


def generate_ca(dir_path: str, days: int = 3650) -> Tuple[str, str]:
    """``corrosion tls ca generate``: self-signed CA key + cert."""
    from cryptography.hazmat.primitives import serialization

    key = _new_key()
    name = _name("corrosion-tpu CA")
    cert = _build(name, name, key.public_key(), key, days, ca=True)
    return _write_pair(
        dir_path, "ca",
        cert.public_bytes(serialization.Encoding.PEM), _key_pem(key),
    )


def _load_ca(ca_cert: str, ca_key: str):
    from cryptography import x509
    from cryptography.hazmat.primitives import serialization

    with open(ca_cert, "rb") as f:
        cert = x509.load_pem_x509_certificate(f.read())
    with open(ca_key, "rb") as f:
        key = serialization.load_pem_private_key(f.read(), password=None)
    return cert, key


def generate_server_cert(dir_path: str, ca_cert: str, ca_key: str,
                         sans: List[str], days: int = 365) -> Tuple[str, str]:
    """``corrosion tls server generate``: CA-signed cert for the gossip
    addresses in ``sans`` (IPs or DNS names)."""
    from cryptography.hazmat.primitives import serialization

    ca, cakey = _load_ca(ca_cert, ca_key)
    key = _new_key()
    cert = _build(
        _name(sans[0] if sans else "corrosion-tpu server"),
        ca.subject, key.public_key(), cakey, days, ca=False, sans=sans,
    )
    return _write_pair(
        dir_path, "server",
        cert.public_bytes(serialization.Encoding.PEM), _key_pem(key),
    )


def generate_client_cert(dir_path: str, ca_cert: str, ca_key: str,
                         common_name: str = "corrosion-tpu client",
                         days: int = 365) -> Tuple[str, str]:
    """``corrosion tls client generate``: CA-signed client-auth cert."""
    from cryptography.hazmat.primitives import serialization

    ca, cakey = _load_ca(ca_cert, ca_key)
    key = _new_key()
    cert = _build(
        _name(common_name), ca.subject, key.public_key(), cakey, days,
        ca=False, client=True,
    )
    return _write_pair(
        dir_path, "client",
        cert.public_bytes(serialization.Encoding.PEM), _key_pem(key),
    )


# -- ssl contexts ------------------------------------------------------


def server_context(cert_file: str, key_file: str,
                   ca_file: Optional[str] = None,
                   require_client: bool = False) -> ssl.SSLContext:
    """Gossip-listener context; with ``require_client`` peers must
    present a cert signed by ``ca_file`` (mTLS)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_3
    ctx.load_cert_chain(cert_file, key_file)
    if require_client:
        if not ca_file:
            raise ValueError("tls_client_required needs tls_ca_file")
        ctx.load_verify_locations(ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_context(ca_file: Optional[str] = None,
                   cert_file: Optional[str] = None,
                   key_file: Optional[str] = None,
                   insecure: bool = False) -> ssl.SSLContext:
    """Outbound-stream context.  ``insecure`` skips server verification
    (the reference's ``insecure = true`` knob); gossip peers are
    addressed by IP, so hostname checking is off and trust comes from
    the CA signature alone, like the reference's SkipServerVerification/
    CA-only rustls verifier."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_3
    ctx.check_hostname = False
    if insecure:
        ctx.verify_mode = ssl.CERT_NONE
    else:
        if not ca_file:
            # never silently skip verification: an operator who wants
            # unauthenticated TLS must say insecure explicitly
            raise ValueError("TLS without tls_ca_file requires "
                             "tls_insecure = true")
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(ca_file)
    if cert_file:
        ctx.load_cert_chain(cert_file, key_file)
    return ctx


def contexts_from_config(cfg) -> Tuple[Optional[ssl.SSLContext],
                                       Optional[ssl.SSLContext]]:
    """(server_ctx, client_ctx) from AgentConfig tls_* fields; (None,
    None) when TLS is off."""
    if not cfg.tls_cert_file:
        return None, None
    srv = server_context(
        cfg.tls_cert_file, cfg.tls_key_file, cfg.tls_ca_file,
        require_client=cfg.tls_client_required,
    )
    # the client cert/key must be chosen as a PAIR: mixing a dedicated
    # client cert with the server's key fails load_cert_chain
    if cfg.tls_client_cert_file:
        cli_cert, cli_key = cfg.tls_client_cert_file, cfg.tls_client_key_file
    elif cfg.tls_client_required:
        cli_cert, cli_key = cfg.tls_cert_file, cfg.tls_key_file
    else:
        cli_cert = cli_key = None
    cli = client_context(
        cfg.tls_ca_file, cli_cert, cli_key, insecure=cfg.tls_insecure,
    )
    return srv, cli
