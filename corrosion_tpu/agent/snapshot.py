"""Snapshot bootstrap: build, stage, crash-safe install.

A fresh (or wiped, or long-dead) node catching up change-by-change pays
O(history) serve work on every peer it syncs from — at production scale
a restart storm turns bootstrap into a cluster-wide serve stampede.
This module is the data half of the snapshot path (docs/sync.md,
"Snapshot serve + install"):

* **build** — a consistent ``VACUUM INTO`` copy of a live database
  (safe against concurrent writers under WAL: the vacuum runs inside
  one read transaction), scrubbed of node-local state by the shared
  :data:`SNAP_SCRUB` registry — the SAME decision set ``backup.py``
  uses, so a bookkeeping table added later cannot silently leak into
  snapshots (the registry-coverage regression test fails instead);
* **stage** — the receiving client writes the snapshot stream into a
  sidecar file next to its database, with a journal marker recording
  the expected whole-snapshot digest, so a crash at ANY point boots
  into a clean retry rather than a torn database;
* **install** — after the content digest verifies, the staged file is
  rewritten in place to carry the INSTALLING node's identity (the
  ``backup.restore`` site-ordinal rewrite), then atomically swapped in
  with ``os.replace`` under the storage lock.  The marker protocol
  makes every crash window recoverable:

  ======================  =========================================
  crash window            boot recovery (:func:`recover_pending_install`)
  ======================  =========================================
  mid-stream / pre-swap   discard sidecar + marker, retry from scratch
  marker written, staged  discard sidecar + marker (old DB intact),
  still present           retry from scratch
  after ``os.replace``    the DB *is* the fully-prepared snapshot:
  (staged gone)           drop the marker, resume normal boot + tail
  ======================  =========================================

The dispatch rule (which peer/need combination goes snapshot instead
of change-by-change) is the pure-function pair
:func:`covered_below_floor` / :func:`client_behind` — a client
requests a snapshot exactly when the server's advertised per-actor
snapshot floors cover needs the server can no longer serve as changes
(its below-floor bookkeeping is compacted) and the client is strictly
behind the server on every actor it tracks (so the install cannot
lose local-only writes).
"""

from __future__ import annotations

import hashlib
import json
import os
import sqlite3
from typing import Dict, Optional

#: node-local tables a snapshot (and a backup) must NOT carry: the
#: receiving node has its own membership view, its own compaction work
#: list, and its own bounded digest cache (the digests are a
#: node-local detection FIFO — reloading another node's window would
#: evict the receiver's own evidence).
SNAP_SCRUB = frozenset({
    "__corro_members",
    "__corro_versions_impacted",
    "__corro_equiv_digests",
    # write-behind flush journal (device-resident apply): donor-local
    # crash bookkeeping — the donor drains before building a snapshot,
    # and a receiver must never replay another node's flush intents
    "__corro_flush_journal",
})

#: portable cluster state a snapshot MUST carry: the data's version
#: cursor (``__corro_state``, minus the node-local ``incarnation`` key
#: — see :func:`scrub_snapshot`), site directory, CRR registry, the
#: whole bookkeeping plane (versions, cleared ranges, partial buffers,
#: gaps, cleared watermarks, snapshot floors), signed equivocation
#: proofs (cryptographic evidence is valid on any node —
#: docs/faults.md, signed attribution), and the pending as_crr
#: backfill queue: its table rows travel in the copy but are still
#: UNVERSIONED, so without the queue entry the receiver's boot-time
#: ``_register_backfills`` would never version them and they would
#: silently drop out of replication.
SNAP_KEEP = frozenset({
    "__corro_state",
    "__corro_sites",
    "__corro_crr_tables",
    "__corro_bookkeeping",
    "__corro_seq_bookkeeping",
    "__corro_buffered_changes",
    "__corro_bookkeeping_gaps",
    "__corro_sync_state",
    "__corro_equiv_proofs",
    "__corro_snap_floors",
    "__corro_backfills",
})

#: per-CRR-table bookkeeping suffixes (clock + causal-length tables):
#: these ARE the replicated state — always kept.
SNAP_KEEP_SUFFIXES = ("__corro_clock", "__corro_cl")

#: prefix-classified node-local families (consul session cache).
SNAP_SCRUB_PREFIXES = ("__corro_consul_",)

#: node-local keys inside kept tables: scrubbed even though the table
#: itself is portable.
SNAP_SCRUB_STATE_KEYS = ("incarnation",)

DIGEST_LEN = 32
_CHUNK = 1 << 20


class SnapshotError(Exception):
    """A snapshot build/stage/install step failed."""


class SnapshotCrash(Exception):
    """Harness-injected crash at a named install stage (faults.SnapFault
    via the virtual cluster) — never raised on a production path."""

    def __init__(self, stage: str):
        super().__init__(stage)
        self.stage = stage


def classify_table(name: str) -> Optional[str]:
    """``"keep"`` / ``"scrub"`` for a ``__corro_*`` table, None for a
    user table.  Every internal table must classify — an unknown
    ``__corro_*`` name raises so a future bookkeeping table cannot
    silently leak into (or vanish from) snapshots."""
    if any(name.endswith(sfx) for sfx in SNAP_KEEP_SUFFIXES):
        # per-CRR-table clock/cl tables ("tests__corro_clock"): the
        # replicated state itself, named after the user table
        return "keep"
    if not name.startswith("__corro_"):
        return None
    if name in SNAP_SCRUB:
        return "scrub"
    if name in SNAP_KEEP:
        return "keep"
    if any(name.startswith(pfx) for pfx in SNAP_SCRUB_PREFIXES):
        return "scrub"
    raise SnapshotError(
        f"internal table {name!r} has no snapshot scrub/keep decision — "
        "add it to snapshot.SNAP_SCRUB or snapshot.SNAP_KEEP"
    )


def scrub_snapshot(conn: sqlite3.Connection) -> None:
    """Delete node-local state from a snapshot/backup copy (shared by
    the sync snapshot path and ``backup.py``).  Caller commits."""
    tables = [
        r[0]
        for r in conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table' "
            "AND name LIKE '\\_\\_corro\\_%' ESCAPE '\\'"
        )
    ]
    for t in tables:
        if classify_table(t) == "scrub":
            conn.execute(f'DELETE FROM "{t}"')
    for key in SNAP_SCRUB_STATE_KEYS:
        conn.execute("DELETE FROM __corro_state WHERE key=?", (key,))


def _connect(path: str) -> sqlite3.Connection:
    """Open a database with the CRR layer's SQL functions registered
    (expression indexes reference them)."""
    from corrosion_tpu.agent.storage import register_udfs

    conn = sqlite3.connect(path)
    register_udfs(conn)
    return conn


def build_snapshot(db_path: str, out_path: str) -> None:
    """Write a consistent, scrubbed, single-file snapshot of
    ``db_path`` to ``out_path`` (must not exist).  Safe against a live
    writer: ``VACUUM INTO`` copies one WAL read snapshot."""
    if os.path.exists(out_path):
        raise SnapshotError(f"snapshot target exists: {out_path}")
    src = _connect(db_path)
    try:
        src.execute("VACUUM INTO ?", (out_path,))
    finally:
        src.close()
    snap = _connect(out_path)
    try:
        # single file on disk: the staged copy travels (and swaps) as
        # one artifact, never a db + sidecar-journal pair
        snap.execute("PRAGMA journal_mode=DELETE")
        scrub_snapshot(snap)
        snap.commit()
        snap.execute("VACUUM")
    finally:
        snap.close()


def file_digest(path: str) -> bytes:
    """Whole-file blake2b content digest (the install gate: a served
    snapshot installs only when the received bytes hash to the digest
    the offer advertised)."""
    h = hashlib.blake2b(digest_size=DIGEST_LEN)
    with open(path, "rb") as f:
        while True:
            block = f.read(_CHUNK)
            if not block:
                break
            h.update(block)
    return h.digest()


# ---------------------------------------------------------------------------
# staging sidecar + crash journal
# ---------------------------------------------------------------------------


def fsync_dir(path: str) -> None:
    """fsync the directory holding ``path``: a rename/unlink is only
    durable once its directory entry is — without this a power loss
    (not just a process kill) could reorder the marker rename against
    the database swap and present the boot-time recovery with a
    window its classification table calls impossible."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def staged_path(db_path: str) -> str:
    return db_path + ".snap-staged"


def marker_path(db_path: str) -> str:
    return db_path + ".snap-state"


def write_marker(db_path: str, stage: str, digest: bytes,
                 size: int) -> None:
    """Durably record the install state machine's position: written
    BEFORE each irreversible step so a crash at any point is
    classifiable at boot."""
    p = marker_path(db_path)
    tmp = p + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {"stage": stage, "digest": digest.hex(), "size": int(size)}, f
        )
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, p)
    fsync_dir(p)


def read_marker(db_path: str) -> Optional[dict]:
    try:
        with open(marker_path(db_path)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def clear_marker(db_path: str) -> None:
    removed = False
    for p in (marker_path(db_path), marker_path(db_path) + ".tmp"):
        if os.path.exists(p):
            os.unlink(p)
            removed = True
    if removed:
        fsync_dir(db_path)


def recover_pending_install(db_path: str) -> Optional[str]:
    """Boot-time crash recovery (called before storage opens).  A node
    killed at ANY install point classifies into exactly two outcomes:

    * ``"finalized"`` — the marker says ``installing`` and the staged
      sidecar is GONE: ``os.replace`` completed, so the database IS the
      fully-prepared snapshot (identity rewrite happens on the staged
      file *before* the swap).  Drop the marker and boot normally; the
      tail anti-entropy round picks up the delta.
    * ``"retry"`` — every other window (mid-stream, verified-but-
      unswapped, marker-but-staged-present): discard the sidecar and
      marker.  The previous database is untouched — the node boots
      into a clean snapshot retry, never a torn install.

    Returns the outcome, or None when no install was pending.
    """
    m = read_marker(db_path)
    sp = staged_path(db_path)
    if m is None:
        if os.path.exists(sp):
            # orphan sidecar with no journal: a crash before the first
            # marker write — nothing was promised, discard it
            os.unlink(sp)
            return "retry"
        return None
    if m.get("stage") == "installing" and not os.path.exists(sp):
        # the swap completed; a crash before the stale -wal/-shm of the
        # REPLACED inode were unlinked leaves them next to the new file
        # — they must not be recovered into the installed snapshot
        for ext in ("-wal", "-shm"):
            p = db_path + ext
            if os.path.exists(p):
                os.unlink(p)
        clear_marker(db_path)
        return "finalized"
    if os.path.exists(sp):
        os.unlink(sp)
    clear_marker(db_path)
    return "retry"


def prepare_staged(staged: str, site_id: bytes,
                   incarnation: Optional[int] = None) -> None:
    """Rewrite a verified staged snapshot IN PLACE to carry the
    installing node's identity — the ``backup.restore`` site-ordinal
    rewrite, run on the sidecar *before* the atomic swap so a crash
    after ``os.replace`` needs no further repair.

    The snapshot origin's identity moves from ordinal 1 to a fresh
    ordinal (keeping every clock row's attribution intact) and ordinal
    1 — the slot the local triggers stamp — becomes ``site_id``.  When
    the installing node's identity already exists in the snapshot's
    site directory (the server knew us), its existing ordinal is
    REUSED: its clock rows re-attribute to ordinal 1 instead of a
    unique-constraint failure."""
    conn = _connect(staged)
    try:
        conn.execute("PRAGMA journal_mode=DELETE")
        row = conn.execute(
            "SELECT site_id FROM __corro_sites WHERE ordinal=1"
        ).fetchone()
        if row is None:
            raise SnapshotError("staged snapshot has no site directory")
        origin = bytes(row[0])
        tables = [
            r[0]
            for r in conn.execute("SELECT name FROM __corro_crr_tables")
        ]

        def _rewrite(old_ord: int, new_ord: int) -> None:
            for t in tables:
                for suffix in SNAP_KEEP_SUFFIXES:
                    conn.execute(
                        f'UPDATE "{t}{suffix}" SET site_ordinal=? '
                        "WHERE site_ordinal=?",
                        (new_ord, old_ord),
                    )

        if origin != site_id:
            (max_ord,) = conn.execute(
                "SELECT COALESCE(MAX(ordinal), 1) FROM __corro_sites"
            ).fetchone()
            ours = conn.execute(
                "SELECT ordinal FROM __corro_sites WHERE site_id=?",
                (site_id,),
            ).fetchone()
            # origin identity out of slot 1, attribution preserved
            conn.execute(
                "UPDATE __corro_sites SET ordinal=? WHERE ordinal=1",
                (max_ord + 1,),
            )
            _rewrite(1, max_ord + 1)
            if ours is not None:
                conn.execute(
                    "UPDATE __corro_sites SET ordinal=1 WHERE site_id=?",
                    (site_id,),
                )
                _rewrite(ours[0], 1)
            else:
                conn.execute(
                    "INSERT INTO __corro_sites (ordinal, site_id) "
                    "VALUES (1, ?)",
                    (site_id,),
                )
        if incarnation is not None:
            conn.execute(
                "INSERT INTO __corro_state (key, value) "
                "VALUES ('incarnation', ?) "
                "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
                (int(incarnation),),
            )
        conn.commit()
    finally:
        conn.close()
    # the prepared bytes must be durable BEFORE the 'installing' marker
    # promises them: fsync file + directory entry
    fd = os.open(staged, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    fsync_dir(staged)


# ---------------------------------------------------------------------------
# snapshot-or-changes dispatch (pure functions)
# ---------------------------------------------------------------------------


def covered_below_floor(needs: Dict, floors: Dict) -> int:
    """How many of the client's needed versions sit at-or-below the
    server's advertised per-actor snapshot floors — versions whose
    per-version bookkeeping the server has COMPACTED and therefore can
    no longer serve change-by-change.  Pure in (client needs, server
    floors): the whole snapshot-or-changes dispatch decides on this
    count (≥ 1 ⇒ only a snapshot can deliver them from this peer)."""
    covered = 0
    for actor, actor_needs in needs.items():
        floor = int(floors.get(actor, 0))
        if floor <= 0:
            continue
        for n in actor_needs:
            if n.kind == "full":
                s, e = n.versions
                if s <= floor:
                    covered += min(int(e), floor) - int(s) + 1
            elif n.kind == "partial" and int(n.version) <= floor:
                covered += 1
    return covered


def client_behind(our_heads: Dict, their_heads: Dict) -> bool:
    """Install-safety gate: a snapshot REPLACES the client's database,
    so it is only sound when the server's recorded head for every
    actor the client tracks (including the client's own) is at least
    the client's — otherwise local-only writes would be lost.  Pure in
    (client heads, server heads); re-checked under the storage lock
    immediately before the swap."""
    for actor, head in our_heads.items():
        if int(head) > int(their_heads.get(actor, 0)):
            return False
    return True
