"""TOML configuration.

Parity: ``crates/corro-types/src/config.rs`` — sections ``[db]``,
``[api]``, ``[gossip]``, ``[perf]``, ``[admin]``, ``[telemetry]``,
``[consul]``; env-var overlay using ``__``-separated keys
(``CORRO_GOSSIP__ADDR=...``), and a builder used by tests.
"""

from __future__ import annotations

import os

try:
    import tomllib
except ModuleNotFoundError:  # py<3.11: same API under the old name
    import tomli as tomllib
from typing import Any, Dict, List, Optional

from corrosion_tpu.agent.runtime import AgentConfig

ENV_PREFIX = "CORRO_"


def _deep_set(d: Dict[str, Any], keys: List[str], value: Any) -> None:
    for k in keys[:-1]:
        d = d.setdefault(k, {})
    d[keys[-1]] = value


def _env_overlay(data: Dict[str, Any]) -> None:
    for name, raw in os.environ.items():
        if not name.startswith(ENV_PREFIX):
            continue
        keys = [k.lower() for k in name[len(ENV_PREFIX):].split("__")]
        value: Any = raw
        if raw.lower() in ("true", "false"):
            value = raw.lower() == "true"
        elif "," in raw:
            value = [s.strip() for s in raw.split(",") if s.strip()]
        else:
            for conv in (int, float):
                try:
                    value = conv(raw)
                    break
                except ValueError:
                    continue
        _deep_set(data, keys, value)


def _split_addr(addr: str, default_port: int = 0):
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port or default_port)


def load_config(path: Optional[str] = None, **overrides) -> AgentConfig:
    """Load a TOML config file (+ CORRO_* env overlay) into AgentConfig."""
    data: Dict[str, Any] = {}
    if path:
        with open(path, "rb") as f:
            data = tomllib.load(f)
    _env_overlay(data)

    db = data.get("db", {})
    api = data.get("api", {})
    gossip = data.get("gossip", {})
    perf = data.get("perf", {})
    admin = data.get("admin", {})

    api_host, api_port = _split_addr(api.get("addr", "127.0.0.1:0"))
    g_host, g_port = _split_addr(gossip.get("addr", "127.0.0.1:0"))

    schema_sql = None
    schema_paths = db.get("schema_paths", [])
    if schema_paths:
        parts = []
        for p in schema_paths:
            if os.path.isdir(p):
                for fn in sorted(os.listdir(p)):
                    if fn.endswith(".sql"):
                        with open(os.path.join(p, fn)) as f:
                            parts.append(f.read())
            elif os.path.exists(p):
                with open(p) as f:
                    parts.append(f.read())
        schema_sql = "\n".join(parts) or None

    bootstrap = gossip.get("bootstrap", [])
    if isinstance(bootstrap, str):
        bootstrap = [bootstrap]

    kwargs: Dict[str, Any] = dict(
        db_path=db.get("path", "corrosion.db"),
        gossip_host=g_host,
        gossip_port=g_port,
        api_host=api_host,
        api_port=api_port,
        bootstrap=list(bootstrap),
        admin_path=admin.get("path"),
        schema_sql=schema_sql,
        cluster_id=int(gossip.get("cluster_id", 0)),
        api_authz=(api.get("authorization") or {}).get("bearer")
        if isinstance(api.get("authorization"), dict)
        else api.get("authorization"),
        subs_path=data.get("subscriptions", {}).get("path"),
    )
    # [api.pg] addr = "host:port" (config.rs PgConfig): the PostgreSQL
    # wire-protocol listener; None/absent = off
    pg = api.get("pg")
    if isinstance(pg, dict) and pg.get("addr"):
        pg_host, pg_port = _split_addr(pg["addr"])
        kwargs["pg_host"] = pg_host
        kwargs["pg_port"] = pg_port
        # [api.pg] verify_client (corro-pg verify_client): PG's own
        # client-cert knob, independent of gossip mTLS
        kwargs["pg_tls_verify_client"] = bool(pg.get("verify_client"))
    # [telemetry.traces] path: append finished spans as OTLP-flavored
    # JSON lines (the reference exports via OTLP; config.rs telemetry).
    # max_bytes bounds the file (one rotation to path.1, then drops
    # counted in corro_trace_spans_dropped_total)
    traces = data.get("telemetry", {}).get("traces")
    if isinstance(traces, dict) and traces.get("path"):
        kwargs["trace_export_path"] = traces["path"]
        if "max_bytes" in traces:
            kwargs["trace_export_max_bytes"] = int(traces["max_bytes"])
    # [telemetry.flight] path: append flight-ring records (metric
    # snapshots + typed events) as JSON lines, bounded with the same
    # one-rotation/drop-counter discipline as the spans export
    flight = data.get("telemetry", {}).get("flight")
    if isinstance(flight, dict) and flight.get("path"):
        kwargs["flight_export_path"] = flight["path"]
        if "max_bytes" in flight:
            kwargs["flight_export_max_bytes"] = int(flight["max_bytes"])
    # [gossip.tls] (config.rs TlsConfig: cert-file/key-file/ca-file/
    # insecure + [gossip.tls.client] cert-file/key-file/required)
    tls = gossip.get("tls", {})
    if tls:
        kwargs.update(
            tls_cert_file=tls.get("cert_file") or tls.get("cert-file"),
            tls_key_file=tls.get("key_file") or tls.get("key-file"),
            tls_ca_file=tls.get("ca_file") or tls.get("ca-file"),
            tls_insecure=bool(tls.get("insecure", False)),
        )
        client = tls.get("client", {})
        if client:
            kwargs.update(
                tls_client_required=bool(client.get("required", False)),
                tls_client_cert_file=(client.get("cert_file")
                                      or client.get("cert-file")),
                tls_client_key_file=(client.get("key_file")
                                     or client.get("key-file")),
            )
    for key in (
        "probe_interval",
        "probe_timeout",
        "suspect_timeout",
        "gossip_interval",
        "gossip_fanout",
        "num_indirect_probes",
        "fanout",
        "max_transmissions",
        "rebroadcast_delay",
        "sync_interval_min",
        "sync_interval_max",
        "sync_peers",
        "max_sync_sessions",
        "seen_cache_size",
        "write_group_commit",
        "write_group_max",
        # convergence observability plane (docs/telemetry.md)
        "provenance",
        "staleness_evict_s",
        "bcast_trace_propagation",
        "stall_probe_interval",
        "stall_probe_slow_ms",
        # flight recorder (docs/telemetry.md)
        "flight_interval_s",
        "flight_ring_max",
        # equivocation defense (docs/faults.md)
        "equivocation_detection",
        # subscription matcher plane (docs/pubsub.md)
        "subs_shards",
        "subs_columnar",
        "subs_shard_max_pending",
        # batched-apply merge plane (docs/crdts.md)
        "columnar_merge",
        "columnar_merge_min",
        # device-resident apply (docs/crdts.md "Device-resident apply")
        "device_cache",
        "device_cache_slots",
    ):
        if key in perf:
            kwargs[key] = perf[key]
    kwargs.update(overrides)
    return AgentConfig(**kwargs)
