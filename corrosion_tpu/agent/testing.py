"""Test harness: boot complete real agents on loopback.

Parity: ``crates/corro-tests/src/lib.rs:13-95`` — ``launch_test_agent``
boots a full agent (gossip on 127.0.0.1:0, HTTP on :0, tempdir DB, schema
applied) so integration tests exercise real gossip, not mocks.
"""

from __future__ import annotations

import asyncio
import tempfile
from typing import List, Optional

from corrosion_tpu.agent.runtime import Agent, AgentConfig

TEST_SCHEMA = """
CREATE TABLE IF NOT EXISTS tests (
  id INTEGER NOT NULL PRIMARY KEY,
  text TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS tests2 (
  id INTEGER NOT NULL PRIMARY KEY,
  text TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS testsblob (
  id BLOB NOT NULL PRIMARY KEY,
  text TEXT NOT NULL DEFAULT ''
);
"""


async def launch_test_agent(
    bootstrap: Optional[List[str]] = None,
    schema: str = TEST_SCHEMA,
    tmpdir: Optional[str] = None,
    fault_filter=None,
    **overrides,
) -> Agent:
    d = tmpdir or tempfile.mkdtemp(prefix="corro-test-")
    kwargs = dict(
        # fast timers for tests (explicit overrides win)
        probe_interval=0.1,
        probe_timeout=0.15,
        suspect_timeout=0.6,
        rebroadcast_delay=0.05,
        sync_interval_min=0.15,
        sync_interval_max=0.4,
        bcast_flush_interval=0.02,
    )
    kwargs.update(overrides)
    cfg = AgentConfig(
        db_path=f"{d}/corrosion.db",
        bootstrap=bootstrap or [],
        schema_sql=schema,
        **kwargs,
    )
    agent = Agent(cfg)
    # the fault-injection hook must be live BEFORE start(): the boot
    # window (bootstrap announces, first probes) is part of the fault
    # model — a node restarting INTO an active partition or lossy link
    # must not get a fault-free head start
    if fault_filter is not None:
        agent.fault_filter = fault_filter
    await agent.start()
    return agent


def seed_full_membership(agents) -> None:
    """Give every agent a complete ALIVE member view of the others.

    Harness shortcut for large static-membership experiments (e.g. the
    sim-vs-agent calibration at N=256): the epidemic under measurement is
    the broadcast, and full membership is its precondition — SWIM's own
    dissemination is measured separately (BASELINE config #2)."""
    for a in agents:
        for b in agents:
            if a is b:
                continue
            a.members.upsert(b.actor_id, tuple(b.gossip_addr))


class CaptureWriter:
    """StreamWriter stand-in that collects written bytes — serve-path
    harnesses point ``_serve_need``/``_serve_sync`` at one of these and
    decode ``buf`` with ``speedy.FrameReader``."""

    def __init__(self):
        self.buf = bytearray()

    def write(self, b: bytes) -> None:
        self.buf += b

    async def drain(self) -> None:
        pass

    def close(self) -> None:
        pass


def make_offline_agent(
    tmpdir: Optional[str] = None,
    schema: str = TEST_SCHEMA,
    **overrides,
) -> Agent:
    """Build a full Agent WITHOUT starting its network loops: storage,
    bookkeeping, and the sync serve path all work (handle_change /
    _serve_need are loop-independent), so serve-side parity and bench
    harnesses can drive thousands of versions without paying gossip
    timers or socket setup.  Callers must ``agent.storage.close()`` (or
    use it inside asyncio.run and close after)."""
    d = tmpdir or tempfile.mkdtemp(prefix="corro-offline-")
    cfg = AgentConfig(
        db_path=f"{d}/corrosion.db",
        schema_sql=schema,
        api_port=None,
        **overrides,
    )
    return Agent(cfg)


async def wait_for(predicate, timeout: float = 10.0, interval: float = 0.05):
    """Poll until predicate() is truthy or raise TimeoutError."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        v = predicate()
        if v:
            return v
        if loop.time() > deadline:
            raise TimeoutError("condition not met in time")
        await asyncio.sleep(interval)
