"""Wire codec: framed messages for gossip, SWIM and sync traffic.

What actually travels on each channel class (keep this current —
``tests/test_live_wire.py`` pins it at the byte level):

* **uni/bi streams (broadcasts + sync)** — speedy-encoded
  ``UniPayload``/``BiPayload`` frames with u32-BE length framing,
  byte-compatible with the reference
  (``crates/corro-types/src/broadcast.rs:37-67``); see
  ``bridge/speedy.py`` and ``runtime.py`` for the encode/decode call
  sites.  The JSON envelope in this module is NOT used on those
  streams.
* **SWIM datagrams (membership)** — binary foca messages
  (``bridge/foca.py`` + ``agent/swim_foca.py``), the wire the
  reference relays verbatim
  (``crates/corro-agent/src/broadcast/mod.rs:185-324``); this is the
  default (``AgentConfig.swim_wire == "foca"``).  The JSON envelope
  defined in this module remains the ``swim_wire="json"`` fallback,
  and receivers accept both formats (sniffed by first byte).

Message kinds:
  swim:     {kind, probe|ack|ping_req|gossip..., member entries}
  change:   one Changeset (full/empty/empty_set) from an actor
  sync_*:   sync session handshake/needs/changesets
"""

from __future__ import annotations

import base64
import json
import struct
from typing import List, Optional

from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq, Version
from corrosion_tpu.types.change import Change
from corrosion_tpu.types.changeset import Changeset, ChangesetKind, ChangeV1
from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.hlc import Timestamp

MAX_FRAME = 16 * 1024 * 1024


def _b64(b: Optional[bytes]) -> Optional[str]:
    return None if b is None else base64.b64encode(b).decode("ascii")


def _unb64(s: Optional[str]) -> Optional[bytes]:
    return None if s is None else base64.b64decode(s)


def _enc_val(v):
    if isinstance(v, (bytes, bytearray, memoryview)):
        return {"__b": _b64(bytes(v))}
    return v


def _dec_val(v):
    if isinstance(v, dict) and "__b" in v:
        return _unb64(v["__b"])
    return v


def change_to_dict(ch: Change) -> dict:
    return {
        "t": ch.table,
        "pk": _b64(ch.pk),
        "c": ch.cid,
        "v": _enc_val(ch.val),
        "cv": ch.col_version,
        "dv": int(ch.db_version),
        "s": int(ch.seq),
        "si": _b64(ch.site_id),
        "cl": ch.cl,
    }


def change_from_dict(d: dict) -> Change:
    return Change(
        table=d["t"],
        pk=_unb64(d["pk"]),
        cid=d["c"],
        val=_dec_val(d["v"]),
        col_version=d["cv"],
        db_version=CrsqlDbVersion(d["dv"]),
        seq=CrsqlSeq(d["s"]),
        site_id=_unb64(d["si"]),
        cl=d["cl"],
    )


def changeset_to_dict(cs: Changeset) -> dict:
    d: dict = {"kind": cs.kind.value}
    if cs.ts is not None:
        d["ts"] = int(cs.ts)
    if cs.kind is ChangesetKind.FULL:
        d["version"] = int(cs.version)
        d["changes"] = [change_to_dict(c) for c in cs.changes]
        d["seqs"] = [int(cs.seqs[0]), int(cs.seqs[1])]
        d["last_seq"] = int(cs.last_seq)
    elif cs.kind is ChangesetKind.EMPTY:
        d["versions"] = [int(cs.versions[0]), int(cs.versions[1])]
    else:
        d["ranges"] = [[int(a), int(b)] for a, b in cs.ranges]
    return d


def changeset_from_dict(d: dict) -> Changeset:
    ts = Timestamp(d["ts"]) if "ts" in d else None
    kind = ChangesetKind(d["kind"])
    if kind is ChangesetKind.FULL:
        return Changeset.full(
            version=Version(d["version"]),
            changes=[change_from_dict(c) for c in d["changes"]],
            seqs=(CrsqlSeq(d["seqs"][0]), CrsqlSeq(d["seqs"][1])),
            last_seq=CrsqlSeq(d["last_seq"]),
            ts=ts,
        )
    if kind is ChangesetKind.EMPTY:
        return Changeset.empty(
            (Version(d["versions"][0]), Version(d["versions"][1])), ts
        )
    return Changeset.empty_set([tuple(r) for r in d.get("ranges", [])], ts)


def change_v1_to_dict(cv: ChangeV1) -> dict:
    return {"actor": _b64(cv.actor_id.bytes), "cs": changeset_to_dict(cv.changeset)}


def change_v1_from_dict(d: dict) -> ChangeV1:
    return ChangeV1(
        actor_id=ActorId(_unb64(d["actor"])),
        changeset=changeset_from_dict(d["cs"]),
    )


# ---------------------------------------------------------------------------
# partial-changeset buffer blobs (__corro_buffered_changes.change)
# ---------------------------------------------------------------------------

# Versioned binary format: one prefix byte, then the speedy Change
# layout (bridge/speedy.py encode_change).  Old databases hold JSON
# blobs from the legacy encoding (change_to_dict + encode_datagram);
# those start with '{' (0x7b, which can never be a known format prefix)
# and still decode on read — no migration pass required.
BUFFERED_BLOB_SPEEDY = 0x01


def encode_buffered_change(ch: Change) -> bytes:
    from corrosion_tpu.bridge import speedy

    return bytes((BUFFERED_BLOB_SPEEDY,)) + speedy.encode_change(ch)


def decode_buffered_change(blob: bytes) -> Change:
    blob = bytes(blob)
    if blob[:1] == b"{":
        # legacy JSON blob written before the binary format
        return change_from_dict(decode_datagram(blob))
    if blob[:1] == bytes((BUFFERED_BLOB_SPEEDY,)):
        from corrosion_tpu.bridge import speedy

        return speedy.decode_change(blob[1:])
    raise ValueError(
        f"unknown buffered-change blob format {blob[:1]!r}"
    )


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def encode_msg(msg: dict) -> bytes:
    body = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        raise ValueError(f"frame too large: {len(body)}")
    return struct.pack(">I", len(body)) + body


def decode_msg(body: bytes) -> dict:
    return json.loads(body.decode("utf-8"))


def encode_datagram(msg: dict) -> bytes:
    """Unframed (datagram) encoding for SWIM packets."""
    return json.dumps(msg, separators=(",", ":")).encode("utf-8")


def decode_datagram(data: bytes) -> dict:
    return json.loads(data.decode("utf-8"))


class FrameReader:
    """Incremental length-prefixed frame decoder for stream transports."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[dict]:
        self._buf += data
        out = []
        while True:
            if len(self._buf) < 4:
                return out
            (ln,) = struct.unpack_from(">I", self._buf, 0)
            if ln > MAX_FRAME:
                raise ValueError(f"frame too large: {ln}")
            if len(self._buf) < 4 + ln:
                return out
            body = bytes(self._buf[4 : 4 + ln])
            del self._buf[: 4 + ln]
            out.append(decode_msg(body))
