"""Single-connection channel multiplexing over TCP.

Parity: the reference carries all three channel classes of a peer pair
over ONE QUIC connection — datagrams, N uni streams, N bi streams —
with per-stream framing and stream-level stats
(``crates/corro-agent/src/transport.rs:55-173``,
``api/peer.rs:97-342``).  Datagrams stay on UDP here (they are
unreliable by design), but the reliable classes now share one cached
TCP connection per peer instead of one-connection-per-class: a ``M``
prelude byte, then mux frames

    [1B class][4B channel id][4B length][payload]

where class 0 is the uni broadcast channel (channel id 0, a
fire-and-forget payload stream), class 1 is client→server bi data,
class 2 is server→client bi data, and class 3 aborts a bi channel.
A bi channel opens implicitly at its first class-1 frame (the client
allocates ids), carries one sync session, half-closes with an empty
data frame (the EOF the sync protocol already speaks), and an abort
surfaces as a ConnectionResetError on the other side — NOT a clean
EOF, exactly the distinction ``_serve_sync``'s slow-peer abort needs.

Virtual streams adapt the mux to the existing sync code unchanged:
the reader side is a real ``asyncio.StreamReader`` fed by the demux
pump; :class:`VirtualWriter` provides the ``StreamWriter`` surface the
sync client/server use (write/drain/write_eof/close/transport.abort),
framing each drain under the connection's write lock so concurrent
channels never interleave mid-frame.

The client side also reproduces the reference's hashed-endpoint
spread (``transport.rs:55-93``: 8 client endpoints, peers assigned by
address hash): :func:`lane_of` maps a peer address onto one of
``LANES`` lanes, which shard the CONNECT concurrency (one semaphore
per lane) so a connect storm to many peers fans across lanes instead
of one queue — the TCP analogue of spreading peers over client
sockets (TCP gives every connection its own socket either way; the
connection cache itself is shared).

Flow control: channel readers are fed by the demux pump, and a
consumer slower than the network would otherwise buffer unboundedly
(and let the remote's ``drain()`` return instantly, defeating the
sync server's slow-peer abort).  The pump therefore STOPS reading the
socket while any channel's buffered backlog exceeds
``CHANNEL_BUF_CAP`` — whole-connection head-of-line blocking, like
TCP itself and unlike QUIC's per-stream windows, but it restores
end-to-end backpressure: a stalled consumer fills the kernel buffers
and the remote's drain genuinely blocks.
"""

from __future__ import annotations

import asyncio
import hashlib
import struct
from typing import Callable, Dict, Optional, Tuple

Addr = Tuple[str, int]

STREAM_MUX = b"M"

CLASS_UNI = 0
CLASS_BI_C2S = 1
CLASS_BI_S2C = 2
CLASS_ABORT = 3

_HDR = struct.Struct(">BII")

# the reference runs 8 client endpoints (transport.rs:55-93)
LANES = 8

# per-channel receive backlog cap: past it the demux pauses the socket
CHANNEL_BUF_CAP = 1 << 20


class TombstoneSet:
    """Closed-channel ids with bounded memory that can never resurrect
    a ghost session.

    Channel ids are CLIENT-MONOTONIC within a connection, so the
    oldest tombstones are the smallest ids.  Eviction is oldest-first
    (insertion-order deque + set), and everything ever evicted stays
    dead via a watermark: ``ch in ts`` is true for any id at or below
    the highest evicted id.  The old ``list(set)[:4096]`` eviction
    discarded an ARBITRARY half — including the most recently closed
    ids, whose late in-flight frames would then reopen ghost sessions.

    The watermark makes membership monotone: a dropped tombstone can
    only widen the dead range, never shrink it.  Callers must check
    LIVE channels first — a long-lived channel whose id falls under
    the advancing watermark is still open and must keep working.
    """

    def __init__(self, cap: int = 8192):
        from collections import deque

        self.cap = cap
        self._set: set = set()
        self._order = deque()
        self._watermark = -1

    def add(self, ch: int) -> None:
        if ch in self:
            return
        self._set.add(ch)
        self._order.append(ch)
        while len(self._order) > self.cap:
            old = self._order.popleft()
            self._set.discard(old)
            if old > self._watermark:
                self._watermark = old

    def __contains__(self, ch: int) -> bool:
        return ch <= self._watermark or ch in self._set

    def __len__(self) -> int:
        return len(self._set)


def _backlog(reader: asyncio.StreamReader) -> int:
    """Buffered-but-unread bytes of a pump-fed reader.  StreamReader
    has no public backlog accessor when fed without a transport; the
    internal buffer attribute is stable across CPython versions."""
    buf = getattr(reader, "_buffer", b"")
    return len(buf)


# same invariant as the legacy speedy path (speedy.py MAX_FRAME_LEN):
# a hostile length prefix must not become an unbounded allocation
MAX_MUX_FRAME = 8 * 1024 * 1024


async def read_frames(reader: asyncio.StreamReader):
    """The one frame grammar for both sides: yields
    (class, channel, payload) until EOF/connection loss.  A frame
    claiming more than MAX_MUX_FRAME tears the connection down."""
    while True:
        hdr = await reader.readexactly(_HDR.size)
        cls, ch, ln = _HDR.unpack(hdr)
        if ln > MAX_MUX_FRAME:
            raise ConnectionResetError(
                f"mux frame length {ln} exceeds cap"
            )
        payload = await reader.readexactly(ln) if ln else b""
        yield cls, ch, payload


async def _pause_while_backlogged(channels, clock=None) -> None:
    if clock is None:
        from corrosion_tpu.clock import SYSTEM_CLOCK as clock
    while any(
        _backlog(r) > CHANNEL_BUF_CAP for r in channels.values()
    ):
        await clock.sleep(0.01)


def lane_of(addr: Addr, lanes: int = LANES) -> int:
    """Peer-address → lane index (the endpoint-choice hash).  Stable
    across processes (no PYTHONHASHSEED dependence)."""
    h = hashlib.blake2s(
        f"{addr[0]}:{addr[1]}".encode(), digest_size=4
    ).digest()
    return int.from_bytes(h, "big") % lanes


def frame(cls: int, channel: int, payload: bytes) -> bytes:
    return _HDR.pack(cls, channel, len(payload)) + payload


class _AbortShim:
    """The ``writer.transport.abort()`` surface _serve_sync uses."""

    def __init__(self, vw: "VirtualWriter"):
        self._vw = vw

    def abort(self) -> None:
        self._vw.abort()


class VirtualWriter:
    """StreamWriter-shaped sender for one bi channel over a mux.

    Semantics map: ``write`` buffers; ``drain`` flushes one data frame;
    ``write_eof``/``close`` flush the tail + half-close frame WITHOUT a
    drain call (a real socket transmits those immediately too — the
    sync session loop relies on it); ``transport.abort()`` tears the
    channel down with an ABORT frame instead of a clean EOF."""

    def __init__(self, send_locked: Callable, channel: int, cls: int,
                 on_close: Optional[Callable] = None):
        self._send = send_locked  # async (bytes) -> None, lock-holding
        self.channel = channel
        self.cls = cls
        self._buf: list = []
        self._eof_sent = False
        self._aborted = False
        self._closed = False
        self._on_close = on_close
        self.transport = _AbortShim(self)

    def write(self, data: bytes) -> None:
        if data:
            self._buf.append(bytes(data))

    async def drain(self) -> None:
        if self._aborted:
            raise ConnectionResetError("channel aborted")
        if self._buf:
            payload = b"".join(self._buf)
            self._buf.clear()
            await self._send(frame(self.cls, self.channel, payload))

    def _flush_tail(self) -> None:
        """Schedule the remaining data + the half-close frame."""
        if self._eof_sent or self._aborted:
            return
        self._eof_sent = True
        data = b"".join(self._buf)
        self._buf = []

        async def _tail():
            try:
                if data:
                    await self._send(frame(self.cls, self.channel, data))
                await self._send(frame(self.cls, self.channel, b""))
            except (OSError, ConnectionError, RuntimeError):
                pass

        try:
            asyncio.ensure_future(_tail())
        except RuntimeError:  # no running loop (teardown)
            pass

    def can_write_eof(self) -> bool:
        return True

    def write_eof(self) -> None:
        self._flush_tail()

    def abort(self) -> None:
        if self._aborted:
            return
        self._aborted = True
        self._closed = True
        if self._on_close is not None:
            self._on_close(self.channel, abort=True)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._flush_tail()
        if self._on_close is not None:
            self._on_close(self.channel, abort=False)

    def is_closing(self) -> bool:
        return self._closed

    async def wait_closed(self) -> None:
        return


class MuxConnection:
    """Client side: one TCP connection carrying the uni channel plus
    any number of concurrent bi channels."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, metrics=None, clock=None):
        self.reader = reader
        self.writer = writer
        self.metrics = metrics
        self._clock = clock  # backpressure-poll time source (None = real)
        self.wlock = asyncio.Lock()
        self._channels: Dict[int, asyncio.StreamReader] = {}
        self._next_id = 1
        self.closed = False
        self._pump_task = asyncio.ensure_future(self._pump())

    # -- sending ---------------------------------------------------------

    async def _send_locked(self, data: bytes) -> None:
        if self.closed:
            raise ConnectionResetError("mux connection closed")
        async with self.wlock:
            self.writer.write(data)
            await self.writer.drain()

    async def send_uni(self, frames_blob: bytes) -> None:
        await self._send_locked(frame(CLASS_UNI, 0, frames_blob))
        if self.metrics is not None:
            self.metrics.counter(
                "corro_transport_bytes_total", len(frames_blob),
                channel="uni",
            )
            self.metrics.counter(
                "corro_transport_frames_total", channel="uni")

    def open_channel(self):
        """(reader, writer) for a fresh bi channel."""
        ch = self._next_id
        self._next_id += 1
        r = asyncio.StreamReader()
        self._channels[ch] = r

        def on_close(channel: int, abort: bool) -> None:
            self._channels.pop(channel, None)
            if abort and not self.closed:
                try:
                    coro = self._send_locked(
                        frame(CLASS_ABORT, channel, b"")
                    )
                    asyncio.ensure_future(coro)
                except RuntimeError:  # no loop (teardown)
                    pass

        async def send(data: bytes) -> None:
            await self._send_locked(data)
            if self.metrics is not None:
                self.metrics.counter(
                    "corro_transport_bytes_total", len(data) - _HDR.size,
                    channel="bi",
                )
                self.metrics.counter(
                    "corro_transport_frames_total", channel="bi")

        w = VirtualWriter(send, ch, CLASS_BI_C2S, on_close)
        if self.metrics is not None:
            self.metrics.counter("corro_transport_bi_channels_total")
        return r, w

    # -- receiving -------------------------------------------------------

    async def _pump(self) -> None:
        try:
            async for cls, ch, payload in read_frames(self.reader):
                await _pause_while_backlogged(self._channels,
                                              clock=self._clock)
                if cls == CLASS_BI_S2C:
                    r = self._channels.get(ch)
                    if r is None:
                        continue
                    if not payload:
                        r.feed_eof()
                    else:
                        r.feed_data(payload)
                elif cls == CLASS_ABORT:
                    r = self._channels.pop(ch, None)
                    if r is not None:
                        r.set_exception(
                            ConnectionResetError("peer aborted channel")
                        )
                # CLASS_UNI toward a client is not part of the protocol
        except (asyncio.IncompleteReadError, OSError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            self.closed = True
            for r in self._channels.values():
                if not r.at_eof():
                    r.set_exception(
                        ConnectionResetError("mux connection lost")
                    )
            self._channels.clear()
            try:
                self.writer.close()
            except Exception:
                pass

    def close(self) -> None:
        self.closed = True
        self._pump_task.cancel()
        try:
            self.writer.close()
        except Exception:
            pass


async def serve_mux(agent, reader: asyncio.StreamReader,
                    writer: asyncio.StreamWriter) -> None:
    """Server side: demux one inbound mux connection.

    Class-0 frames feed the broadcast ingest exactly like a dedicated
    uni stream; each new class-1 channel id becomes one sync session
    served by the UNCHANGED ``_serve_sync`` over virtual streams."""
    from corrosion_tpu.bridge import speedy

    uni_frames = speedy.FrameReader()
    # the delivering transport's address, carried with each uni
    # payload so a failed origin signature can blame the delivery
    # (runtime._blame_relay, docs/faults.md signed attribution)
    mux_peer = writer.get_extra_info("peername")
    if mux_peer is not None:
        mux_peer = tuple(mux_peer[:2])
    wlock = asyncio.Lock()
    channels: Dict[int, asyncio.StreamReader] = {}
    tasks: Dict[int, asyncio.Task] = {}
    closed = False

    async def send_locked(data: bytes) -> None:
        if closed:
            raise ConnectionResetError("mux connection closed")
        async with wlock:
            writer.write(data)
            await writer.drain()

    def open_server_channel(ch: int) -> asyncio.StreamReader:
        r = asyncio.StreamReader()
        channels[ch] = r

        def on_close(channel: int, abort: bool) -> None:
            channels.pop(channel, None)
            tombstones.add(channel)
            if abort and not closed:
                try:
                    asyncio.ensure_future(
                        send_locked(frame(CLASS_ABORT, channel, b""))
                    )
                except RuntimeError:
                    pass

        async def send(data: bytes) -> None:
            await send_locked(data)
            if agent.metrics is not None:
                agent.metrics.counter(
                    "corro_transport_bytes_total",
                    len(data) - _HDR.size, channel="bi",
                )

        vw = VirtualWriter(send, ch, CLASS_BI_S2C, on_close)

        async def run_session():
            try:
                await agent._serve_sync(r, vw)
            finally:
                # _serve_sync close()s (or aborts) the writer, which
                # flushes the tail + half-close the client's session
                # loop is waiting on; this is only the belt-and-braces
                # for exits that skipped close()
                vw.close()
                tasks.pop(ch, None)

        tasks[ch] = asyncio.ensure_future(run_session())
        return r

    # ids whose server side already closed/aborted: late in-flight
    # client frames for them are DROPPED, not resurrected as ghost
    # sessions (oldest-first eviction + a dead-range watermark on the
    # client-monotonic ids — see TombstoneSet)
    tombstones = TombstoneSet()
    try:
        async for cls, ch, payload in read_frames(reader):
            await _pause_while_backlogged(
                channels, clock=getattr(agent, "_clock", None)
            )
            if cls == CLASS_UNI:
                agent._ingest_uni_payloads(
                    uni_frames.feed(payload), mux_peer
                )
                if agent.metrics is not None:
                    agent.metrics.counter(
                        "corro_transport_frames_total", channel="uni")
            elif cls == CLASS_BI_C2S:
                # LIVE channels first: an old id still open must keep
                # working even under the advancing watermark
                r = channels.get(ch)
                if r is None:
                    if ch in tombstones:
                        continue
                    r = open_server_channel(ch)
                if not payload:
                    r.feed_eof()
                else:
                    r.feed_data(payload)
            elif cls == CLASS_ABORT:
                tombstones.add(ch)
                r = channels.pop(ch, None)
                if r is not None:
                    r.set_exception(
                        ConnectionResetError("client aborted channel")
                    )
    except (asyncio.IncompleteReadError, OSError, ConnectionError):
        pass
    finally:
        closed = True
        for r in channels.values():
            if not r.at_eof():
                r.set_exception(ConnectionResetError("mux lost"))
        for t in list(tasks.values()):
            t.cancel()
        writer.close()
