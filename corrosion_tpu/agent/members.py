"""Cluster member registry with RTT tiers.

Parity: ``crates/corro-types/src/members.rs`` — member states keyed by
actor, per-member RTT ring buffers (20 samples), latency buckets and the
**ring0** tier (peers under 6 ms) that broadcast fanout prefers; persisted
to ``__corro_members`` (``broadcast/mod.rs:803-935``).
"""

from __future__ import annotations

import enum
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

RING0_MAX_RTT_MS = 6.0
RTT_SAMPLES = 20

# quarantine evidence ranking (docs/faults.md): transport-class
# evidence ("breaker", "sig_failure") is recoverable and equal-rank;
# an unsigned equivocation verdict outranks it; a PROVEN signed
# equivocation outranks everything and is never relabeled or cleared
# by weaker evidence
_REASON_RANK = {
    "": 0,
    "breaker": 1,
    "sig_failure": 1,
    "equivocation": 2,
    "signed_equivocation": 3,
}
# reasons that survive an address move (evidence about the ACTOR)
_ACTOR_REASONS = ("equivocation", "signed_equivocation")
# transport-class restores clear each other (a half-open success is
# evidence about the same channel either way); verdict-class reasons
# only clear on their own exact restore call
_TRANSPORT_REASONS = ("breaker", "sig_failure")


def _restores(current: str, reason: str) -> bool:
    if current == reason:
        return True
    return current in _TRANSPORT_REASONS and reason in _TRANSPORT_REASONS


class MemberState(enum.Enum):
    ALIVE = "alive"
    SUSPECT = "suspect"
    DOWN = "down"


@dataclass
class Member:
    actor_id: bytes
    addr: Tuple[str, int]
    state: MemberState = MemberState.ALIVE
    incarnation: int = 0
    cluster_id: int = 0
    # RTT ring, allocated on the FIRST sample (None until then): most
    # members of a large cluster are never probed between samples, and
    # the per-record deque allocation is ~1 s of a 512-node boot
    rtts: Optional[deque] = None
    last_sync_ts: float = 0.0
    last_seen: float = field(default_factory=time.monotonic)
    # quarantine: a peer is deprioritized in fanout sampling the way
    # high-RTT peers are.  `quarantine_reason` records the evidence
    # class — "breaker" / "sig_failure" (transport-level: persistent
    # send failures / a delivery whose origin signature failed to
    # verify; restored on half-open success), "equivocation"
    # (protocol-level: conflicting changesets for one (actor,
    # version); never restored by transport success — cleared only by
    # the runtime's bounded verdict expiry or an identity renewal), or
    # "signed_equivocation" (a VERIFIED signed conflicting pair:
    # permanent, survives address moves and restarts, outranks all
    # other evidence)
    quarantined: bool = False
    quarantine_reason: str = ""

    def note_rtt(self, rtt_ms: float) -> None:
        if self.rtts is None:
            self.rtts = deque(maxlen=RTT_SAMPLES)
        self.rtts.append(rtt_ms)

    @property
    def rtt_ms(self) -> Optional[float]:
        if not self.rtts:
            return None
        return sum(self.rtts) / len(self.rtts)

    @property
    def is_ring0(self) -> bool:
        if self.quarantined:
            return False
        rtt = self.rtt_ms
        return rtt is not None and rtt < RING0_MAX_RTT_MS


class Members:
    """Thread-safe membership view (written by the SWIM loop, read by
    broadcast fanout and sync peer selection).

    ``clock`` sources every ``last_seen`` stamp (the injectable-clock
    seam, ``corrosion_tpu/clock.py``): under a virtual-time campaign
    member freshness ages on the event heap, not the wall."""

    def __init__(self, self_actor: bytes, clock=None):
        from corrosion_tpu.clock import SYSTEM_CLOCK

        self.self_actor = self_actor
        self._clock = clock or SYSTEM_CLOCK
        self._members: Dict[bytes, Member] = {}
        self._lock = threading.RLock()
        # alive() result cache, invalidated by membership/state
        # mutations: broadcast fanout samples call alive() per flush
        # and the O(N) rebuild dominates big-cluster flush rounds.
        # Callers receive the SHARED list and must not mutate it.
        self._alive_cache: Optional[List[Member]] = None

    def upsert(
        self,
        actor_id: bytes,
        addr: Tuple[str, int],
        state: MemberState = MemberState.ALIVE,
        incarnation: int = 0,
        cluster_id: int = 0,
    ) -> bool:
        """Merge a member record; SWIM override rules (higher incarnation
        wins; equal incarnation: down > suspect > alive).  Returns True if
        the record changed."""
        if actor_id == self.self_actor:
            return False
        rank = {MemberState.ALIVE: 0, MemberState.SUSPECT: 1, MemberState.DOWN: 2}
        with self._lock:
            m = self._members.get(actor_id)
            if m is None:
                self._members[actor_id] = Member(
                    actor_id=actor_id, addr=tuple(addr), state=state,
                    incarnation=incarnation, cluster_id=cluster_id,
                    last_seen=self._clock.monotonic(),
                )
                self._alive_cache = None
                return True
            if (incarnation, rank[state]) <= (m.incarnation, rank[m.state]):
                return False
            self._alive_cache = None
            if tuple(addr) != tuple(m.addr) \
                    and m.quarantine_reason not in _ACTOR_REASONS:
                # the peer moved (e.g. restarted on a fresh ephemeral
                # port): transport-level quarantine was evidence about
                # the OLD address, and the old breaker can never
                # half-open-succeed to clear it — start the new address
                # with a clean slate.  Equivocation evidence (signed or
                # not) is about the ACTOR, not the address: it
                # survives a move
                m.quarantined = False
                m.quarantine_reason = ""
            m.state = state
            m.incarnation = incarnation
            m.addr = tuple(addr)
            m.last_seen = self._clock.monotonic()
            return True

    def revive(self, actor_id: bytes) -> None:
        """Direct evidence (a probe ack) clears OUR suspicion locally.

        SWIM's incarnation rules only let a higher incarnation demote
        suspect→alive cluster-wide, but first-hand contact is stronger
        than hearsay for the local view — without this, one dropped ack
        excludes a healthy peer from sync forever."""
        with self._lock:
            m = self._members.get(actor_id)
            if m and m.state is MemberState.SUSPECT:
                m.state = MemberState.ALIVE
                m.last_seen = self._clock.monotonic()
                self._alive_cache = None

    def remove(self, actor_id: bytes) -> None:
        with self._lock:
            self._members.pop(actor_id, None)
            self._alive_cache = None

    def get(self, actor_id: bytes) -> Optional[Member]:
        with self._lock:
            return self._members.get(actor_id)

    def record_rtt(self, actor_id: bytes, rtt_ms: float) -> None:
        with self._lock:
            m = self._members.get(actor_id)
            if m:
                m.note_rtt(rtt_ms)
                m.last_seen = self._clock.monotonic()

    def update_sync_ts(self, actor_id: bytes, ts: float) -> None:
        with self._lock:
            m = self._members.get(actor_id)
            if m:
                m.last_sync_ts = ts

    def set_quarantined(self, actor_id: bytes, flag: bool,
                        reason: str = "breaker") -> None:
        """Quarantine verdict for one evidence class: ``True`` opens
        (deprioritize the peer and record the reason), ``False``
        restores — but only when the SAME evidence class quarantined
        it: a transport half-open success must not clear an
        equivocation verdict."""
        with self._lock:
            m = self._members.get(actor_id)
            if m:
                self._apply_quarantine(m, flag, reason)

    @staticmethod
    def _apply_quarantine(m: Member, flag: bool, reason: str) -> None:
        if flag:
            # stronger evidence sticks: a hostile actor whose transport
            # also flaps must stay marked hostile, and a PROVEN
            # (signed) equivocator must never be relabeled by anything
            if _REASON_RANK.get(reason, 0) \
                    >= _REASON_RANK.get(m.quarantine_reason, 0):
                m.quarantine_reason = reason
            m.quarantined = True
        elif m.quarantined and _restores(m.quarantine_reason, reason):
            m.quarantined = False
            m.quarantine_reason = ""

    def quarantine_by_addr(self, addr, flag: bool,
                           reason: str = "breaker") -> bool:
        """Same, keyed by gossip address (what the transport knows)."""
        addr = tuple(addr)
        with self._lock:
            for m in self._members.values():
                if tuple(m.addr) == addr:
                    self._apply_quarantine(m, flag, reason)
                    return True
        return False

    def alive(self) -> List[Member]:
        """Non-DOWN members.  The returned list is CACHED and shared
        between calls until the next membership/state mutation —
        read-only by contract (every in-tree caller filters or samples
        from it)."""
        with self._lock:
            cached = self._alive_cache
            if cached is None:
                cached = self._alive_cache = [
                    m for m in self._members.values()
                    if m.state is not MemberState.DOWN
                ]
            return cached

    def all(self) -> List[Member]:
        with self._lock:
            return list(self._members.values())

    def ring0(self) -> List[Member]:
        return [m for m in self.alive() if m.is_ring0]

    def sample(self, k: int, rng: Optional[random.Random] = None,
               ring0_first: bool = True,
               exclude: Optional[set] = None) -> List[Member]:
        """Broadcast fanout choice.

        Parity (``broadcast/mod.rs:586-702``): a *local* broadcast
        (``ring0_first=True``) goes to ALL ring0 members (<6 ms RTT tier,
        uncapped) plus a random sample of k non-ring0 peers; a rebroadcast
        is a uniform sample of k peers.  ``exclude`` mirrors the
        reference's per-payload ``sent_to`` set — a payload is never sent
        to the same peer twice across retransmission rounds."""
        rng = rng or random
        exclude = exclude or set()
        alive = [m for m in self.alive() if m.actor_id not in exclude]
        # breaker-quarantined peers are deprioritized like high-RTT
        # peers: never in ring0 (is_ring0 is False while quarantined),
        # and sampled only when the healthy pool can't fill k — they
        # stay reachable (half-open trials need traffic) but a flush
        # round prefers peers that are actually answering
        healthy = [m for m in alive if not m.quarantined]
        shunned = [m for m in alive if m.quarantined]

        def pick(pool, fallback, n):
            out = rng.sample(pool, min(len(pool), n))
            short = n - len(out)
            if short > 0 and fallback:
                out += rng.sample(fallback, min(len(fallback), short))
            return out

        if not ring0_first:
            if len(alive) <= k:
                return alive
            return pick(healthy, shunned, k)
        ring0 = [m for m in healthy if m.is_ring0]
        rest = [m for m in healthy if not m.is_ring0]
        picked = list(ring0)
        picked += pick(rest, shunned, k)
        return picked


# measured-topology export: RTT tier edges in ms.  Tier 1 is exactly
# the reference's ring0 (<6 ms); the rest double per tier (geo-RTT
# bands: metro, regional, continental, intercontinental); anything
# past the last edge lands in one final open tier.
DEFAULT_RTT_TIER_EDGES_MS: Tuple[float, ...] = (
    RING0_MAX_RTT_MS, 12.0, 24.0, 48.0, 96.0
)


def rtt_tier_of(rtt_ms: float,
                edges: Tuple[float, ...] = DEFAULT_RTT_TIER_EDGES_MS
                ) -> int:
    """1-based RTT tier of one mean RTT sample: the first edge the RTT
    falls under; ``len(edges) + 1`` beyond the last edge."""
    for t, edge in enumerate(edges, start=1):
        if rtt_ms < edge:
            return t
    return len(edges) + 1


def rtt_topology(members: "Members",
                 edges: Tuple[float, ...] = DEFAULT_RTT_TIER_EDGES_MS
                 ) -> Dict:
    """Export this node's ``Members`` RTT-ring tier distribution as
    measured-topology JSON — the capture path behind ``corro admin rtt
    dump`` and the vcluster capture helper.

    ``weights`` are per-tier MEMBER counts (each member placed by its
    ring mean ``rtt_ms``), trailing empty tiers trimmed — exactly the
    ``rtt_tier_weights`` the sim's ``measured_ring`` topology consumes
    (``bench.py --frontier --topology measured_ring``).  Members with
    no RTT samples yet are reported separately, not binned."""
    nodes = []
    counts = [0] * (len(edges) + 1)
    unsampled = 0
    for m in members.all():
        rtt = m.rtt_ms
        if rtt is None:
            unsampled += 1
            continue
        tier = rtt_tier_of(rtt, edges)
        counts[tier - 1] += 1
        nodes.append({
            "actor": m.actor_id.hex(),
            "rtt_ms": round(rtt, 3),
            "samples": len(m.rtts or ()),
            "tier": tier,
            "ring0": m.is_ring0,
        })
    last = 0
    for t, c in enumerate(counts, start=1):
        if c:
            last = t
    weights = counts[:last] if last else []
    return {
        "topology": "measured_ring",
        "tier_edges_ms": list(edges),
        "rtt_tiers": len(weights),
        "weights": weights,
        "members_sampled": len(nodes),
        "members_unsampled": unsampled,
        "nodes": nodes,
    }
