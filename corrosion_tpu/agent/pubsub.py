"""Reactive query layer: streaming subscriptions + table-level updates.

Parity: ``crates/corro-types/src/pubsub.rs`` (the Matcher engine —
incremental materialized views over SQL subscriptions, per-subscription
persistence, buffered candidate batching, ``QueryEvent`` streams) and
``updates.rs`` (table-level notify streams), served over HTTP by
``api/public/pubsub.rs`` / ``update.rs``.

Design differences (deliberate):

* table extraction uses sqlite's authorizer hook during prepare — the
  database is the SQL parser (the reference rewrites ASTs with
  ``sqlite3-parser``);
* incremental maintenance is pk-scoped like the reference's candidate
  rewrite (``pubsub.rs:602-737,1432-1707``), achieved through the same
  core move — every referenced table's primary key columns are added to
  the projection as hidden ``__corro_pk_<table>_<i>`` aliases — but via
  top-level text splicing + query nesting instead of full AST surgery.
  A change batch on table t evaluates ``SELECT * FROM (<rewritten>)
  WHERE (t's hidden pk cols) IN (VALUES ...candidates...)``: sqlite's
  subquery flattening pushes the predicate onto t's pk index, so the
  work is proportional to the candidate rows — including multi-table
  JOIN subscriptions, where each changed table scopes its own delta
  (the analogue of the reference's per-table temp-pk-table scoping).
  Result rows are identity-keyed by the joined pk tuple, yielding true
  ``update`` events.  Ineligible queries (aggregates, DISTINCT, LIMIT,
  subqueries, set ops, windows, self-joins) keep the
  re-evaluate-and-diff path (correct, not incremental);
* per-subscription state (sql, rows, change log) persists in its own
  sqlite file under ``subs_path`` and is restored on boot
  (``pubsub.rs:819-856`` parity).

Event wire format (matches the reference's ``TypedQueryEvent``):
  {"columns": [...]}            first frame of a snapshot
  {"row": [row_id, cells]}      snapshot row
  {"eoq": {"time": t, "change_id": id}}
  {"change": [kind, row_id, cells, change_id]}   kind: insert|update|delete
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import re
import sqlite3
import threading
import time
import uuid
from typing import Dict, List, Optional, Set, Tuple

from corrosion_tpu.agent.pack import jsonable_row, pack_values, unpack_values
from corrosion_tpu.types.changeset import ChangeV1

DEBOUNCE_S = 0.05
MAX_CHANGE_LOG = 100_000
# more candidate pks than this per round -> full refresh is cheaper
DELTA_MAX_PKS = 2048
# words whose presence means a row's content or membership can depend on
# OTHER rows: pk-scoped delta evaluation would be wrong, so such queries
# use full refresh.  Deliberately over-broad (a column merely NAMED
# "count" costs only the optimization, never correctness).
_GLOBAL_WORDS = frozenset(
    (
        "DISTINCT", "GROUP", "HAVING", "UNION", "INTERSECT", "EXCEPT",
        "LIMIT", "OFFSET", "OVER", "WITH",
        # aggregates
        "COUNT", "SUM", "AVG", "TOTAL", "MAX", "MIN", "GROUP_CONCAT",
        "STRING_AGG",
        # join forms the textual item parser doesn't model
        "USING", "NATURAL",
    )
)

# outer-join words disqualify the delta path outright: an outer join
# can TRANSITION a result row to its NULL-extended form when the inner
# side's match disappears, and a pk-IN scope on the inner table cannot
# see that new row (its pk columns are NULL there)
_OUTER_WORDS = frozenset(("LEFT", "RIGHT", "FULL", "OUTER"))
_ITEM_STOP_WORDS = frozenset(("ON", "WHERE", "ORDER", "AND", "OR"))


def _scan_top_level(sql: str):
    """Yield (index, char, depth) for chars outside string literals,
    with paren depth tracked."""
    depth = 0
    in_str: Optional[str] = None
    for i, ch in enumerate(sql):
        if in_str:
            if ch == in_str:
                in_str = None
            continue
        if ch in ("'", '"'):
            in_str = ch
            continue
        if ch == "(":
            depth += 1
            continue
        if ch == ")":
            depth -= 1
            continue
        yield i, ch, depth


def _top_level_word(sql: str, word: str, start: int = 0) -> int:
    """Index of the first depth-0 occurrence of ``word`` as a bare word
    outside strings, or -1."""
    up = sql.upper()
    w = word.upper()
    for i, ch, depth in _scan_top_level(sql):
        if depth != 0 or i < start:
            continue
        if up.startswith(w, i) and (i == 0 or not up[i - 1].isalnum()):
            end = i + len(w)
            if end == len(sql) or not (up[end].isalnum() or up[end] == "_"):
                return i
    return -1


def from_items(nsql: str) -> Optional[List[Tuple[str, str]]]:
    """Top-level from-items of a normalized single SELECT as
    ``(table, alias)`` pairs, or None when the shape is out of scope
    (subquery in FROM, USING joins, quoted exotica).  The textual
    counterpart of the reference's table extraction
    (``pubsub.rs:1813-2107``)."""
    fi = _top_level_word(nsql, "FROM")
    if fi < 0:
        return None
    end = len(nsql)
    for stop in ("WHERE", "ORDER", "GROUP", "LIMIT", "HAVING", "WINDOW"):
        si = _top_level_word(nsql, stop, fi + 4)
        if 0 <= si < end:
            end = si
    clause = nsql[fi + 4:end].strip()
    if "(" in clause:
        return None  # subquery or function in FROM
    if any(w in _OUTER_WORDS
           for w in re.findall(r"[A-Za-z_]+", clause.upper())):
        return None  # outer joins: see _OUTER_WORDS
    # split items on top-level commas and inner-JOIN connectors
    parts = re.split(
        r"(?:,|\b(?:INNER|CROSS)?\s*\bJOIN\b)",
        clause, flags=re.IGNORECASE,
    )
    items: List[Tuple[str, str]] = []
    for part in parts:
        # keep only the item itself (strip any ON condition)
        m = re.match(r"\s*(.*?)\s*(?:\bON\b.*)?$", part,
                     flags=re.IGNORECASE | re.DOTALL)
        piece = m.group(1) if m else part.strip()
        if not piece:
            continue
        toks = piece.replace('"', "").split()
        if not toks:
            return None
        table = toks[0]
        alias = table
        rest = [t for t in toks[1:] if t.upper() != "AS"]
        if rest:
            if len(rest) > 1 or rest[0].upper() in _ITEM_STOP_WORDS:
                return None
            alias = rest[0]
        if not re.fullmatch(r"\w+", table) or not re.fullmatch(
            r"\w+", alias
        ):
            return None
        items.append((table, alias))
    return items or None


def splice_pk_cols(nsql: str, items: List[Tuple[str, str]],
                   pk_cols: Dict[str, List[str]]) -> Tuple[str, int]:
    """Rewrite the SELECT to append every from-item's pk columns as
    hidden ``__corro_pk_<alias>_<i>`` aliases (the reference's
    ``__corro_pk`` projection tagging, ``pubsub.rs:602-737``).  Returns
    (rewritten sql, number of hidden columns)."""
    fi = _top_level_word(nsql, "FROM")
    extras = []
    for table, alias in items:
        for i, col in enumerate(pk_cols[table]):
            extras.append(
                f'"{alias}"."{col}" AS __corro_pk_{alias}_{i}'
            )
    return (
        nsql[:fi].rstrip() + ", " + ", ".join(extras) + " " + nsql[fi:],
        len(extras),
    )


def normalize_sql(sql: str) -> str:
    """Collapse whitespace OUTSIDE string literals only."""
    out = []
    in_str: Optional[str] = None
    ws = False
    for ch in sql.strip().rstrip(";").strip():
        if in_str:
            out.append(ch)
            if ch == in_str:
                in_str = None
            continue
        if ch in ("'", '"'):
            in_str = ch
            out.append(ch)
            ws = False
        elif ch.isspace():
            ws = True
        else:
            if ws and out:
                out.append(" ")
            ws = False
            out.append(ch)
    return "".join(out)


def tables_of_query(conn: sqlite3.Connection, sql: str) -> Set[str]:
    """Which tables does this SELECT read?  The authorizer sees every
    SQLITE_READ during prepare."""
    tables: Set[str] = set()

    def auth(action, arg1, arg2, dbname, trigger):
        if action == sqlite3.SQLITE_READ and arg1:
            tables.add(arg1)
        return sqlite3.SQLITE_OK

    conn.set_authorizer(auth)
    try:
        conn.execute(f"EXPLAIN {sql}")
    finally:
        conn.set_authorizer(None)
    return tables


class SubscriptionHandle:
    """One live subscription; many HTTP streams can attach."""

    def __init__(self, manager: "SubsManager", sub_id: str, sql: str,
                 columns: List[str], tables: Set[str], db_path: str):
        self.manager = manager
        self.id = sub_id
        self.sql = sql
        self.columns = columns
        self.tables = tables
        self.db_path = db_path
        # zero-receiver GC bookkeeping (pubsub.rs:131-227 parity)
        self.last_receiver_at = time.time()
        self._lock = threading.RLock()
        # row identity -> (row_id, cells); change log for catch-up
        self.rows: Dict[str, Tuple[int, list]] = {}
        self.last_row_id = 0
        self.last_change_id = 0
        self._closed = False
        self._streams: List[queue.Queue] = []
        # pk-scoped incremental evaluation (set by the manager when the
        # query qualifies): the rewritten query with hidden
        # __corro_pk_* columns, the from-items in projection order, the
        # hidden-column index ranges per table, and the identity index
        # (table, pk-hex) -> [identities]
        self.exec_sql: Optional[str] = None
        self.n_hidden = 0
        self.pk_items: Optional[List[Tuple[str, str]]] = None
        self.pk_idx: Dict[str, List[int]] = {}  # table -> exec col idx
        self.by_pk: Dict[Tuple[str, str], List[str]] = {}
        self.pk_of: Dict[str, Dict[str, str]] = {}  # identity -> hexes
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._db.executescript(
            """
CREATE TABLE IF NOT EXISTS meta (key TEXT PRIMARY KEY, value TEXT);
CREATE TABLE IF NOT EXISTS rows (
  identity TEXT PRIMARY KEY, row_id INTEGER NOT NULL, cells TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS changes (
  change_id INTEGER PRIMARY KEY, kind TEXT NOT NULL,
  row_id INTEGER NOT NULL, cells TEXT NOT NULL);
"""
        )
        have = {r[1] for r in self._db.execute("PRAGMA table_info(rows)")}
        if "pk" not in have:
            self._db.execute("ALTER TABLE rows ADD COLUMN pk TEXT")
        self._db.execute(
            "INSERT OR REPLACE INTO meta VALUES ('sql', ?)", (sql,)
        )
        self._db.commit()

    @property
    def incremental(self) -> bool:
        return self.pk_items is not None

    # -- persistence -----------------------------------------------------

    def _restore(self) -> bool:
        # read the change-log high-water mark FIRST: even with an empty
        # materialized set (all rows deleted pre-restart), new change ids
        # must continue after the persisted log or they collide
        last = self._db.execute("SELECT MAX(change_id) FROM changes").fetchone()
        self.last_change_id = last[0] or 0
        rows = self._db.execute(
            "SELECT identity, row_id, cells, pk FROM rows"
        ).fetchall()
        if self.incremental and rows and any(pk is None for *_r, pk in rows):
            # state persisted under the old hash-keyed identity scheme:
            # silently re-key (a diff against the restored identities
            # would read as a full-table delete+insert storm).  The old
            # change log references the now-dead row_ids, so truncate it
            # too — _can_catch_up then fails and resuming clients get a
            # fresh snapshot instead of events against unknown rids
            self._db.execute("DELETE FROM rows")
            self._db.execute("DELETE FROM changes")
            self._db.commit()
            self.last_row_id = max((r[1] for r in rows), default=0)
            self.refresh(initial=True)
            return True
        for identity, row_id, cells, pk in rows:
            self.rows[identity] = (row_id, json.loads(cells))
            self.last_row_id = max(self.last_row_id, row_id)
            if pk is not None and self.incremental:
                if pk.startswith("{"):
                    hexes = json.loads(pk)
                else:  # legacy single-table plain hex
                    hexes = {self.pk_items[0][0]: pk}
                self.pk_of[identity] = hexes
                for t, h in hexes.items():
                    self.by_pk.setdefault((t, h), []).append(identity)
        return bool(rows) or self.last_change_id > 0

    def _persist_rows(self, upserts, deletes, pks=None) -> None:
        def encode_pk(i):
            hexes = (pks or {}).get(i)
            if not hexes:
                return None
            if len(hexes) == 1:
                return next(iter(hexes.values()))  # legacy plain hex
            return json.dumps(hexes, sort_keys=True)

        self._db.executemany(
            "INSERT OR REPLACE INTO rows (identity, row_id, cells, pk) "
            "VALUES (?, ?, ?, ?)",
            [
                (i, rid, json.dumps(c), encode_pk(i))
                for i, (rid, c) in upserts.items()
            ],
        )
        self._db.executemany(
            "DELETE FROM rows WHERE identity=?", [(i,) for i in deletes]
        )

    def _persist_change(self, change_id, kind, row_id, cells) -> None:
        self._db.execute(
            "INSERT INTO changes (change_id, kind, row_id, cells) "
            "VALUES (?, ?, ?, ?)",
            (change_id, kind, row_id, json.dumps(cells)),
        )
        if change_id % 1000 == 0:
            self._db.execute(
                "DELETE FROM changes WHERE change_id <= ?",
                (change_id - MAX_CHANGE_LOG,),
            )

    # -- evaluation ------------------------------------------------------

    @staticmethod
    def _identity(cells: list, occurrence: int) -> str:
        """Row identity = content hash + occurrence index, so duplicate
        result rows keep multiset cardinality (a projection can make rows
        non-distinct)."""
        h = hashlib.blake2s(
            json.dumps(cells, sort_keys=True, default=str).encode(),
            digest_size=16,
        ).hexdigest()
        return f"{h}:{occurrence}"

    def _pk_keyed(self, rows):
        """identity -> user cells and identity -> {table: pk-hex} for an
        exec-query result set: identities key on the joined tuple of
        every from-item's hidden pk columns (stable across evaluations
        — true update events; the single-table identity is the plain
        ``hex:occ`` the old format used, so persisted state carries
        over)."""
        new_ids: Dict[str, list] = {}
        pks_of: Dict[str, Dict[str, str]] = {}
        counts: Dict[str, int] = {}
        n_user = None
        for r in rows:
            if n_user is None:
                n_user = len(r) - self.n_hidden
            cells = jsonable_row(r[:n_user])
            hexes = {
                t: pack_values([r[i] for i in self.pk_idx[t]]).hex()
                for t, _a in self.pk_items
            }
            joined = "|".join(hexes[t] for t, _a in self.pk_items)
            occ = counts.get(joined, 0)
            counts[joined] = occ + 1
            identity = f"{joined}:{occ}"
            new_ids[identity] = cells
            pks_of[identity] = hexes
        return new_ids, pks_of

    def _apply_diff(self, new_ids, pks_of, scope_old, initial,
                    cand_keys=None) -> None:
        """Diff ``new_ids`` against ``scope_old`` (the materialized rows
        the evaluation could have produced), persist, emit events.
        ``cand_keys``: the (table, pk-hex) scope keys of a delta round,
        None for a full refresh.  Caller holds ``self._lock``."""
        upserts: Dict[str, Tuple[int, list]] = {}
        events = []
        for identity, cells in new_ids.items():
            old = scope_old.get(identity)
            if old is None:
                self.last_row_id += 1
                rid = self.last_row_id
                upserts[identity] = (rid, cells)
                if not initial:
                    self.last_change_id += 1
                    events.append(("insert", rid, cells, self.last_change_id))
            elif old[1] != cells:
                rid = old[0]
                upserts[identity] = (rid, cells)
                if not initial:
                    self.last_change_id += 1
                    events.append(("update", rid, cells, self.last_change_id))
        deletes = []
        for identity, (rid, cells) in scope_old.items():
            if identity not in new_ids:
                deletes.append(identity)
                if not initial:
                    self.last_change_id += 1
                    events.append(("delete", rid, cells, self.last_change_id))
        self.rows.update(upserts)
        for i in deletes:
            self.rows.pop(i, None)
        if self.incremental:
            if cand_keys is None:
                self.by_pk = {}
                self.pk_of = {}
            else:
                for i in deletes:
                    # drop the row from EVERY table's index (a delta
                    # scoped on one table deletes rows the other
                    # tables' entries still reference); prune emptied
                    # keys or delete churn grows by_pk without bound
                    for t, h in self.pk_of.pop(i, {}).items():
                        lst = self.by_pk.get((t, h))
                        if lst and i in lst:
                            lst.remove(i)
                        if lst is not None and not lst:
                            del self.by_pk[(t, h)]
            for identity, hexes in pks_of.items():
                self.pk_of[identity] = hexes
                for t, h in hexes.items():
                    lst = self.by_pk.setdefault((t, h), [])
                    if identity not in lst:
                        lst.append(identity)
        self._persist_rows(upserts, deletes, pks_of)
        for kind, rid, cells, cid in events:
            self._persist_change(cid, kind, rid, cells)
        self._db.commit()
        for kind, rid, cells, cid in events:
            self._fanout({"change": [kind, rid, cells, cid]})

    def refresh(self, initial: bool = False) -> None:
        """Re-evaluate the whole query and emit diff events."""
        if self.incremental:
            cols, rows = self.manager.agent.storage.read_query(
                self.exec_sql
            )
            with self._lock:
                self.columns = cols[: len(cols) - self.n_hidden]
                new_ids, pks_of = self._pk_keyed(rows)
                self._apply_diff(new_ids, pks_of, dict(self.rows), initial)
            return
        cols, rows = self.manager.agent.storage.read_query(self.sql)
        with self._lock:
            self.columns = cols
            new_ids = {}
            counts: Dict[str, int] = {}
            for r in rows:
                cells = jsonable_row(r)
                key = json.dumps(cells, sort_keys=True, default=str)
                occ = counts.get(key, 0)
                counts[key] = occ + 1
                new_ids[self._identity(cells, occ)] = cells
            self._apply_diff(new_ids, {}, dict(self.rows), initial)

    def delta(self, table_pks: Dict[str, Set[bytes]]) -> None:
        """Pk-scoped incremental evaluation (the candidate path,
        ``pubsub.rs:1432-1707``): work proportional to the candidate
        rows, not the table.  Each changed table scopes its own
        evaluation through its hidden pk columns — the join analogue of
        the reference's per-table temp-pk-table re-evaluation."""
        for table, pks in table_pks.items():
            if not pks or table not in self.pk_idx:
                continue
            idx = self.pk_idx[table]
            cols_sql = ", ".join(
                f"__corro_pk_{self._alias_of(table)}_{i}"
                for i in range(len(idx))
            )
            row_ph = "(" + ", ".join("?" for _ in idx) + ")"
            values = ", ".join(row_ph for _ in pks)
            sql = (
                f"SELECT * FROM ({self.exec_sql}) "
                f"WHERE ({cols_sql}) IN (VALUES {values})"
            )
            params = [v for pk in pks for v in unpack_values(pk)]
            _, rows = self.manager.agent.storage.read_query(sql, params)
            cand_keys = {(table, pk.hex()) for pk in pks}
            with self._lock:
                new_ids, pks_of = self._pk_keyed(rows)
                scope_old = {
                    i: self.rows[i]
                    for k in cand_keys
                    for i in self.by_pk.get(k, [])
                    if i in self.rows
                }
                self._apply_diff(
                    new_ids, pks_of, scope_old, initial=False,
                    cand_keys=cand_keys,
                )

    def _alias_of(self, table: str) -> str:
        for t, a in self.pk_items or ():
            if t == table:
                return a
        raise KeyError(table)

    def _fanout(self, event: dict) -> None:
        self.manager.agent.metrics.counter("corro_subs_events_total")
        for q in list(self._streams):
            try:
                q.put_nowait(event)
            except queue.Full:
                pass

    # -- streaming -------------------------------------------------------

    def stream(self, from_change_id: Optional[int] = None):
        """Generator of events: snapshot (or catch-up) then live tail."""
        q: queue.Queue = queue.Queue(maxsize=4096)
        with self._lock:
            self._streams.append(q)
            if from_change_id is not None and self._can_catch_up(from_change_id):
                backlog = [
                    {"change": [kind, rid, json.loads(cells), cid]}
                    for cid, kind, rid, cells in self._db.execute(
                        "SELECT change_id, kind, row_id, cells FROM changes "
                        "WHERE change_id > ? ORDER BY change_id",
                        (from_change_id,),
                    )
                ]
            else:
                backlog = [{"columns": self.columns}]
                backlog += [
                    {"row": [rid, cells]}
                    for rid, cells in sorted(self.rows.values())
                ]
                backlog.append(
                    {"eoq": {"time": 0.0, "change_id": self.last_change_id}}
                )
        try:
            for ev in backlog:
                yield ev
            while not self._closed:
                try:
                    ev = q.get(timeout=5.0)
                except queue.Empty:
                    continue
                if ev is None:  # close sentinel
                    return
                yield ev
        finally:
            with self._lock:
                if q in self._streams:
                    self._streams.remove(q)
                self.last_receiver_at = time.time()

    def unsubscribe_stream(self) -> None:
        pass  # generator finally-block handles removal

    def _can_catch_up(self, from_change_id: int) -> bool:
        row = self._db.execute("SELECT MIN(change_id) FROM changes").fetchone()
        lo = row[0]
        return lo is not None and from_change_id >= lo - 1

    def close(self) -> None:
        self._closed = True
        for q in list(self._streams):
            try:
                q.put_nowait(None)  # wake + end attached streams
            except queue.Full:
                pass
        self._db.close()


class SubsManager:
    """Owns all subscriptions + the table-update notify streams."""

    def __init__(self, agent, subs_path: Optional[str] = None):
        self.agent = agent
        self.subs_path = subs_path or os.path.join(
            os.path.dirname(agent.config.db_path) or ".", "subs"
        )
        os.makedirs(self.subs_path, exist_ok=True)
        self._subs: Dict[str, SubscriptionHandle] = {}
        self._by_sql: Dict[str, str] = {}
        self._lock = threading.RLock()
        self._pending: Set[str] = set()
        self._pending_pks: Dict[str, Dict[str, Set[bytes]]] = {}
        self._draining = False
        self._worker_died = False
        self._update_streams: Dict[str, List[queue.Queue]] = {}
        self._wake = threading.Event()
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()
        agent.on_change = self.on_change
        self._restore()

    # -- lifecycle -------------------------------------------------------

    def _restore(self) -> None:
        for fn in os.listdir(self.subs_path):
            if not fn.endswith(".db"):
                continue
            sub_id = fn[:-3]
            path = os.path.join(self.subs_path, fn)
            try:
                db = sqlite3.connect(path)
                row = db.execute(
                    "SELECT value FROM meta WHERE key='sql'"
                ).fetchone()
                db.close()
                if not row:
                    continue
                sql = row[0]
                handle = self._create(sub_id, sql)
                if not handle._restore():
                    handle.refresh(initial=True)
                else:
                    # state may have moved while we were down
                    handle.refresh(initial=False)
            except sqlite3.Error:
                continue

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        self._worker.join(timeout=2)
        with self._lock:
            for h in self._subs.values():
                h.close()

    # -- subscription management ----------------------------------------

    def subscribe(self, sql: str) -> SubscriptionHandle:
        nsql = normalize_sql(sql)
        with self._lock:
            sub_id = self._by_sql.get(nsql)
            if sub_id:
                h = self._subs[sub_id]
                # hand-out counts as receiver activity: the caller gets
                # a full GC horizon to attach its stream
                h.last_receiver_at = time.time()
                return h
            # create while holding the lock: two racing subscribers with
            # the same new SQL must share one subscription
            handle = self._create(str(uuid.uuid4()), nsql)
        handle.refresh(initial=True)
        return handle

    def _create(self, sub_id: str, nsql: str) -> SubscriptionHandle:
        from corrosion_tpu.agent.storage import register_udfs

        scratch = sqlite3.connect(self.agent.config.db_path)
        register_udfs(scratch)
        try:
            tables = tables_of_query(scratch, nsql)
        finally:
            scratch.close()
        raw_tables = set(tables)
        crr = set(self.agent.storage.tables)
        tables &= crr
        if not tables:
            raise ValueError("query does not read any replicated table")
        # columns are filled by the first refresh (probing with an extra
        # LIMIT clause would break queries that already have one)
        handle = SubscriptionHandle(
            self, sub_id, nsql, [], tables,
            os.path.join(self.subs_path, f"{sub_id}.db"),
        )
        self._detect_incremental(handle, nsql, tables, raw_tables)
        with self._lock:
            self._subs[sub_id] = handle
            self._by_sql[nsql] = sub_id
        return handle

    def _detect_incremental(self, handle: SubscriptionHandle, nsql: str,
                            tables: Set[str],
                            raw_tables: Set[str]) -> None:
        """Qualify a query for pk-scoped delta evaluation by appending
        hidden ``__corro_pk_*`` columns for every from-item (the
        reference's projection tagging, ``pubsub.rs:602-737``).
        Requirements (conservative — a miss costs the optimization,
        never correctness):

        * a single top-level SELECT (no subqueries — a correlated or
          same-table subquery would make rows interdependent), no
          global operator / aggregate / set op / window / LIMIT;
        * a from-clause of inner-joined (plain/INNER/CROSS/comma)
          replicated tables, each referenced once (no self-joins; no
          outer joins — a row transitioning to its NULL-extended form
          escapes the inner table's pk filter; no local lookup tables —
          their changes aren't notified);
        * the per-table delta filter provably reaches that table's
          index (EXPLAIN QUERY PLAN shows a SEARCH, never a SCAN, of
          the scoped table).
        """
        up = nsql.upper()
        words = re.findall(r"[A-Za-z_]+", up)
        if words.count("SELECT") != 1:
            return
        if any(w in _GLOBAL_WORDS for w in words):
            return
        items = from_items(nsql)
        if not items:
            return
        names = [t for t, _a in items]
        if len(set(names)) != len(names):
            return  # self-join
        if set(names) != raw_tables or not set(names) <= set(tables):
            # every table the query reads must be a replicated from-item
            # (raw_tables catches local lookup tables, whose changes
            # would never re-trigger evaluation)
            return
        infos = {}
        for t in names:
            info = self.agent.storage._tables.get(t)
            if info is None:
                return
            infos[t] = list(info.pk_cols)
        try:
            exec_sql, n_hidden = splice_pk_cols(nsql, items, infos)
            cols, _ = self.agent.storage.read_query(
                f"SELECT * FROM ({exec_sql}) LIMIT 0"
            )
        except (sqlite3.Error, ValueError):
            return
        # hidden-column projection indices per table
        pk_idx: Dict[str, List[int]] = {}
        pos = len(cols) - n_hidden
        for t, _a in items:
            pk_idx[t] = list(range(pos, pos + len(infos[t])))
            pos += len(infos[t])
        # every delta plan must reach EVERY from-item's index: a sibling
        # with no index on its join column would SCAN once per changed
        # row, costing O(sibling) per delta — worse than the full
        # refresh this path replaces (plans name the alias when used)
        for t, a in items:
            idx = pk_idx[t]
            cols_sql = ", ".join(
                f"__corro_pk_{a}_{i}" for i in range(len(idx))
            )
            row_ph = "(" + ", ".join("?" for _ in idx) + ")"
            try:
                _, plan = self.agent.storage.read_query(
                    "EXPLAIN QUERY PLAN SELECT * FROM "
                    f"({exec_sql}) WHERE ({cols_sql}) IN "
                    f"(VALUES {row_ph})",
                    [None] * len(idx),
                )
            except sqlite3.Error:
                return
            plan_text = " ".join(str(c) for row in plan for c in row)

            # word-boundary matching: table "item" must not match the
            # plan line of its sibling "items" in the same join plan
            def in_plan(op, name):
                return re.search(
                    rf"{op} {re.escape(name)}\b", plan_text
                ) is not None

            for t2, a2 in items:
                searched = in_plan("SEARCH", a2) or in_plan("SEARCH", t2)
                if not searched or in_plan("SCAN", a2):
                    return
        handle.exec_sql = exec_sql
        handle.n_hidden = n_hidden
        handle.pk_items = items
        handle.pk_idx = pk_idx

    def get(self, sub_id: str) -> Optional[SubscriptionHandle]:
        with self._lock:
            h = self._subs.get(sub_id)
            if h is not None:
                h.last_receiver_at = time.time()  # see subscribe()
            return h

    def list(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "id": h.id,
                    "sql": h.sql,
                    "tables": sorted(h.tables),
                    "rows": len(h.rows),
                    "last_change_id": h.last_change_id,
                    "incremental": h.incremental,
                    "receivers": len(h._streams),
                }
                for h in self._subs.values()
            ]

    # -- change intake ---------------------------------------------------

    def on_change(self, cv: ChangeV1) -> None:
        """Called by the agent for every local commit + applied remote
        changeset (``match_changes`` parity)."""
        cs = cv.changeset
        touched: Dict[str, List] = {}
        for ch in cs.changes:
            touched.setdefault(ch.table, []).append(ch)
        with self._lock:
            for h in self._subs.values():
                if h.incremental:
                    hit = [t for t, _a in h.pk_items if t in touched]
                    if hit:
                        per = self._pending_pks.setdefault(h.id, {})
                        for t in hit:
                            per.setdefault(t, set()).update(
                                ch.pk for ch in touched[t]
                            )
                elif any(t in h.tables for t in touched):
                    self._pending.add(h.id)
        for table, chs in touched.items():
            self._notify_updates(table, chs)
        if touched:
            self._wake.set()

    SUB_GC_S = 120.0  # drop subs with no receivers this long (pubsub.rs GC)
    GC_SWEEP_S = 5.0

    def _run(self) -> None:
        try:
            self._run_inner()
        except BaseException:
            # a dead worker must fail idle() loudly, not hang it
            # (_draining stuck) or lie (popped batch never processed)
            self._worker_died = True
            raise

    def _run_inner(self) -> None:
        last_gc = time.monotonic()
        while not self._closed:
            woke = self._wake.wait(timeout=self.GC_SWEEP_S)
            if self._closed:
                return
            # sweep on a deadline, NOT only when idle: a node with
            # steady write traffic never times the wait out
            if time.monotonic() - last_gc >= self.GC_SWEEP_S:
                self._gc_idle_subs()
                last_gc = time.monotonic()
            if not woke:
                continue
            time.sleep(DEBOUNCE_S)  # batch candidates
            self._wake.clear()
            with self._lock:
                pending, self._pending = self._pending, set()
                pending_pks, self._pending_pks = self._pending_pks, {}
                # popped-but-unprocessed work keeps idle() false: the
                # sets alone go empty the instant a round is claimed,
                # long before its refresh/delta SQL has finished
                self._draining = bool(pending or pending_pks)
            try:
                self._drain_round(pending, pending_pks)
            finally:
                with self._lock:
                    self._draining = False

    def _drain_round(
        self, pending: Set[str],
        pending_pks: Dict[str, Dict[str, Set[bytes]]],
    ) -> None:
        """Process one popped batch of candidate work."""
        for sub_id, table_pks in pending_pks.items():
            if sub_id in pending:
                continue  # a full refresh covers the candidates
            h = self._subs.get(sub_id)
            if h is None:
                continue
            # the delta path needs the projection (first refresh) and
            # loses to a full pass beyond DELTA_MAX_PKS candidates
            total = sum(len(p) for p in table_pks.values())
            if not h.columns or total > DELTA_MAX_PKS:
                pending.add(sub_id)
                continue
            try:
                h.delta(table_pks)
            except sqlite3.Error:
                # correct but expensive; counted so a systemic
                # cause (e.g. busy storms) is visible in metrics
                self.agent.metrics.counter(
                    "corro_subs_delta_fallbacks_total"
                )
                pending.add(sub_id)  # fall back to a full pass
        with self._lock:
            handles = [self._subs[i] for i in pending if i in self._subs]
        for h in handles:
            try:
                h.refresh()
            except sqlite3.Error:
                pass

    def idle(self) -> bool:
        """True when no candidate work is queued OR in flight — the
        condition tests must wait on before measuring delta cost.
        Raises if the worker died: neither a hang (flag stuck) nor a
        silent True (batch never processed) is an acceptable answer."""
        if self._worker_died:
            raise RuntimeError("subscription worker thread died")
        with self._lock:
            return not (
                self._pending or self._pending_pks or self._draining
            )

    def _gc_idle_subs(self) -> None:
        """Drop subscriptions nobody has streamed from in SUB_GC_S
        (``public/pubsub.rs:131-227``: matchers with zero receivers are
        garbage-collected after 120 s; a later identical subscribe
        simply recreates the state from a fresh snapshot)."""
        now = time.time()
        with self._lock:
            dead = [
                h for h in self._subs.values()
                if not h._streams and now - h.last_receiver_at > self.SUB_GC_S
            ]
            for h in dead:
                self._subs.pop(h.id, None)
                self._by_sql.pop(h.sql, None)
        for h in dead:
            h.close()
            try:
                os.unlink(h.db_path)
            except OSError:
                pass
        if dead:
            self.agent.metrics.counter("corro_subs_gcd_total", len(dead))
        self.agent.metrics.gauge("corro_subs_active", len(self._subs))

    # -- table-level updates (updates.rs parity) -------------------------

    def table_updates(self, table: str):
        """Generator of {"change": [kind, pk_cells]} events for one table."""
        q: queue.Queue = queue.Queue(maxsize=4096)
        self._update_streams.setdefault(table, []).append(q)
        try:
            while True:
                try:
                    yield q.get(timeout=30.0)
                except queue.Empty:
                    continue
        finally:
            self._update_streams.get(table, []).remove(q)

    def _notify_updates(self, table: str, changes: List) -> None:
        streams = self._update_streams.get(table)
        if not streams:
            return
        seen_pks = set()
        for ch in changes:
            if ch.pk in seen_pks:
                continue
            seen_pks.add(ch.pk)
            # cl parity: even causal length means the row is deleted
            kind = "delete" if ch.cl % 2 == 0 else "upsert"
            cells = jsonable_row(unpack_values(ch.pk))
            for q in list(streams):
                try:
                    q.put_nowait({"change": [kind, cells]})
                except queue.Full:
                    pass

