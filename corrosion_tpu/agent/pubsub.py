"""Reactive query layer: streaming subscriptions + table-level updates.

Parity: ``crates/corro-types/src/pubsub.rs`` (the Matcher engine —
incremental materialized views over SQL subscriptions, per-subscription
persistence, buffered candidate batching, ``QueryEvent`` streams) and
``updates.rs`` (table-level notify streams), served over HTTP by
``api/public/pubsub.rs`` / ``update.rs``.

Design differences (deliberate):

* table extraction uses sqlite's authorizer hook during prepare — the
  database is the SQL parser (the reference rewrites ASTs with
  ``sqlite3-parser``);
* incremental maintenance re-evaluates the subscription query on the
  read-only connection and diffs against the previous materialized rows
  (keyed by row identity), batched behind a short debounce window — the
  reference's per-table candidate rewrite is an optimization of the same
  observable behavior, and can slot in later without changing events;
* per-subscription state (sql, rows, change log) persists in its own
  sqlite file under ``subs_path`` and is restored on boot
  (``pubsub.rs:819-856`` parity).

Event wire format (matches the reference's ``TypedQueryEvent``):
  {"columns": [...]}            first frame of a snapshot
  {"row": [row_id, cells]}      snapshot row
  {"eoq": {"time": t, "change_id": id}}
  {"change": [kind, row_id, cells, change_id]}   kind: insert|update|delete
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import sqlite3
import threading
import time
import uuid
from typing import Dict, Iterable, List, Optional, Set, Tuple

from corrosion_tpu.agent.pack import jsonable_row, unpack_values
from corrosion_tpu.types.change import SENTINEL_CID
from corrosion_tpu.types.changeset import ChangeV1

DEBOUNCE_S = 0.05
MAX_CHANGE_LOG = 100_000


def normalize_sql(sql: str) -> str:
    """Collapse whitespace OUTSIDE string literals only."""
    out = []
    in_str: Optional[str] = None
    ws = False
    for ch in sql.strip().rstrip(";").strip():
        if in_str:
            out.append(ch)
            if ch == in_str:
                in_str = None
            continue
        if ch in ("'", '"'):
            in_str = ch
            out.append(ch)
            ws = False
        elif ch.isspace():
            ws = True
        else:
            if ws and out:
                out.append(" ")
            ws = False
            out.append(ch)
    return "".join(out)


def tables_of_query(conn: sqlite3.Connection, sql: str) -> Set[str]:
    """Which tables does this SELECT read?  The authorizer sees every
    SQLITE_READ during prepare."""
    tables: Set[str] = set()

    def auth(action, arg1, arg2, dbname, trigger):
        if action == sqlite3.SQLITE_READ and arg1:
            tables.add(arg1)
        return sqlite3.SQLITE_OK

    conn.set_authorizer(auth)
    try:
        conn.execute(f"EXPLAIN {sql}")
    finally:
        conn.set_authorizer(None)
    return tables


class SubscriptionHandle:
    """One live subscription; many HTTP streams can attach."""

    def __init__(self, manager: "SubsManager", sub_id: str, sql: str,
                 columns: List[str], tables: Set[str], db_path: str):
        self.manager = manager
        self.id = sub_id
        self.sql = sql
        self.columns = columns
        self.tables = tables
        self.db_path = db_path
        self._lock = threading.RLock()
        # row identity -> (row_id, cells); change log for catch-up
        self.rows: Dict[str, Tuple[int, list]] = {}
        self.last_row_id = 0
        self.last_change_id = 0
        self._closed = False
        self._streams: List[queue.Queue] = []
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._db.executescript(
            """
CREATE TABLE IF NOT EXISTS meta (key TEXT PRIMARY KEY, value TEXT);
CREATE TABLE IF NOT EXISTS rows (
  identity TEXT PRIMARY KEY, row_id INTEGER NOT NULL, cells TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS changes (
  change_id INTEGER PRIMARY KEY, kind TEXT NOT NULL,
  row_id INTEGER NOT NULL, cells TEXT NOT NULL);
"""
        )
        self._db.execute(
            "INSERT OR REPLACE INTO meta VALUES ('sql', ?)", (sql,)
        )
        self._db.commit()

    # -- persistence -----------------------------------------------------

    def _restore(self) -> bool:
        # read the change-log high-water mark FIRST: even with an empty
        # materialized set (all rows deleted pre-restart), new change ids
        # must continue after the persisted log or they collide
        last = self._db.execute("SELECT MAX(change_id) FROM changes").fetchone()
        self.last_change_id = last[0] or 0
        rows = self._db.execute(
            "SELECT identity, row_id, cells FROM rows"
        ).fetchall()
        for identity, row_id, cells in rows:
            self.rows[identity] = (row_id, json.loads(cells))
            self.last_row_id = max(self.last_row_id, row_id)
        return bool(rows) or self.last_change_id > 0

    def _persist_rows(self, upserts, deletes) -> None:
        self._db.executemany(
            "INSERT OR REPLACE INTO rows (identity, row_id, cells) "
            "VALUES (?, ?, ?)",
            [(i, rid, json.dumps(c)) for i, (rid, c) in upserts.items()],
        )
        self._db.executemany(
            "DELETE FROM rows WHERE identity=?", [(i,) for i in deletes]
        )

    def _persist_change(self, change_id, kind, row_id, cells) -> None:
        self._db.execute(
            "INSERT INTO changes (change_id, kind, row_id, cells) "
            "VALUES (?, ?, ?, ?)",
            (change_id, kind, row_id, json.dumps(cells)),
        )
        if change_id % 1000 == 0:
            self._db.execute(
                "DELETE FROM changes WHERE change_id <= ?",
                (change_id - MAX_CHANGE_LOG,),
            )

    # -- evaluation ------------------------------------------------------

    @staticmethod
    def _identity(cells: list, occurrence: int) -> str:
        """Row identity = content hash + occurrence index, so duplicate
        result rows keep multiset cardinality (a projection can make rows
        non-distinct)."""
        h = hashlib.blake2s(
            json.dumps(cells, sort_keys=True, default=str).encode(),
            digest_size=16,
        ).hexdigest()
        return f"{h}:{occurrence}"

    def refresh(self, initial: bool = False) -> None:
        """Re-evaluate the query and emit diff events."""
        cols, rows = self.manager.agent.storage.read_query(self.sql)
        with self._lock:
            self.columns = cols
            new_ids: Dict[str, list] = {}
            counts: Dict[str, int] = {}
            for r in rows:
                cells = jsonable_row(r)
                key = json.dumps(cells, sort_keys=True, default=str)
                occ = counts.get(key, 0)
                counts[key] = occ + 1
                new_ids[self._identity(cells, occ)] = cells
            old = self.rows
            upserts: Dict[str, Tuple[int, list]] = {}
            events = []
            for identity, cells in new_ids.items():
                if identity not in old:
                    self.last_row_id += 1
                    rid = self.last_row_id
                    upserts[identity] = (rid, cells)
                    if not initial:
                        self.last_change_id += 1
                        events.append(
                            ("insert", rid, cells, self.last_change_id)
                        )
            deletes = []
            for identity, (rid, cells) in old.items():
                if identity not in new_ids:
                    deletes.append(identity)
                    if not initial:
                        self.last_change_id += 1
                        events.append(
                            ("delete", rid, cells, self.last_change_id)
                        )
            old.update(upserts)
            for i in deletes:
                del old[i]
            self._persist_rows(upserts, deletes)
            for kind, rid, cells, cid in events:
                self._persist_change(cid, kind, rid, cells)
            self._db.commit()
            for kind, rid, cells, cid in events:
                self._fanout({"change": [kind, rid, cells, cid]})

    def _fanout(self, event: dict) -> None:
        for q in list(self._streams):
            try:
                q.put_nowait(event)
            except queue.Full:
                pass

    # -- streaming -------------------------------------------------------

    def stream(self, from_change_id: Optional[int] = None):
        """Generator of events: snapshot (or catch-up) then live tail."""
        q: queue.Queue = queue.Queue(maxsize=4096)
        with self._lock:
            self._streams.append(q)
            if from_change_id is not None and self._can_catch_up(from_change_id):
                backlog = [
                    {"change": [kind, rid, json.loads(cells), cid]}
                    for cid, kind, rid, cells in self._db.execute(
                        "SELECT change_id, kind, row_id, cells FROM changes "
                        "WHERE change_id > ? ORDER BY change_id",
                        (from_change_id,),
                    )
                ]
            else:
                backlog = [{"columns": self.columns}]
                backlog += [
                    {"row": [rid, cells]}
                    for rid, cells in sorted(self.rows.values())
                ]
                backlog.append(
                    {"eoq": {"time": 0.0, "change_id": self.last_change_id}}
                )
        try:
            for ev in backlog:
                yield ev
            while not self._closed:
                try:
                    ev = q.get(timeout=5.0)
                except queue.Empty:
                    continue
                if ev is None:  # close sentinel
                    return
                yield ev
        finally:
            with self._lock:
                if q in self._streams:
                    self._streams.remove(q)

    def unsubscribe_stream(self) -> None:
        pass  # generator finally-block handles removal

    def _can_catch_up(self, from_change_id: int) -> bool:
        row = self._db.execute("SELECT MIN(change_id) FROM changes").fetchone()
        lo = row[0]
        return lo is not None and from_change_id >= lo - 1

    def close(self) -> None:
        self._closed = True
        for q in list(self._streams):
            try:
                q.put_nowait(None)  # wake + end attached streams
            except queue.Full:
                pass
        self._db.close()


class SubsManager:
    """Owns all subscriptions + the table-update notify streams."""

    def __init__(self, agent, subs_path: Optional[str] = None):
        self.agent = agent
        self.subs_path = subs_path or os.path.join(
            os.path.dirname(agent.config.db_path) or ".", "subs"
        )
        os.makedirs(self.subs_path, exist_ok=True)
        self._subs: Dict[str, SubscriptionHandle] = {}
        self._by_sql: Dict[str, str] = {}
        self._lock = threading.RLock()
        self._pending: Set[str] = set()
        self._update_streams: Dict[str, List[queue.Queue]] = {}
        self._wake = threading.Event()
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()
        agent.on_change = self.on_change
        self._restore()

    # -- lifecycle -------------------------------------------------------

    def _restore(self) -> None:
        for fn in os.listdir(self.subs_path):
            if not fn.endswith(".db"):
                continue
            sub_id = fn[:-3]
            path = os.path.join(self.subs_path, fn)
            try:
                db = sqlite3.connect(path)
                row = db.execute(
                    "SELECT value FROM meta WHERE key='sql'"
                ).fetchone()
                db.close()
                if not row:
                    continue
                sql = row[0]
                handle = self._create(sub_id, sql)
                if not handle._restore():
                    handle.refresh(initial=True)
                else:
                    # state may have moved while we were down
                    handle.refresh(initial=False)
            except sqlite3.Error:
                continue

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        self._worker.join(timeout=2)
        with self._lock:
            for h in self._subs.values():
                h.close()

    # -- subscription management ----------------------------------------

    def subscribe(self, sql: str) -> SubscriptionHandle:
        nsql = normalize_sql(sql)
        with self._lock:
            sub_id = self._by_sql.get(nsql)
            if sub_id:
                return self._subs[sub_id]
            # create while holding the lock: two racing subscribers with
            # the same new SQL must share one subscription
            handle = self._create(str(uuid.uuid4()), nsql)
        handle.refresh(initial=True)
        return handle

    def _create(self, sub_id: str, nsql: str) -> SubscriptionHandle:
        from corrosion_tpu.agent.storage import register_udfs

        scratch = sqlite3.connect(self.agent.config.db_path)
        register_udfs(scratch)
        try:
            tables = tables_of_query(scratch, nsql)
        finally:
            scratch.close()
        crr = set(self.agent.storage.tables)
        tables &= crr
        if not tables:
            raise ValueError("query does not read any replicated table")
        # columns are filled by the first refresh (probing with an extra
        # LIMIT clause would break queries that already have one)
        handle = SubscriptionHandle(
            self, sub_id, nsql, [], tables,
            os.path.join(self.subs_path, f"{sub_id}.db"),
        )
        with self._lock:
            self._subs[sub_id] = handle
            self._by_sql[nsql] = sub_id
        return handle

    def get(self, sub_id: str) -> Optional[SubscriptionHandle]:
        with self._lock:
            return self._subs.get(sub_id)

    def list(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "id": h.id,
                    "sql": h.sql,
                    "tables": sorted(h.tables),
                    "rows": len(h.rows),
                    "last_change_id": h.last_change_id,
                }
                for h in self._subs.values()
            ]

    # -- change intake ---------------------------------------------------

    def on_change(self, cv: ChangeV1) -> None:
        """Called by the agent for every local commit + applied remote
        changeset (``match_changes`` parity)."""
        cs = cv.changeset
        touched: Dict[str, List] = {}
        for ch in cs.changes:
            touched.setdefault(ch.table, []).append(ch)
        with self._lock:
            for h in self._subs.values():
                if any(t in h.tables for t in touched):
                    self._pending.add(h.id)
        for table, chs in touched.items():
            self._notify_updates(table, chs)
        if touched:
            self._wake.set()

    def _run(self) -> None:
        while not self._closed:
            self._wake.wait()
            if self._closed:
                return
            time.sleep(DEBOUNCE_S)  # batch candidates
            self._wake.clear()
            with self._lock:
                pending, self._pending = self._pending, set()
                handles = [self._subs[i] for i in pending if i in self._subs]
            for h in handles:
                try:
                    h.refresh()
                except sqlite3.Error:
                    pass

    # -- table-level updates (updates.rs parity) -------------------------

    def table_updates(self, table: str):
        """Generator of {"change": [kind, pk_cells]} events for one table."""
        q: queue.Queue = queue.Queue(maxsize=4096)
        self._update_streams.setdefault(table, []).append(q)
        try:
            while True:
                try:
                    yield q.get(timeout=30.0)
                except queue.Empty:
                    continue
        finally:
            self._update_streams.get(table, []).remove(q)

    def _notify_updates(self, table: str, changes: List) -> None:
        streams = self._update_streams.get(table)
        if not streams:
            return
        seen_pks = set()
        for ch in changes:
            if ch.pk in seen_pks:
                continue
            seen_pks.add(ch.pk)
            # cl parity: even causal length means the row is deleted
            kind = "delete" if ch.cl % 2 == 0 else "upsert"
            cells = jsonable_row(unpack_values(ch.pk))
            for q in list(streams):
                try:
                    q.put_nowait({"change": [kind, cells]})
                except queue.Full:
                    pass

