"""Reactive query layer: streaming subscriptions + table-level updates.

Parity: ``crates/corro-types/src/pubsub.rs`` (the Matcher engine —
incremental materialized views over SQL subscriptions, per-subscription
persistence, buffered candidate batching, ``QueryEvent`` streams) and
``updates.rs`` (table-level notify streams), served over HTTP by
``api/public/pubsub.rs`` / ``update.rs``.

Design differences (deliberate):

* table extraction uses sqlite's authorizer hook during prepare — the
  database is the SQL parser (the reference rewrites ASTs with
  ``sqlite3-parser``);
* incremental maintenance is pk-scoped like the reference's candidate
  rewrite (``pubsub.rs:602-737,1432-1707``), achieved through the same
  core move — every referenced table's primary key columns are added to
  the projection as hidden ``__corro_pk_<table>_<i>`` aliases — but via
  top-level text splicing + query nesting instead of full AST surgery.
  A change batch on table t evaluates ``SELECT * FROM (<rewritten>)
  WHERE (t's hidden pk cols) IN (VALUES ...candidates...)``: sqlite's
  subquery flattening pushes the predicate onto t's pk index, so the
  work is proportional to the candidate rows — including multi-table
  JOIN subscriptions, where each changed table scopes its own delta
  (the analogue of the reference's per-table temp-pk-table scoping).
  Result rows are identity-keyed by the joined pk tuple, yielding true
  ``update`` events.  Ineligible queries (aggregates, DISTINCT, LIMIT,
  subqueries, set ops, windows, self-joins) keep the
  re-evaluate-and-diff path (correct, not incremental);
* per-subscription state (sql, rows, change log) persists in its own
  sqlite file under ``subs_path`` and is restored on boot
  (``pubsub.rs:819-856`` parity).

Event wire format (matches the reference's ``TypedQueryEvent``):
  {"columns": [...]}            first frame of a snapshot
  {"row": [row_id, cells]}      snapshot row
  {"eoq": {"time": t, "change_id": id}}
  {"change": [kind, row_id, cells, change_id]}   kind: insert|update|delete
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue
import re
import sqlite3
import threading
import time
import uuid
from typing import Dict, List, Optional, Set, Tuple

logger = logging.getLogger("corrosion_tpu.agent.pubsub")

from corrosion_tpu.agent import submatch
from corrosion_tpu.agent.pack import jsonable_row, pack_values, unpack_values
from corrosion_tpu.types.changeset import ChangeV1

DEBOUNCE_S = 0.05
MAX_CHANGE_LOG = 100_000
# more candidate pks than this per round -> full refresh is cheaper
DELTA_MAX_PKS = 2048
# row-fetch VALUES chunking: stay well under sqlite's host-parameter
# ceiling even for wide composite pks
FETCH_PARAM_BUDGET = 900
# words whose presence means a row's content or membership can depend on
# OTHER rows: pk-scoped delta evaluation would be wrong, so such queries
# use full refresh.  Deliberately over-broad (a column merely NAMED
# "count" costs only the optimization, never correctness).
_GLOBAL_WORDS = frozenset(
    (
        "DISTINCT", "GROUP", "HAVING", "UNION", "INTERSECT", "EXCEPT",
        "LIMIT", "OFFSET", "OVER", "WITH",
        # aggregates
        "COUNT", "SUM", "AVG", "TOTAL", "MAX", "MIN", "GROUP_CONCAT",
        "STRING_AGG",
        # join forms the textual item parser doesn't model
        "USING", "NATURAL",
    )
)

# RIGHT/FULL joins disqualify the delta path outright: they break the
# anchor property (the FIRST from-item's rows can then be NULL-extended,
# so no non-NULL pk tuple identifies every result row).  LEFT joins are
# handled: a change on a NULLABLE (left-joined) alias re-scopes through
# the anchor (see SubscriptionHandle._delta_nullable).
_OUTER_DISQUALIFY = frozenset(("RIGHT", "FULL"))
_ITEM_STOP_WORDS = frozenset(("ON", "WHERE", "ORDER", "AND", "OR"))


def _scan_top_level(sql: str):
    """Yield (index, char, depth) for chars outside string literals,
    with paren depth tracked."""
    depth = 0
    in_str: Optional[str] = None
    for i, ch in enumerate(sql):
        if in_str:
            if ch == in_str:
                in_str = None
            continue
        if ch in ("'", '"'):
            in_str = ch
            continue
        if ch == "(":
            depth += 1
            continue
        if ch == ")":
            depth -= 1
            continue
        yield i, ch, depth


def _top_level_word(sql: str, word: str, start: int = 0) -> int:
    """Index of the first depth-0 occurrence of ``word`` as a bare word
    outside strings, or -1."""
    up = sql.upper()
    w = word.upper()
    for i, ch, depth in _scan_top_level(sql):
        if depth != 0 or i < start:
            continue
        if up.startswith(w, i) and (i == 0 or not up[i - 1].isalnum()):
            end = i + len(w)
            if end == len(sql) or not (up[end].isalnum() or up[end] == "_"):
                return i
    return -1


def _mask_strings(s: str) -> str:
    """Copy of ``s`` with string-literal/quoted content blanked, so
    word regexes cannot match inside literals."""
    out = [" "] * len(s)
    for i, ch, _depth in _scan_top_level(s):
        out[i] = ch
    return "".join(out)


_CONN_RE = re.compile(
    r",|\bLEFT\s+OUTER\s+JOIN\b|\bLEFT\s+JOIN\b"
    r"|\b(?:INNER\s+|CROSS\s+)?JOIN\b",
    re.IGNORECASE,
)


def from_items_ex(nsql: str):
    """Top-level from-items of a normalized single SELECT.

    Returns ``(items, conn_spans)`` where ``items`` is a list of
    ``(table, alias, nullable)`` triples — ``nullable`` marks an item
    introduced by a LEFT [OUTER] JOIN, whose columns can be
    NULL-extended in the result — and ``conn_spans[j]`` is the absolute
    (start, end) span in ``nsql`` of the connector introducing item j
    (None for the anchor), used to build the inner-join scope variant
    for nullable deltas.  ``(None, None)`` when the shape is out of
    scope (subquery in FROM, USING/NATURAL/RIGHT/FULL joins, quoted
    exotica).  The textual counterpart of the reference's table
    extraction (``pubsub.rs:1813-2107``)."""
    fi = _top_level_word(nsql, "FROM")
    if fi < 0:
        return None, None
    end = len(nsql)
    for stop in ("WHERE", "ORDER", "GROUP", "LIMIT", "HAVING", "WINDOW"):
        si = _top_level_word(nsql, stop, fi + 4)
        if 0 <= si < end:
            end = si
    cstart = fi + 4
    clause = nsql[cstart:end]
    if "(" in clause:
        return None, None  # subquery or function in FROM
    masked = _mask_strings(clause)
    for w in re.findall(r"[A-Za-z_]+", masked.upper()):
        if w in _OUTER_DISQUALIFY:
            return None, None  # RIGHT/FULL: anchor property broken
        if w in ("NATURAL", "USING"):
            # join forms whose columns the splitter doesn't model —
            # without this, "t NATURAL JOIN u" would parse as table t
            # aliased NATURAL
            return None, None
    conns = list(_CONN_RE.finditer(masked))
    # item segments live between consecutive connectors
    bounds = []
    prev = 0
    for m in conns:
        bounds.append((prev, m.start()))
        prev = m.end()
    bounds.append((prev, len(clause)))
    items: List[Tuple[str, str, bool]] = []
    conn_spans: List[Optional[Tuple[int, int]]] = []
    for j, (s, e) in enumerate(bounds):
        seg = clause[s:e]
        seg_masked = masked[s:e]
        # keep only the item itself (strip any ON condition; located on
        # the masked copy so an 'ON' inside a literal cannot match)
        mo = re.search(r"\bON\b", seg_masked, flags=re.IGNORECASE)
        piece = (seg[: mo.start()] if mo else seg).strip()
        if not piece:
            if j == 0:
                return None, None  # leading connector
            continue
        toks = piece.replace('"', "").split()
        if not toks:
            return None, None
        table = toks[0]
        alias = table
        rest = [t for t in toks[1:] if t.upper() != "AS"]
        if rest:
            if len(rest) > 1 or rest[0].upper() in _ITEM_STOP_WORDS:
                return None, None
            alias = rest[0]
        if not re.fullmatch(r"\w+", table) or not re.fullmatch(
            r"\w+", alias
        ):
            return None, None
        conn = conns[j - 1] if j > 0 else None
        nullable = bool(
            conn and conn.group(0).upper().startswith("LEFT")
        )
        items.append((table, alias, nullable))
        conn_spans.append(
            (cstart + conn.start(), cstart + conn.end()) if conn else None
        )
    if not items:
        return None, None
    return items, conn_spans


def from_items(nsql: str) -> Optional[List[Tuple[str, str, bool]]]:
    """`from_items_ex` without the connector spans."""
    items, _spans = from_items_ex(nsql)
    return items


def group_by_exprs(nsql: str) -> Optional[List[str]]:
    """The GROUP BY expressions of a normalized single SELECT, when
    every one is a bare column or alias.column reference (the shapes
    the scoped re-aggregation can key on); None otherwise or when there
    is no GROUP BY."""
    gi = _top_level_word(nsql, "GROUP")
    if gi < 0:
        return None
    m = re.match(r"GROUP\s+BY\b", nsql[gi:], flags=re.IGNORECASE)
    if not m:
        return None
    start = gi + m.end()
    end = len(nsql)
    for stop in ("HAVING", "ORDER", "LIMIT", "WINDOW"):
        si = _top_level_word(nsql, stop, start)
        if 0 <= si < end:
            end = si
    exprs = [e.strip() for e in nsql[start:end].split(",")]
    for e in exprs:
        if not re.fullmatch(r"\w+(\.\w+)?", e):
            return None
    return exprs or None


def from_clause_text(nsql: str) -> str:
    """The text of the top-level FROM clause (between FROM and the
    first top-level stop word)."""
    fi = _top_level_word(nsql, "FROM")
    end = len(nsql)
    for stop in ("WHERE", "ORDER", "GROUP", "LIMIT", "HAVING", "WINDOW"):
        si = _top_level_word(nsql, stop, fi + 4)
        if 0 <= si < end:
            end = si
    return nsql[fi + 4:end].strip()


def splice_pk_cols(nsql: str, items: List[Tuple[str, str, bool]],
                   pk_cols: Dict[str, List[str]]) -> Tuple[str, int]:
    """Rewrite the SELECT to append every from-item's pk columns as
    hidden ``__corro_pk_<alias>_<i>`` aliases (the reference's
    ``__corro_pk`` projection tagging, ``pubsub.rs:602-737``).  Returns
    (rewritten sql, number of hidden columns)."""
    fi = _top_level_word(nsql, "FROM")
    extras = []
    for table, alias, _nullable in items:
        for i, col in enumerate(pk_cols[table]):
            extras.append(
                f'"{alias}"."{col}" AS __corro_pk_{alias}_{i}'
            )
    return (
        nsql[:fi].rstrip() + ", " + ", ".join(extras) + " " + nsql[fi:],
        len(extras),
    )


def plan_mentions(plan_text: str, op: str, name: str) -> bool:
    """Does an EXPLAIN QUERY PLAN transcript apply ``op`` (SEARCH/SCAN)
    to the from-item ``name``?  Handles both plan formats: sqlite >=
    3.36 prints ``SEARCH t``, older builds print ``SEARCH TABLE tests
    AS t`` (or ``SEARCH TABLE tests`` when unaliased).  Word-boundary
    matching, and a bare table-name hit directly followed by ``AS`` is
    rejected — there it is the TABLE of some other alias, not the
    from-item asked about."""
    return re.search(
        rf"{op} (?:TABLE )?(?:\w+ AS )?{re.escape(name)}\b(?!\s+AS\b)",
        plan_text,
    ) is not None


def normalize_sql(sql: str) -> str:
    """Collapse whitespace OUTSIDE string literals only."""
    out = []
    in_str: Optional[str] = None
    ws = False
    for ch in sql.strip().rstrip(";").strip():
        if in_str:
            out.append(ch)
            if ch == in_str:
                in_str = None
            continue
        if ch in ("'", '"'):
            in_str = ch
            out.append(ch)
            ws = False
        elif ch.isspace():
            ws = True
        else:
            if ws and out:
                out.append(" ")
            ws = False
            out.append(ch)
    return "".join(out)


def tables_of_query(conn: sqlite3.Connection, sql: str) -> Set[str]:
    """Which tables does this SELECT read?  The authorizer sees every
    SQLITE_READ during prepare."""
    tables: Set[str] = set()

    def auth(action, arg1, arg2, dbname, trigger):
        if action == sqlite3.SQLITE_READ and arg1:
            tables.add(arg1)
        return sqlite3.SQLITE_OK

    conn.set_authorizer(auth)
    try:
        conn.execute(f"EXPLAIN {sql}")
    finally:
        conn.set_authorizer(None)
    return tables


class SubscriptionHandle:
    """One live subscription; many HTTP streams can attach."""

    def __init__(self, manager: "SubsManager", sub_id: str, sql: str,
                 columns: List[str], tables: Set[str], db_path: str):
        self.manager = manager
        self.id = sub_id
        self.sql = sql
        self.columns = columns
        self.tables = tables
        self.db_path = db_path
        # zero-receiver GC bookkeeping (pubsub.rs:131-227 parity)
        self.last_receiver_at = time.time()
        # last SUCCESSFUL refresh/delta round (wall): the per-sub
        # staleness base — corro_subs_staleness_seconds{id=} rises from
        # here, so a sub silently losing its refreshes (counted in
        # corro_subs_refresh_failures_total) is visible as a rising age
        self.last_ok_at = time.time()
        self._lock = threading.RLock()
        # row identity -> (row_id, cells); change log for catch-up
        self.rows: Dict[str, Tuple[int, list]] = {}
        self.last_row_id = 0
        self.last_change_id = 0
        self._closed = False
        self._streams: List[queue.Queue] = []
        # pk-scoped incremental evaluation (set by the manager when the
        # query qualifies): the rewritten query with hidden
        # __corro_pk_* columns, the from-items in projection order, the
        # hidden-column index ranges per ALIAS (a self-join has one
        # scope per occurrence), and the identity index
        # (alias, pk-hex) -> [identities]
        self.exec_sql: Optional[str] = None
        self.n_hidden = 0
        self.pk_items: Optional[List[Tuple[str, str, bool]]] = None
        self.pk_idx: Dict[str, List[int]] = {}  # alias -> exec col idx
        self.by_pk: Dict[Tuple[str, str], List[str]] = {}
        self.pk_of: Dict[str, Dict[str, str]] = {}  # identity -> hexes
        # nullable alias -> (harvest select, scope-cols sql): the
        # affected-anchor harvest for LEFT-joined tables.  sqlite
        # cannot push a pk-IN predicate through a LEFT JOIN's nullable
        # side, and the user WHERE can hide a transition, so the
        # harvest selects the ANCHOR's pk columns over the from-clause
        # with that one connector flipped LEFT JOIN -> JOIN and NO user
        # WHERE — a superset of the affected anchors
        self.harvest_sql: Dict[str, Tuple[str, str]] = {}
        # aliases whose scoped delta cannot reach an index: a change on
        # their table falls back to one full refresh for the round
        self.full_refresh_aliases: Set[str] = set()
        # bounded re-evaluation mode (ORDER BY + LIMIT over an
        # index-served ordering): a change wave re-runs the whole query
        # but the index bounds the cost to O(limit), so it counts as a
        # delta round, not a full refresh
        self.bounded = False
        # COUNT(*)-only mode: the single count row is maintained
        # incrementally from per-pk membership transitions (the
        # pk_groups side table records which pks are currently counted)
        self.count_only = False
        self.count_full_probe: Optional[str] = None
        self.count_has_where = False
        self.count_pk_cols_sql = ""
        # columnar matcher spec (submatch.SubSpec) when the shape is
        # decidable from (pk, liveness, current row); None = this sub
        # stays on the per-sub oracle path
        self.columnar_spec = None
        # matcher shard this sub's candidate work routes to
        self.shard = 0
        # single-table GROUP BY aggregate mode: the group-key tuple is
        # the row identity; a delta probes the changed pks' CURRENT
        # groups (no user WHERE — it can hide a membership change),
        # unions them with the pks' previously-recorded groups (the
        # pk_groups side table), and re-aggregates only those groups
        # (the reference's scoped re-aggregation, pubsub.rs:1432-1707)
        self.agg = False
        self.agg_probe_sql: Optional[str] = None
        self.agg_pk_cols_sql = ""
        self.agg_n_grp = 0
        # (prefix, suffix, per-group conjunction): the scoped re-agg
        # splices its group predicate INTO the query's own WHERE ahead
        # of GROUP BY — sqlite does not push outer predicates into an
        # aggregate subquery, so wrapping would re-scan the table
        self.agg_scope_parts: Optional[Tuple[str, str, str]] = None
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._db.executescript(
            """
CREATE TABLE IF NOT EXISTS meta (key TEXT PRIMARY KEY, value TEXT);
CREATE TABLE IF NOT EXISTS rows (
  identity TEXT PRIMARY KEY, row_id INTEGER NOT NULL, cells TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS changes (
  change_id INTEGER PRIMARY KEY, kind TEXT NOT NULL,
  row_id INTEGER NOT NULL, cells TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS pk_groups (
  pk TEXT PRIMARY KEY, grp TEXT NOT NULL);
"""
        )
        have = {r[1] for r in self._db.execute("PRAGMA table_info(rows)")}
        if "pk" not in have:
            self._db.execute("ALTER TABLE rows ADD COLUMN pk TEXT")
        self._db.execute(
            "INSERT OR REPLACE INTO meta VALUES ('sql', ?)", (sql,)
        )
        self._db.commit()

    @property
    def incremental(self) -> bool:
        return self.pk_items is not None

    # -- persistence -----------------------------------------------------

    def _restore(self) -> bool:
        # read the change-log high-water mark FIRST: even with an empty
        # materialized set (all rows deleted pre-restart), new change ids
        # must continue after the persisted log or they collide
        last = self._db.execute("SELECT MAX(change_id) FROM changes").fetchone()
        self.last_change_id = last[0] or 0
        rows = self._db.execute(
            "SELECT identity, row_id, cells, pk FROM rows"
        ).fetchall()
        if self.incremental and rows and any(pk is None for *_r, pk in rows):
            # state persisted under the old hash-keyed identity scheme:
            # silently re-key (a diff against the restored identities
            # would read as a full-table delete+insert storm).  The old
            # change log references the now-dead row_ids, so truncate it
            # too — _can_catch_up then fails and resuming clients get a
            # fresh snapshot instead of events against unknown rids
            self._db.execute("DELETE FROM rows")
            self._db.execute("DELETE FROM changes")
            self._db.commit()
            self.last_row_id = max((r[1] for r in rows), default=0)
            self.refresh(initial=True)
            return True
        for identity, row_id, cells, pk in rows:
            self.rows[identity] = (row_id, json.loads(cells))
            self.last_row_id = max(self.last_row_id, row_id)
            if pk is not None and self.incremental:
                if pk.startswith("{"):
                    hexes = json.loads(pk)
                else:  # legacy single-table plain hex (alias == table)
                    hexes = {self.pk_items[0][1]: pk}
                self.pk_of[identity] = hexes
                for t, h in hexes.items():
                    self.by_pk.setdefault((t, h), []).append(identity)
        return bool(rows) or self.last_change_id > 0

    def _persist_rows(self, upserts, deletes, pks=None) -> None:
        def encode_pk(i):
            hexes = (pks or {}).get(i)
            if not hexes:
                return None
            first = self.pk_items[0][1] if self.pk_items else None
            if len(hexes) == 1 and next(iter(hexes)) == first:
                return next(iter(hexes.values()))  # legacy plain hex
            return json.dumps(hexes, sort_keys=True)

        self._db.executemany(
            "INSERT OR REPLACE INTO rows (identity, row_id, cells, pk) "
            "VALUES (?, ?, ?, ?)",
            [
                (i, rid, json.dumps(c), encode_pk(i))
                for i, (rid, c) in upserts.items()
            ],
        )
        self._db.executemany(
            "DELETE FROM rows WHERE identity=?", [(i,) for i in deletes]
        )

    def _persist_change(self, change_id, kind, row_id, cells) -> None:
        self._db.execute(
            "INSERT INTO changes (change_id, kind, row_id, cells) "
            "VALUES (?, ?, ?, ?)",
            (change_id, kind, row_id, json.dumps(cells)),
        )
        if change_id % 1000 == 0:
            self._db.execute(
                "DELETE FROM changes WHERE change_id <= ?",
                (change_id - MAX_CHANGE_LOG,),
            )

    # -- evaluation ------------------------------------------------------

    @staticmethod
    def _identity(cells: list, occurrence: int) -> str:
        """Row identity = content hash + occurrence index, so duplicate
        result rows keep multiset cardinality (a projection can make rows
        non-distinct)."""
        h = hashlib.blake2s(
            json.dumps(cells, sort_keys=True, default=str).encode(),
            digest_size=16,
        ).hexdigest()
        return f"{h}:{occurrence}"

    def _pk_keyed(self, rows):
        """identity -> user cells and identity -> {alias: pk-hex} for an
        exec-query result set: identities key on the joined tuple of
        every from-item's hidden pk columns (stable across evaluations
        — true update events; the single-table identity is the plain
        ``hex:occ`` the old format used, so persisted state carries
        over).  A NULL-extended left-join side packs its NULL pk values
        like any others — the anchor side keeps the identity unique."""
        new_ids: Dict[str, list] = {}
        pks_of: Dict[str, Dict[str, str]] = {}
        counts: Dict[str, int] = {}
        n_user = None
        for r in rows:
            if n_user is None:
                n_user = len(r) - self.n_hidden
            cells = jsonable_row(r[:n_user])
            hexes = {
                a: pack_values([r[i] for i in self.pk_idx[a]]).hex()
                for _t, a, _n in self.pk_items
            }
            joined = "|".join(hexes[a] for _t, a, _n in self.pk_items)
            occ = counts.get(joined, 0)
            counts[joined] = occ + 1
            identity = f"{joined}:{occ}"
            new_ids[identity] = cells
            pks_of[identity] = hexes
        return new_ids, pks_of

    def _grp_keyed(self, rows):
        """identity -> user cells and identity -> pseudo-alias hexes
        for an aggregate exec result: one row per group, identity keyed
        on the packed group-key tuple (stable — count changes arrive as
        in-place updates)."""
        new_ids: Dict[str, list] = {}
        pks_of: Dict[str, Dict[str, str]] = {}
        n_user = None
        for r in rows:
            if n_user is None:
                n_user = len(r) - self.agg_n_grp
            cells = jsonable_row(r[:n_user])
            h = pack_values(list(r[n_user:])).hex()
            identity = f"{h}:0"
            new_ids[identity] = cells
            pks_of[identity] = {"__corro_grp": h}
        return new_ids, pks_of

    def _rebuild_pk_groups(self) -> None:
        """Recompute the pk -> group side map wholesale (boot/refresh:
        rows may have moved groups while the map wasn't maintained).
        Caller holds ``self._lock``; caller commits."""
        _, rows = self.manager.agent.storage.read_query(self.agg_probe_sql)
        n = self.agg_n_grp
        self._db.execute("DELETE FROM pk_groups")
        self._db.executemany(
            "INSERT OR REPLACE INTO pk_groups VALUES (?, ?)",
            [
                (
                    pack_values(list(r[n:])).hex(),
                    pack_values(list(r[:n])).hex(),
                )
                for r in rows
            ],
        )

    def _apply_diff(self, new_ids, pks_of, scope_old, initial,
                    cand_keys=None) -> None:
        """Diff ``new_ids`` against ``scope_old`` (the materialized rows
        the evaluation could have produced), persist, emit events.
        ``cand_keys``: the (table, pk-hex) scope keys of a delta round,
        None for a full refresh.  Caller holds ``self._lock``."""
        upserts: Dict[str, Tuple[int, list]] = {}
        events = []
        for identity, cells in new_ids.items():
            old = scope_old.get(identity)
            if old is None:
                self.last_row_id += 1
                rid = self.last_row_id
                upserts[identity] = (rid, cells)
                if not initial:
                    self.last_change_id += 1
                    events.append(("insert", rid, cells, self.last_change_id))
            elif old[1] != cells:
                rid = old[0]
                upserts[identity] = (rid, cells)
                if not initial:
                    self.last_change_id += 1
                    events.append(("update", rid, cells, self.last_change_id))
        deletes = []
        for identity, (rid, cells) in scope_old.items():
            if identity not in new_ids:
                deletes.append(identity)
                if not initial:
                    self.last_change_id += 1
                    events.append(("delete", rid, cells, self.last_change_id))
        self.rows.update(upserts)
        for i in deletes:
            self.rows.pop(i, None)
        if self.incremental:
            if cand_keys is None:
                self.by_pk = {}
                self.pk_of = {}
            else:
                for i in deletes:
                    # drop the row from EVERY table's index (a delta
                    # scoped on one table deletes rows the other
                    # tables' entries still reference); prune emptied
                    # keys or delete churn grows by_pk without bound
                    for t, h in self.pk_of.pop(i, {}).items():
                        lst = self.by_pk.get((t, h))
                        if lst and i in lst:
                            lst.remove(i)
                        if lst is not None and not lst:
                            del self.by_pk[(t, h)]
            for identity, hexes in pks_of.items():
                self.pk_of[identity] = hexes
                for t, h in hexes.items():
                    lst = self.by_pk.setdefault((t, h), [])
                    if identity not in lst:
                        lst.append(identity)
        self._persist_rows(upserts, deletes, pks_of)
        for kind, rid, cells, cid in events:
            self._persist_change(cid, kind, rid, cells)
        self._db.commit()
        for kind, rid, cells, cid in events:
            self._fanout({"change": [kind, rid, cells, cid]})

    def refresh(self, initial: bool = False) -> None:
        """Re-evaluate the whole query and emit diff events."""
        self._refresh_inner(initial)
        # only a COMPLETED pass moves the staleness base (an exception
        # above propagates to the drain round's failure counter)
        self.last_ok_at = time.time()

    def _refresh_inner(self, initial: bool = False,
                       count: bool = True) -> None:
        # bounded (ORDER BY + LIMIT) re-evals run through here too but
        # count as delta rounds, not refreshes — the index bounds their
        # cost to O(limit)
        if count:
            self.manager.agent.metrics.counter("corro_subs_refresh_total")
        if self.incremental and self.count_only:
            cols, rows = self.manager.agent.storage.read_query(self.sql)
            with self._lock:
                self.columns = cols
                cells = jsonable_row(rows[0]) if rows else [0]
                self._apply_diff(
                    {"__corro_count:0": cells}, {"__corro_count:0": {}},
                    dict(self.rows), initial,
                )
                self._rebuild_count_members()
                self._db.commit()
            return
        if self.incremental and self.agg:
            cols, rows = self.manager.agent.storage.read_query(
                self.exec_sql
            )
            with self._lock:
                self.columns = cols[: len(cols) - self.agg_n_grp]
                new_ids, pks_of = self._grp_keyed(rows)
                self._apply_diff(new_ids, pks_of, dict(self.rows), initial)
                self._rebuild_pk_groups()
                self._db.commit()
            return
        if self.incremental:
            cols, rows = self.manager.agent.storage.read_query(
                self.exec_sql
            )
            with self._lock:
                self.columns = cols[: len(cols) - self.n_hidden]
                new_ids, pks_of = self._pk_keyed(rows)
                self._apply_diff(new_ids, pks_of, dict(self.rows), initial)
            return
        cols, rows = self.manager.agent.storage.read_query(self.sql)
        with self._lock:
            self.columns = cols
            new_ids = {}
            counts: Dict[str, int] = {}
            for r in rows:
                cells = jsonable_row(r)
                key = json.dumps(cells, sort_keys=True, default=str)
                occ = counts.get(key, 0)
                counts[key] = occ + 1
                new_ids[self._identity(cells, occ)] = cells
            self._apply_diff(new_ids, {}, dict(self.rows), initial)

    def delta(self, table_pks: Dict[str, Set[bytes]]) -> None:
        """Pk-scoped incremental evaluation (the candidate path,
        ``pubsub.rs:1432-1707``): work proportional to the candidate
        rows, not the table.  Each changed table scopes its own
        evaluation through its hidden pk columns, ONCE PER OCCURRENCE —
        a self-join re-evaluates each aliased occurrence separately —
        the join analogue of the reference's per-table temp-pk-table
        re-evaluation.  A change on a NULLABLE (left-joined) alias
        re-scopes through the anchor instead (``_delta_nullable``)."""
        self.manager.agent.metrics.counter("corro_subs_delta_rounds_total")
        if self.bounded:
            # ORDER BY + LIMIT: membership depends on OTHER rows (a new
            # row can push one out of the top-N), so the candidate pks
            # are irrelevant — re-run the bounded query whole.  The
            # ordering index caps the cost at O(limit).
            self.manager.agent.metrics.counter(
                "corro_subs_bounded_refresh_total"
            )
            self._refresh_inner(count=False)
            self.last_ok_at = time.time()
            return
        if self.count_only:
            pks = table_pks.get(self.pk_items[0][0])
            if pks:
                self._delta_count(pks)
            self.last_ok_at = time.time()
            return
        if self.agg:
            pks = table_pks.get(self.pk_items[0][0])
            if pks:
                self._delta_agg(pks)
            self.last_ok_at = time.time()
            return
        work = []
        need_refresh = False
        anchor_alias = self.pk_items[0][1] if self.pk_items else None
        for table, pks in table_pks.items():
            if not pks:
                continue
            for _t, alias, nullable in self.pk_items or ():
                if _t != table:
                    continue
                if alias in self.full_refresh_aliases or (
                    # a nullable delta re-scopes THROUGH the anchor, so
                    # a degraded anchor degrades it too
                    nullable and anchor_alias in self.full_refresh_aliases
                ):
                    # only the DEGRADED alias routes through refresh
                    # (one per round, at the end); sibling aliases keep
                    # their scoped deltas below, so their events emit
                    # without waiting on the full re-evaluation
                    need_refresh = True
                    continue
                work.append((alias, nullable, pks))
        for alias, nullable, pks in work:
            if nullable:
                self._delta_nullable(alias, pks)
            else:
                self._delta_scoped(alias, pks)
        if need_refresh:
            self.refresh()
        self.last_ok_at = time.time()

    def _scope_rows(self, alias: str, pk_values: List[tuple]):
        """Evaluate the exec query scoped to ``alias``'s pk tuples."""
        idx = self.pk_idx[alias]
        cols_sql = ", ".join(
            f"__corro_pk_{alias}_{i}" for i in range(len(idx))
        )
        row_ph = "(" + ", ".join("?" for _ in idx) + ")"
        values = ", ".join(row_ph for _ in pk_values)
        sql = (
            f"SELECT * FROM ({self.exec_sql}) "
            f"WHERE ({cols_sql}) IN (VALUES {values})"
        )
        params = [v for vals in pk_values for v in vals]
        _, rows = self.manager.agent.storage.read_query(sql, params)
        return rows

    def _delta_scoped(self, alias: str, pks: Set[bytes]) -> None:
        """One alias's direct pk-scoped delta round."""
        rows = self._scope_rows(alias, [tuple(unpack_values(p)) for p in pks])
        cand_keys = {(alias, pk.hex()) for pk in pks}
        with self._lock:
            new_ids, pks_of = self._pk_keyed(rows)
            scope_old = {
                i: self.rows[i]
                for k in cand_keys
                for i in self.by_pk.get(k, [])
                if i in self.rows
            }
            self._apply_diff(
                new_ids, pks_of, scope_old, initial=False,
                cand_keys=cand_keys,
            )

    def _delta_nullable(self, alias: str, pks: Set[bytes]) -> None:
        """Delta for a change on a LEFT-joined (nullable) alias.

        A pk-IN scope on the nullable side cannot see NULL-extension
        transitions: deleting the matched inner row must RE-EMIT the
        outer row NULL-extended, and inserting a first match must
        RETRACT it — both outside the changed pks' scope (their hidden
        pk columns are NULL there).  So the delta runs in two stages
        (the reference re-scopes through its per-table temp pk tables,
        ``pubsub.rs:602-737``):

        1. harvest the ANCHOR pks affected by the change — from the
           currently-JOINING rows (the harvest query: anchor pks over
           the from-clause with this alias's connector flipped to an
           inner join and NO user WHERE, since the WHERE can hide a
           transition) plus the previously-materialized rows that
           referenced the changed pks (``by_pk``);
        2. run a normal anchor-scoped delta for those anchor pks, which
           recomputes the affected outer rows in full — matched,
           filtered away, or NULL-extended.
        """
        anchor = self.pk_items[0][1]
        anchor_vals: Dict[tuple, None] = {}  # ordered de-dup
        harvest, scope_cols = self.harvest_sql[alias]
        pk_values = [tuple(unpack_values(p)) for p in pks]
        row_ph = "(" + ", ".join("?" for _ in pk_values[0]) + ")"
        values = ", ".join(row_ph for _ in pk_values)
        sql = f"{harvest} WHERE ({scope_cols}) IN (VALUES {values})"
        params = [v for vals in pk_values for v in vals]
        _, rows = self.manager.agent.storage.read_query(sql, params)
        for r in rows:
            anchor_vals[tuple(r)] = None
        with self._lock:
            for pk in pks:
                for i in self.by_pk.get((alias, pk.hex()), ()):
                    h = self.pk_of.get(i, {}).get(anchor)
                    if h is not None:
                        anchor_vals[tuple(unpack_values(bytes.fromhex(h)))] \
                            = None
        if not anchor_vals:
            return
        if len(anchor_vals) > DELTA_MAX_PKS:
            self.refresh()
            return
        self._delta_scoped(
            anchor, {pack_values(list(v)) for v in anchor_vals}
        )

    def _delta_agg(self, pks: Set[bytes]) -> None:
        """Scoped re-aggregation for a change batch on the aggregate's
        table.

        Affected groups = the changed rows' CURRENT groups (probed
        without the user WHERE, which can hide a membership change)
        UNION the groups those pks were last seen in (``pk_groups``) —
        a row that moved groups dirties both.  Only those groups are
        re-aggregated; a group whose last row left (or that fails
        HAVING) disappears from the scoped result and is emitted as a
        delete."""
        storage = self.manager.agent.storage
        pk_values = [tuple(unpack_values(p)) for p in pks]
        row_ph = "(" + ", ".join("?" for _ in pk_values[0]) + ")"
        values = ", ".join(row_ph for _ in pk_values)
        _, rows = storage.read_query(
            f"{self.agg_probe_sql} WHERE ({self.agg_pk_cols_sql}) IN "
            f"(VALUES {values})",
            [v for vals in pk_values for v in vals],
        )
        n = self.agg_n_grp
        current = {
            pack_values(list(r[n:])).hex(): tuple(r[:n]) for r in rows
        }
        affected: Dict[str, tuple] = {}
        with self._lock:
            for pk in pks:
                ph = pk.hex()
                old = self._db.execute(
                    "SELECT grp FROM pk_groups WHERE pk = ?", (ph,)
                ).fetchone()
                if old is not None:
                    affected[old[0]] = tuple(
                        unpack_values(bytes.fromhex(old[0]))
                    )
                grp = current.get(ph)
                if grp is not None:
                    gh = pack_values(list(grp)).hex()
                    affected[gh] = grp
                    self._db.execute(
                        "INSERT OR REPLACE INTO pk_groups VALUES (?, ?)",
                        (ph, gh),
                    )
                else:
                    self._db.execute(
                        "DELETE FROM pk_groups WHERE pk = ?", (ph,)
                    )
        if not affected:
            self._db.commit()
            return
        # group keys can be NULL (one NULL group per GROUP BY), and
        # NULL never matches IN — scope with IS conjunctions, spliced
        # into the query's own WHERE (see agg_scope_parts)
        prefix, suffix, conj = self.agg_scope_parts
        pred = " OR ".join(conj for _ in affected)
        _, rows2 = storage.read_query(
            prefix + pred + suffix,
            [v for grp in affected.values() for v in grp],
        )
        cand_keys = {("__corro_grp", h) for h in affected}
        with self._lock:
            new_ids, pks_of = self._grp_keyed(rows2)
            scope_old = {
                i: self.rows[i]
                for k in cand_keys
                for i in self.by_pk.get(k, [])
                if i in self.rows
            }
            self._apply_diff(
                new_ids, pks_of, scope_old, initial=False,
                cand_keys=cand_keys,
            )

    def _rebuild_count_members(self) -> None:
        """Recompute the counted-pk membership side table wholesale
        (boot/refresh).  Caller holds ``self._lock``; caller commits."""
        _, rows = self.manager.agent.storage.read_query(
            self.count_full_probe
        )
        self._db.execute("DELETE FROM pk_groups")
        self._db.executemany(
            "INSERT OR REPLACE INTO pk_groups VALUES (?, '1')",
            [(pack_values(list(r)).hex(),) for r in rows],
        )

    def _delta_count(self, pks: Set[bytes]) -> None:
        """Incremental COUNT(*) maintenance: probe the changed pks'
        CURRENT membership (the count query's own WHERE, scoped on the
        pk index), diff against each pk's recorded membership
        (``pk_groups``), and move the single count row by the net
        transition — no re-aggregation, no table scan."""
        pk_values = [tuple(unpack_values(p)) for p in pks]
        row_ph = "(" + ", ".join("?" for _ in pk_values[0]) + ")"
        values = ", ".join(row_ph for _ in pk_values)
        sep = " AND " if self.count_has_where else " WHERE "
        _, rows = self.manager.agent.storage.read_query(
            f"{self.count_full_probe}{sep}"
            f"(({self.count_pk_cols_sql}) IN (VALUES {values}))",
            [v for vals in pk_values for v in vals],
        )
        current = {pack_values(list(r)).hex() for r in rows}
        with self._lock:
            moved = 0
            for pk in pks:
                ph = pk.hex()
                was = self._db.execute(
                    "SELECT 1 FROM pk_groups WHERE pk = ?", (ph,)
                ).fetchone()
                if ph in current and was is None:
                    moved += 1
                    self._db.execute(
                        "INSERT OR REPLACE INTO pk_groups VALUES (?, '1')",
                        (ph,),
                    )
                elif ph not in current and was is not None:
                    moved -= 1
                    self._db.execute(
                        "DELETE FROM pk_groups WHERE pk = ?", (ph,)
                    )
            if not moved:
                self._db.commit()
                return
            identity = "__corro_count:0"
            old = self.rows.get(identity)
            old_n = old[1][0] if old else 0
            self._apply_diff(
                {identity: [old_n + moved]}, {identity: {}},
                dict(self.rows), initial=False, cand_keys=frozenset(),
            )

    def apply_columnar(self, verdicts: Dict[bytes, Optional[tuple]]) -> None:
        """Apply one shard wave's resolved verdicts (the columnar fast
        path): ``verdicts[pk]`` is the current row in declared column
        order (upsert) or None (delete).  Produces the exact rows,
        identities and events the per-sub oracle (``_delta_scoped``)
        would — pinned by tests/test_subs_parity.py."""
        alias = self.pk_items[0][1]
        spec = self.columnar_spec
        new_ids: Dict[str, list] = {}
        pks_of: Dict[str, Dict[str, str]] = {}
        cand_keys = set()
        for pk, row in verdicts.items():
            h = pk.hex()
            cand_keys.add((alias, h))
            if row is None:
                continue
            identity = f"{h}:0"
            new_ids[identity] = jsonable_row(
                [row[i] for i in spec.proj_idx]
            )
            pks_of[identity] = {alias: h}
        with self._lock:
            scope_old = {
                i: self.rows[i]
                for k in cand_keys
                for i in self.by_pk.get(k, [])
                if i in self.rows
            }
            self._apply_diff(
                new_ids, pks_of, scope_old, initial=False,
                cand_keys=cand_keys,
            )
        self.last_ok_at = time.time()

    def _fanout(self, event: dict) -> None:
        self.manager.agent.metrics.counter("corro_subs_events_total")
        for q in list(self._streams):
            try:
                q.put_nowait(event)
                continue
            except queue.Full:
                pass
            # bounded buffer, drop-OLDEST: a slow consumer loses its
            # oldest events (it must resubscribe from a snapshot anyway
            # once it notices the change-id gap) instead of silently
            # losing the newest — and the drop is counted, per sub
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            try:
                q.put_nowait(event)
            except queue.Full:
                pass
            self.manager.agent.metrics.counter(
                "corro_subs_events_dropped_total", sub_id=self.id
            )

    # -- streaming -------------------------------------------------------

    def stream(self, from_change_id: Optional[int] = None):
        """Generator of events: snapshot (or catch-up) then live tail."""
        q: queue.Queue = queue.Queue(maxsize=4096)
        with self._lock:
            self._streams.append(q)
            if from_change_id is not None and self._can_catch_up(from_change_id):
                backlog = [
                    {"change": [kind, rid, json.loads(cells), cid]}
                    for cid, kind, rid, cells in self._db.execute(
                        "SELECT change_id, kind, row_id, cells FROM changes "
                        "WHERE change_id > ? ORDER BY change_id",
                        (from_change_id,),
                    )
                ]
            else:
                backlog = [{"columns": self.columns}]
                backlog += [
                    {"row": [rid, cells]}
                    for rid, cells in sorted(self.rows.values())
                ]
                backlog.append(
                    {"eoq": {"time": 0.0, "change_id": self.last_change_id}}
                )
        try:
            for ev in backlog:
                yield ev
            while not self._closed:
                try:
                    ev = q.get(timeout=5.0)
                except queue.Empty:
                    continue
                if ev is None:  # close sentinel
                    return
                yield ev
        finally:
            with self._lock:
                if q in self._streams:
                    self._streams.remove(q)
                self.last_receiver_at = time.time()

    def unsubscribe_stream(self) -> None:
        pass  # generator finally-block handles removal

    def _can_catch_up(self, from_change_id: int) -> bool:
        row = self._db.execute("SELECT MIN(change_id) FROM changes").fetchone()
        lo = row[0]
        return lo is not None and from_change_id >= lo - 1

    def close(self) -> None:
        self._closed = True
        for q in list(self._streams):
            try:
                q.put_nowait(None)  # wake + end attached streams
            except queue.Full:
                pass
        self._db.close()


class _MatcherShard:
    """One matcher worker shard: its own pending sets, columnar wave
    buffers, predicate index, and drain thread.

    Subscriptions hash onto shards by sub_id (``submatch.shard_of``);
    ``SubsManager.on_change`` — called from the group-commit broadcast
    collector (the corro-wbcast worker) and the remote apply path —
    only ROUTES: per-table change waves to the shards holding columnar
    subs on that table, per-sub candidate pks to the owning shard's
    queues.  All matching (SQL or columnar) runs on shard threads, off
    the event loop and off the collector."""

    def __init__(self, mgr: "SubsManager", idx: int):
        self.mgr = mgr
        self.idx = idx
        self.index = submatch.ShardIndex()
        self.pending: Set[str] = set()
        self.pending_pks: Dict[str, Dict[str, Set[bytes]]] = {}
        self.waves: Dict[str, List] = {}
        self.draining = False
        self.wake = threading.Event()
        self.thread = threading.Thread(
            target=self._run, name=f"corro-subs-{idx}", daemon=True
        )

    def depth(self) -> int:
        """Queued candidate work (caller holds the manager lock)."""
        return (
            len(self.pending)
            + sum(
                len(p)
                for per in self.pending_pks.values()
                for p in per.values()
            )
            + sum(len(chs) for chs in self.waves.values())
        )

    def overflow(self) -> None:
        """Bounded-depth enforcement (caller holds the manager lock):
        past the cap, queued precision work converts to full-refresh
        candidates — a refresh covers any candidate set, so nothing is
        lost, and the queue depth collapses to O(subs)."""
        self.mgr.agent.metrics.counter(
            "corro_subs_shard_overflow_total", shard=str(self.idx)
        )
        for sub_id in self.pending_pks:
            self.pending.add(sub_id)
        self.pending_pks = {}
        for table in self.waves:
            self.pending |= self.index.subs_on(table)
        self.waves = {}

    def _run(self) -> None:
        try:
            self._run_inner()
        except BaseException:
            # a dead worker must fail idle() loudly, not hang it
            # (draining stuck) or lie (popped batch never processed)
            self.mgr._worker_died = True
            raise

    def _run_inner(self) -> None:
        mgr = self.mgr
        last_gc = time.monotonic()
        while not mgr._closed:
            woke = self.wake.wait(timeout=mgr.GC_SWEEP_S)
            if mgr._closed:
                return
            # sweep on a deadline, NOT only when idle: a node with
            # steady write traffic never times the wait out.  Shard 0
            # carries the GC duty.
            if (
                self.idx == 0
                and time.monotonic() - last_gc >= mgr.GC_SWEEP_S
            ):
                mgr._gc_idle_subs()
                last_gc = time.monotonic()
            if not woke:
                continue
            time.sleep(DEBOUNCE_S)  # batch candidates
            self.wake.clear()
            with mgr._lock:
                pending, self.pending = self.pending, set()
                pending_pks, self.pending_pks = self.pending_pks, {}
                waves, self.waves = self.waves, {}
                # popped-but-unprocessed work keeps idle() false: the
                # sets alone go empty the instant a round is claimed,
                # long before its refresh/delta SQL has finished
                self.draining = bool(pending or pending_pks or waves)
            try:
                if waves:
                    # columnar waves first: a sub they degrade (fetch
                    # error, missing projection) lands in `pending` and
                    # is covered by the round's refresh pass below
                    mgr._drain_waves(self, waves, pending)
                mgr._drain_round(pending, pending_pks)
            finally:
                with mgr._lock:
                    self.draining = False


class SubsManager:
    """Owns all subscriptions + the table-update notify streams."""

    def __init__(self, agent, subs_path: Optional[str] = None):
        self.agent = agent
        self.subs_path = subs_path or os.path.join(
            os.path.dirname(agent.config.db_path) or ".", "subs"
        )
        os.makedirs(self.subs_path, exist_ok=True)
        self._subs: Dict[str, SubscriptionHandle] = {}
        self._by_sql: Dict[str, str] = {}
        self._lock = threading.RLock()
        self._worker_died = False
        self._update_streams: Dict[str, List[queue.Queue]] = {}
        self._closed = False
        self._columnar = bool(
            getattr(agent.config, "subs_columnar", True)
        )
        self._shard_max = int(
            getattr(agent.config, "subs_shard_max_pending", 50_000)
        )
        n_shards = max(1, int(getattr(agent.config, "subs_shards", 4)))
        self._shards = [_MatcherShard(self, i) for i in range(n_shards)]
        for s in self._shards:
            s.thread.start()
        agent.on_change = self.on_change
        self._restore()

    # -- lifecycle -------------------------------------------------------

    def _restore(self) -> None:
        for fn in os.listdir(self.subs_path):
            if not fn.endswith(".db"):
                continue
            sub_id = fn[:-3]
            path = os.path.join(self.subs_path, fn)
            try:
                db = sqlite3.connect(path)
                row = db.execute(
                    "SELECT value FROM meta WHERE key='sql'"
                ).fetchone()
                db.close()
                if not row:
                    continue
                sql = row[0]
                handle = self._create(sub_id, sql)
                if not handle._restore():
                    handle.refresh(initial=True)
                else:
                    # state may have moved while we were down
                    handle.refresh(initial=False)
            except sqlite3.Error:
                continue

    def close(self) -> None:
        self._closed = True
        for s in self._shards:
            s.wake.set()
        for s in self._shards:
            s.thread.join(timeout=2)
        with self._lock:
            for h in self._subs.values():
                h.close()

    # -- subscription management ----------------------------------------

    def subscribe(self, sql: str) -> SubscriptionHandle:
        nsql = normalize_sql(sql)
        with self._lock:
            sub_id = self._by_sql.get(nsql)
            if sub_id:
                h = self._subs[sub_id]
                # hand-out counts as receiver activity: the caller gets
                # a full GC horizon to attach its stream
                h.last_receiver_at = time.time()
                return h
            # create while holding the lock: two racing subscribers with
            # the same new SQL must share one subscription
            handle = self._create(str(uuid.uuid4()), nsql)
        handle.refresh(initial=True)
        return handle

    def _create(self, sub_id: str, nsql: str) -> SubscriptionHandle:
        from corrosion_tpu.agent.storage import register_udfs

        scratch = sqlite3.connect(self.agent.config.db_path)
        register_udfs(scratch)
        try:
            tables = tables_of_query(scratch, nsql)
        finally:
            scratch.close()
        raw_tables = set(tables)
        crr = set(self.agent.storage.tables)
        tables &= crr
        if not tables:
            raise ValueError("query does not read any replicated table")
        # columns are filled by the first refresh (probing with an extra
        # LIMIT clause would break queries that already have one)
        handle = SubscriptionHandle(
            self, sub_id, nsql, [], tables,
            os.path.join(self.subs_path, f"{sub_id}.db"),
        )
        handle.shard = submatch.shard_of(sub_id, len(self._shards))
        self._detect_incremental(handle, nsql, tables, raw_tables)
        if self._columnar:
            self._detect_columnar(handle, nsql)
        with self._lock:
            self._subs[sub_id] = handle
            self._by_sql[nsql] = sub_id
            if handle.columnar_spec is not None:
                self._shards[handle.shard].index.add(handle.columnar_spec)
        return handle

    def _detect_incremental(self, handle: SubscriptionHandle, nsql: str,
                            tables: Set[str],
                            raw_tables: Set[str]) -> None:
        """Qualify a query for pk-scoped delta evaluation by appending
        hidden ``__corro_pk_*`` columns for every from-item (the
        reference's projection tagging, ``pubsub.rs:602-737``).
        Requirements (conservative — a miss costs the optimization,
        never correctness):

        * a single top-level SELECT (no subqueries — a correlated or
          same-table subquery would make rows interdependent), no
          global operator / aggregate / set op / window / LIMIT;
        * a from-clause of inner-joined (plain/INNER/CROSS/comma)
          replicated tables, each referenced once (no self-joins; no
          outer joins — a row transitioning to its NULL-extended form
          escapes the inner table's pk filter; no local lookup tables —
          their changes aren't notified);
        * the per-table delta filter provably reaches that table's
          index (EXPLAIN QUERY PLAN shows a SEARCH, never a SCAN, of
          the scoped table).
        """
        up = nsql.upper()
        words = re.findall(r"[A-Za-z_]+", up)
        if words.count("SELECT") != 1:
            return
        hit = {w for w in words if w in _GLOBAL_WORDS}
        if hit:
            # escape hatches, so full refresh stays the exception:
            # ORDER BY + LIMIT over an index-served ordering gets
            # bounded re-evaluation, COUNT(*)-only gets incremental
            # membership counting, single-table GROUP BY aggregates get
            # scoped re-aggregation
            if hit == {"LIMIT"}:
                self._detect_incremental_bounded(
                    handle, nsql, tables, raw_tables
                )
                if handle.bounded:
                    return
            if hit == {"COUNT"}:
                self._detect_incremental_count(
                    handle, nsql, tables, raw_tables
                )
                if handle.count_only:
                    return
            self._detect_incremental_agg(handle, nsql, tables,
                                         raw_tables, words)
            return
        items, conn_spans = from_items_ex(nsql)
        if not items:
            return
        names = [t for t, _a, _n in items]
        aliases = [a for _t, a, _n in items]
        if len(set(aliases)) != len(aliases):
            return  # ambiguous occurrence scoping
        if any(a.startswith("__corro_") for a in aliases):
            return  # would collide with the hidden-column namespace
        if set(names) != raw_tables or not set(names) <= set(tables):
            # every table the query reads must be a replicated from-item
            # (raw_tables catches local lookup tables, whose changes
            # would never re-trigger evaluation)
            return
        infos = {}
        for t in names:
            info = self.agent.storage._tables.get(t)
            if info is None:
                return
            infos[t] = list(info.pk_cols)
        try:
            exec_sql, n_hidden = splice_pk_cols(nsql, items, infos)
            cols, _ = self.agent.storage.read_query(
                f"SELECT * FROM ({exec_sql}) LIMIT 0"
            )
        except (sqlite3.Error, ValueError):
            return
        # per-nullable-alias affected-anchor harvests: anchor pk
        # columns over the from-clause with that one connector flipped
        # LEFT JOIN -> JOIN, no user WHERE (see harvest_sql)
        anchor_t, anchor_a, _ = items[0]
        harvest_sql: Dict[str, Tuple[str, str]] = {}
        for j, (t, a, nullable) in enumerate(items):
            if not nullable:
                continue
            s, e = conn_spans[j]
            variant = nsql[:s] + "JOIN" + nsql[e:]
            anchor_cols = ", ".join(
                f'"{anchor_a}"."{c}"' for c in infos[anchor_t]
            )
            scope_cols = ", ".join(
                f'"{a}"."{c}"' for c in infos[t]
            )
            harvest = (
                f"SELECT {anchor_cols} FROM {from_clause_text(variant)}"
            )
            try:
                self.agent.storage.read_query(f"{harvest} LIMIT 0")
            except sqlite3.Error:
                return
            harvest_sql[a] = (harvest, scope_cols)
        # hidden-column projection indices per ALIAS (a self-join
        # scopes each occurrence separately)
        pk_idx: Dict[str, List[int]] = {}
        pos = len(cols) - n_hidden
        for t, a, _n in items:
            pk_idx[a] = list(range(pos, pos + len(infos[t])))
            pos += len(infos[t])
        # every delta plan must reach an index — a sibling with no
        # index on its join column would SCAN once per changed row,
        # costing O(sibling) per delta, worse than the full refresh
        # this path replaces (plans name the alias when used).  An
        # alias whose plan cannot reach an index DEGRADES individually:
        # changes on its table trigger one full refresh for the round
        # while the other aliases keep their scoped deltas.  If every
        # alias degrades the query is not incremental at all.
        full_refresh_aliases: Set[str] = set()

        def plan_of(sql: str, n_params: int):
            try:
                _, plan = self.agent.storage.read_query(
                    f"EXPLAIN QUERY PLAN {sql}", [None] * n_params
                )
            except sqlite3.Error:
                return None
            return " ".join(str(c) for row in plan for c in row)

        def in_plan(plan_text, op, name):
            # word-boundary matching: table "item" must not match the
            # plan line of its sibling "items" in the same join plan
            return plan_mentions(plan_text, op, name)

        for t, a, nullable in items:
            idx = pk_idx[a]
            if nullable:
                # the harvest is what this alias's delta runs; sqlite
                # may legally OMIT unused left-joined siblings from it
                # (absent is fine, SCAN is not), but the scoped alias
                # itself must SEARCH
                harvest, scope_cols = harvest_sql[a]
                row_ph = "(" + ", ".join("?" for _ in idx) + ")"
                plan_text = plan_of(
                    f"{harvest} WHERE ({scope_cols}) IN "
                    f"(VALUES {row_ph})",
                    len(idx),
                )
                ok = (
                    plan_text is not None
                    and in_plan(plan_text, "SEARCH", a)
                    and not any(
                        in_plan(plan_text, "SCAN", a2)
                        for _t2, a2, _n2 in items
                    )
                )
            else:
                cols_sql = ", ".join(
                    f"__corro_pk_{a}_{i}" for i in range(len(idx))
                )
                row_ph = "(" + ", ".join("?" for _ in idx) + ")"
                plan_text = plan_of(
                    f"SELECT * FROM ({exec_sql}) WHERE ({cols_sql}) "
                    f"IN (VALUES {row_ph})",
                    len(idx),
                )
                ok = plan_text is not None and all(
                    in_plan(plan_text, "SEARCH", a2)
                    and not in_plan(plan_text, "SCAN", a2)
                    for _t2, a2, _n2 in items
                )
            if not ok:
                full_refresh_aliases.add(a)
        if len(full_refresh_aliases) == len(items):
            return
        handle.exec_sql = exec_sql
        handle.harvest_sql = harvest_sql
        handle.full_refresh_aliases = full_refresh_aliases
        handle.n_hidden = n_hidden
        handle.pk_items = items
        handle.pk_idx = pk_idx

    def _detect_incremental_agg(self, handle: SubscriptionHandle,
                                nsql: str, tables: Set[str],
                                raw_tables: Set[str],
                                words: List[str]) -> None:
        """Qualify a single-table GROUP BY aggregate for scoped
        re-aggregation (``_delta_agg``).  Requirements:

        * GROUP BY on bare/qualified column names of ONE replicated
          from-item (HAVING and ORDER BY are fine — they ride inside
          the re-aggregated exec query);
        * no DISTINCT / set ops / windows / CTEs / LIMIT — their row
          content or membership depends on rows outside any group
          scope;
        * the group-scoped evaluation provably reaches an index on the
          group column(s) (EXPLAIN shows SEARCH, never SCAN).
        """
        for w in ("DISTINCT", "UNION", "INTERSECT", "EXCEPT", "LIMIT",
                  "OFFSET", "OVER", "WITH", "WINDOW", "USING",
                  "NATURAL"):
            if w in words:
                return
        grp_exprs = group_by_exprs(nsql)
        if not grp_exprs:
            return
        items, _spans = from_items_ex(nsql)
        if not items or len(items) != 1 or items[0][2]:
            return
        table, alias, _n = items[0]
        if alias.startswith("__corro_"):
            return
        if {table} != raw_tables or table not in tables:
            return
        info = self.agent.storage._tables.get(table)
        if info is None:
            return
        for e in grp_exprs:
            if "." in e and e.split(".", 1)[0] != alias:
                return
        n_grp = len(grp_exprs)
        fi = _top_level_word(nsql, "FROM")
        extras = ", ".join(
            f"{e} AS __corro_grp_{i}" for i, e in enumerate(grp_exprs)
        )
        exec_sql = (
            nsql[:fi].rstrip() + ", " + extras + " " + nsql[fi:]
        )
        pk_cols_sql = ", ".join(
            f'"{alias}"."{c}"' for c in info.pk_cols
        )
        probe = (
            f"SELECT {', '.join(grp_exprs)}, {pk_cols_sql} "
            f"FROM {from_clause_text(nsql)}"
        )
        # the scoped re-agg splices its predicate into the query's own
        # WHERE (ahead of GROUP BY): sqlite does not push an outer
        # predicate into an aggregate subquery
        gi = _top_level_word(exec_sql, "GROUP")
        wi = _top_level_word(exec_sql, "WHERE")
        if wi >= 0:
            # parenthesize the user WHERE: a top-level OR would
            # otherwise bind tighter than the appended AND and leak
            # unaffected groups into the scoped re-aggregation
            prefix = (
                exec_sql[:wi] + "WHERE (" + exec_sql[wi + 5:gi].strip()
                + ") AND ("
            )
        else:
            prefix = exec_sql[:gi] + "WHERE ("
        suffix = ") " + exec_sql[gi:]
        conj = "(" + " AND ".join(f"({e} IS ?)" for e in grp_exprs) + ")"
        try:
            self.agent.storage.read_query(
                f"SELECT * FROM ({exec_sql}) LIMIT 0"
            )
            self.agent.storage.read_query(f"{probe} LIMIT 0")
            _, plan = self.agent.storage.read_query(
                f"EXPLAIN QUERY PLAN {prefix}{conj}{suffix}",
                [None] * n_grp,
            )
        except sqlite3.Error:
            return
        plan_text = " ".join(str(c) for row in plan for c in row)
        if not plan_mentions(plan_text, "SEARCH", alias) or \
                plan_mentions(plan_text, "SCAN", alias):
            return
        handle.agg = True
        handle.exec_sql = exec_sql
        handle.agg_probe_sql = probe
        handle.agg_pk_cols_sql = pk_cols_sql
        handle.agg_n_grp = n_grp
        handle.agg_scope_parts = (prefix, suffix, conj)
        handle.pk_items = [items[0]]
        handle.pk_idx = {}

    def _detect_incremental_bounded(self, handle: SubscriptionHandle,
                                    nsql: str, tables: Set[str],
                                    raw_tables: Set[str]) -> None:
        """Qualify ORDER BY + LIMIT over an index-served ordering for
        bounded re-evaluation: membership depends on other rows (a new
        row can evict one from the top-N), so a change wave re-runs the
        WHOLE query — but only when EXPLAIN proves the ordering comes
        straight off an index (no ``USE TEMP B-TREE FOR ORDER BY``),
        which caps the cost at O(limit) regardless of table size.
        Counted as delta rounds (``corro_subs_bounded_refresh_total``),
        not full refreshes."""
        masked = _mask_strings(nsql).upper()
        if not re.search(r"\bLIMIT\s+\d+\s*$", masked):
            return  # OFFSET / expression limits keep full refresh
        if _top_level_word(nsql, "ORDER") < 0:
            return  # LIMIT without ORDER BY is nondeterministic
        items, _spans = from_items_ex(nsql)
        if not items or len(items) != 1 or items[0][2]:
            return
        table, alias, _n = items[0]
        if alias.startswith("__corro_"):
            return
        if {table} != raw_tables or table not in tables:
            return
        info = self.agent.storage._tables.get(table)
        if info is None:
            return
        try:
            exec_sql, n_hidden = splice_pk_cols(
                nsql, items, {table: list(info.pk_cols)}
            )
            cols, _ = self.agent.storage.read_query(
                f"SELECT * FROM ({exec_sql}) LIMIT 0"
            )
            _, plan = self.agent.storage.read_query(
                f"EXPLAIN QUERY PLAN {exec_sql}"
            )
        except (sqlite3.Error, ValueError):
            return
        plan_text = " ".join(
            str(c) for row in plan for c in row
        ).upper()
        if "TEMP B-TREE" in plan_text:
            # un-indexed sort: the re-eval would pay O(n log n) per
            # change wave — worse than the refresh path it replaces
            return
        handle.bounded = True
        handle.exec_sql = exec_sql
        handle.n_hidden = n_hidden
        handle.pk_items = items
        handle.pk_idx = {
            alias: list(range(len(cols) - n_hidden, len(cols)))
        }

    def _detect_incremental_count(self, handle: SubscriptionHandle,
                                  nsql: str, tables: Set[str],
                                  raw_tables: Set[str]) -> None:
        """Qualify ``SELECT COUNT(*) FROM t [WHERE …]`` for incremental
        membership counting (``_delta_count``): the single count row
        moves by the changed pks' net membership transitions, probed
        with the query's own WHERE scoped onto the pk index — never a
        re-aggregation.  Requirements: exactly that projection, one
        replicated from-item, and the scoped membership probe provably
        SEARCHes (never SCANs) the table."""
        if not re.match(r"SELECT\s+COUNT\(\s*\*\s*\)\s+FROM\b", nsql,
                        flags=re.IGNORECASE):
            return
        for stop in ("ORDER", "GROUP", "HAVING", "WINDOW"):
            if _top_level_word(nsql, stop) >= 0:
                return
        items, _spans = from_items_ex(nsql)
        if not items or len(items) != 1 or items[0][2]:
            return
        table, alias, _n = items[0]
        if alias.startswith("__corro_"):
            return
        if {table} != raw_tables or table not in tables:
            return
        info = self.agent.storage._tables.get(table)
        if info is None:
            return
        pk_cols_sql = ", ".join(
            f'"{alias}"."{c}"' for c in info.pk_cols
        )
        wi = _top_level_word(nsql, "WHERE")
        probe = (
            f"SELECT {pk_cols_sql} FROM {from_clause_text(nsql)}"
        )
        has_where = wi >= 0
        if has_where:
            # parenthesized so a top-level OR cannot out-bind the
            # scoping conjunction appended by _delta_count
            probe += f" WHERE ({nsql[wi + 5:].strip()})"
        row_ph = "(" + ", ".join("?" for _ in info.pk_cols) + ")"
        sep = " AND " if has_where else " WHERE "
        try:
            self.agent.storage.read_query(f"{probe} LIMIT 0")
            _, plan = self.agent.storage.read_query(
                f"EXPLAIN QUERY PLAN {probe}{sep}"
                f"(({pk_cols_sql}) IN (VALUES {row_ph}))",
                [None] * len(info.pk_cols),
            )
        except sqlite3.Error:
            return
        plan_text = " ".join(str(c) for row in plan for c in row)
        if not plan_mentions(plan_text, "SEARCH", alias) or \
                plan_mentions(plan_text, "SCAN", alias):
            return
        handle.count_only = True
        handle.count_full_probe = probe
        handle.count_has_where = has_where
        handle.count_pk_cols_sql = pk_cols_sql
        handle.pk_items = [items[0]]
        handle.pk_idx = {}

    def _detect_columnar(self, handle: SubscriptionHandle,
                         nsql: str) -> None:
        """Qualify an incremental single-table subscription for the
        shard matcher's columnar fast path: the verdict must be fully
        decidable from (pk, liveness, current row), i.e. a bare-column
        projection and either no WHERE or a pk IN-list predicate (the
        per-user subscription-list shape, single- or multi-column pk).
        Anything else keeps the per-sub oracle path."""
        if (
            not handle.incremental or handle.agg or handle.bounded
            or handle.count_only or handle.full_refresh_aliases
            or handle.pk_items is None or len(handle.pk_items) != 1
            or handle.pk_items[0][2]
        ):
            return
        table, alias, _n = handle.pk_items[0]
        info = self.agent.storage._tables.get(table)
        if info is None:
            return
        m = re.match(r"SELECT\s+", nsql, flags=re.IGNORECASE)
        fi = _top_level_word(nsql, "FROM")
        if not m or fi < 0:
            return
        for stop in ("ORDER", "GROUP", "LIMIT", "HAVING", "WINDOW"):
            if _top_level_word(nsql, stop) >= 0:
                return
        proj = self._parse_bare_projection(
            nsql[m.end():fi].strip(), alias, info.all_cols
        )
        if proj is None:
            return
        pk_filter = None
        wi = _top_level_word(nsql, "WHERE")
        if wi >= 0:
            pk_filter = self._parse_pk_in_list(
                nsql[wi + 5:].strip(), alias, table, list(info.pk_cols)
            )
            if pk_filter is None:
                return
        handle.columnar_spec = submatch.SubSpec(
            handle.id, table, tuple(proj), pk_filter
        )

    @staticmethod
    def _parse_bare_projection(sel: str, alias: str,
                               all_cols) -> Optional[List[int]]:
        """Map a select list of bare (optionally alias-qualified,
        optionally AS-renamed) column references onto declared-order
        column indices; None when any item is an expression."""
        col_pos = {c.lower(): i for i, c in enumerate(all_cols)}
        if sel == "*":
            return list(range(len(all_cols)))
        proj: List[int] = []
        # depth-0 comma split (an expression projection with a comma
        # inside parens never splits here — it just fails the regex)
        pieces, prev = [], 0
        for i, ch, depth in _scan_top_level(sel):
            if ch == "," and depth == 0:
                pieces.append(sel[prev:i])
                prev = i + 1
        pieces.append(sel[prev:])
        for piece in pieces:
            m = re.fullmatch(
                r'(?:(\w+)\.)?"?(\w+)"?(?:\s+AS\s+"?\w+"?)?',
                piece.strip(), flags=re.IGNORECASE,
            )
            if not m:
                return None
            qual, col = m.group(1), m.group(2)
            if qual is not None and qual != alias:
                return None
            pos = col_pos.get(col.lower())
            if pos is None:
                return None
            proj.append(pos)
        return proj

    def _parse_pk_in_list(self, where: str, alias: str, table: str,
                          pk_cols: List[str]):
        """Parse ``WHERE <pk> IN (…)`` / ``WHERE (<pk…>) IN (VALUES …)``
        into a packed-pk membership set, or None when the predicate is
        anything else.  Literal typing is affinity-checked against the
        declared pk column types: a quoted literal against an INTEGER
        pk (or vice versa) would rely on sqlite's affinity coercion,
        which Python-side packed-bytes equality cannot reproduce — such
        predicates stay on the oracle path."""
        try:
            _, tinfo = self.agent.storage.read_query(
                f'PRAGMA table_info("{table}")'
            )
        except sqlite3.Error:
            return None
        decl = {str(r[1]).lower(): str(r[2] or "").upper() for r in tinfo}

        def affinity(col: str) -> str:
            d = decl.get(col.lower(), "")
            if "INT" in d:
                return "int"
            if "CHAR" in d or "CLOB" in d or "TEXT" in d:
                return "text"
            return "other"

        def parse_lit(text: str, col: str):
            text = text.strip()
            aff = affinity(col)
            if re.fullmatch(r"-?\d+", text):
                return int(text) if aff == "int" else None
            m = re.fullmatch(r"'([^']*)'", text)
            if m is not None and aff == "text":
                return m.group(1)
            return None

        def col_ref(text: str) -> Optional[str]:
            m = re.fullmatch(
                r'(?:(\w+)\.)?"?(\w+)"?', text.strip()
            )
            if not m or (m.group(1) is not None and m.group(1) != alias):
                return None
            return m.group(2)

        pk_lower = [c.lower() for c in pk_cols]
        m = re.fullmatch(
            r"(.+?)\s+IN\s*\((.+)\)", where, flags=re.IGNORECASE | re.S
        )
        if not m:
            return None
        lhs, rhs = m.group(1).strip(), m.group(2).strip()
        if len(pk_cols) == 1 and not lhs.startswith("("):
            col = col_ref(lhs)
            if col is None or col.lower() != pk_lower[0]:
                return None
            vals = []
            for part in rhs.split(","):
                v = parse_lit(part, pk_cols[0])
                if v is None:
                    return None
                vals.append((v,))
            order = [0]
        else:
            mc = re.fullmatch(r"\((.+)\)", lhs, flags=re.S)
            if not mc:
                return None
            listed = []
            for part in mc.group(1).split(","):
                col = col_ref(part)
                if col is None:
                    return None
                listed.append(col.lower())
            # the listed columns must be exactly the pk, any order —
            # tuples are re-ordered into pk declaration order so the
            # packed bytes match the change stream's packed pks
            if sorted(listed) != sorted(pk_lower):
                return None
            order = [listed.index(c) for c in pk_lower]
            mv = re.match(r"VALUES\s*(.+)$", rhs,
                          flags=re.IGNORECASE | re.S)
            if not mv:
                return None
            tuples = re.findall(r"\(([^()]*)\)", mv.group(1))
            if not tuples:
                return None
            vals = []
            for tup in tuples:
                parts = tup.split(",")
                if len(parts) != len(pk_cols):
                    return None
                row = []
                for pos, col in zip(order, pk_cols):
                    v = parse_lit(parts[pos], col)
                    if v is None:
                        return None
                    row.append(v)
                vals.append(tuple(row))
        try:
            return frozenset(pack_values(list(v)) for v in vals)
        except Exception:
            return None

    def get(self, sub_id: str) -> Optional[SubscriptionHandle]:
        with self._lock:
            h = self._subs.get(sub_id)
            if h is not None:
                h.last_receiver_at = time.time()  # see subscribe()
            return h

    def list(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "id": h.id,
                    "sql": h.sql,
                    "tables": sorted(h.tables),
                    "rows": len(h.rows),
                    "last_change_id": h.last_change_id,
                    "incremental": h.incremental,
                    "receivers": len(h._streams),
                }
                for h in self._subs.values()
            ]

    def metric_gauges(self) -> List[tuple]:
        """Scrape-time subscription-plane gauges (the ROADMAP
        incremental-subs observability feed), emitted next to
        ``corro_subs_refresh_failures_total``:

        * ``corro_subs_pending_depth`` — queued candidate work summed
          across all matcher shards (full-refresh candidates + pk
          candidates + buffered wave changes), the pre-existing gauge;
        * ``corro_subs_matcher_queue_depth{shard=…}`` — one shard
          worker's whole backlog: queued candidates plus the round
          currently draining (a long-running refresh is load even
          after its candidates left the queue); a single hot shard is
          a routing skew, all shards hot is plane overload;
        * ``corro_subs_staleness_seconds{id=…}`` — seconds since each
          subscription's last SUCCESSFUL refresh/delta round; a rising
          series is a sub silently serving stale rows (its failures
          count in the refresh-failures counter)."""
        now = time.time()
        with self._lock:
            depths = [
                (s.idx, s.depth(), 1 if s.draining else 0)
                for s in self._shards
            ]
            stale = [
                (h.id, max(0.0, now - h.last_ok_at))
                for h in self._subs.values()
            ]
        out = [
            ("corro_subs_pending_depth",
             float(sum(d for _i, d, _dr in depths)), {}),
        ]
        out.extend(
            ("corro_subs_matcher_queue_depth", float(d + dr),
             {"shard": str(i)})
            for i, d, dr in depths
        )
        out.extend(
            ("corro_subs_staleness_seconds", round(age, 3), {"id": sid})
            for sid, age in stale
        )
        return out

    # -- change intake ---------------------------------------------------

    def on_change(self, cv: ChangeV1) -> None:
        """Called by the agent for every local commit + applied remote
        changeset (``match_changes`` parity) — from the group-commit
        broadcast collector (corro-wbcast) for local writes and the
        apply path for remote ones.  This method only ROUTES: per-table
        change waves to the shards indexing columnar subs on the table,
        per-sub pk candidates to the owning shard's queues.  No SQL, no
        matching — those run on the shard threads."""
        cs = cv.changeset
        touched: Dict[str, List] = {}
        for ch in cs.changes:
            touched.setdefault(ch.table, []).append(ch)
        woken: Set[int] = set()
        with self._lock:
            for h in self._subs.values():
                if h.columnar_spec is not None:
                    continue  # covered by the shard's wave buffer
                shard = self._shards[h.shard]
                if h.incremental:
                    hit = {t for t, _a, _n in h.pk_items if t in touched}
                    if hit:
                        per = shard.pending_pks.setdefault(h.id, {})
                        for t in hit:
                            per.setdefault(t, set()).update(
                                ch.pk for ch in touched[t]
                            )
                        woken.add(h.shard)
                elif any(t in h.tables for t in touched):
                    shard.pending.add(h.id)
                    woken.add(h.shard)
            for table, chs in touched.items():
                for shard in self._shards:
                    if shard.index.has(table):
                        shard.waves.setdefault(table, []).extend(chs)
                        woken.add(shard.idx)
            for i in woken:
                if self._shards[i].depth() > self._shard_max:
                    self._shards[i].overflow()
        for table, chs in touched.items():
            self._notify_updates(table, chs)
        for i in woken:
            self._shards[i].wake.set()

    SUB_GC_S = 120.0  # drop subs with no receivers this long (pubsub.rs GC)
    GC_SWEEP_S = 5.0

    def _fetch_rows(self, table: str, info,
                    pks: List[bytes]) -> Dict[bytes, tuple]:
        """Fetch current rows for a wave's live pks, ONCE per
        (table, wave) — the single database touch the columnar match
        pipeline makes.  Chunked to stay under sqlite's host-parameter
        limit; keyed back by packed pk so verdicts line up with the
        change stream's pk encoding."""
        pk_cols = list(info.pk_cols)
        npk = len(pk_cols)
        sel_cols = ", ".join(
            [f'"{c}"' for c in pk_cols]
            + [f'"{c}"' for c in info.all_cols]
        )
        key_sql = ", ".join(f'"{c}"' for c in pk_cols)
        chunk = max(1, FETCH_PARAM_BUDGET // npk)
        out: Dict[bytes, tuple] = {}
        for i in range(0, len(pks), chunk):
            batch = pks[i:i + chunk]
            values, params = [], []
            for pk in batch:
                cells = list(unpack_values(pk))
                if len(cells) != npk:
                    continue  # foreign-shaped pk cannot match a row
                values.append(
                    "(" + ", ".join("?" for _ in cells) + ")"
                )
                params.extend(cells)
            if not values:
                continue
            _, rows = self.agent.storage.read_query(
                f'SELECT {sel_cols} FROM "{table}"'
                f" WHERE ({key_sql}) IN (VALUES {', '.join(values)})",
                params,
            )
            for r in rows:
                out[pack_values(list(r[:npk]))] = tuple(r[npk:])
        return out

    def _drain_waves(self, shard: "_MatcherShard",
                     waves: Dict[str, List],
                     pending: Set[str]) -> None:
        """Columnar half of one shard round: resolve each table's
        buffered wave once through the merge kernel, fan verdicts to
        the shard's indexed predicates, and apply them per handle.  A
        handle the fast path cannot serve right now (no projection yet,
        fetch/apply error) degrades into ``pending`` — the oracle
        refresh in the same round covers it."""
        for table, changes in waves.items():
            subs = shard.index.subs_on(table)
            if not subs:
                continue
            self.agent.metrics.counter("corro_subs_columnar_rounds_total")
            info = self.agent.storage._tables.get(table)
            try:
                # the kernel coalesces the wave to one verdict slot per
                # pk; the fetch (DB truth) decides each slot's final
                # upsert/delete — see submatch.match_wave on why the
                # wave-local liveness bits are advisory only
                pks, _alive = submatch.resolve_wave(
                    changes, backend="numpy"
                )
                verdicts, n_pairs = submatch.match_wave(
                    shard.index, table, pks,
                    lambda need: self._fetch_rows(table, info, need),
                )
            except sqlite3.Error:
                self.agent.metrics.counter(
                    "corro_subs_delta_fallbacks_total"
                )
                pending |= subs
                continue
            if n_pairs:
                self.agent.metrics.counter(
                    "corro_subs_columnar_verdicts_total", n_pairs
                )
            now = time.time()
            for sub_id in subs:
                h = self._subs.get(sub_id)
                if h is None:
                    continue
                v = verdicts.get(sub_id)
                if not v:
                    # wave missed this sub's pk filter entirely — it is
                    # as fresh as a delta round that found no work
                    h.last_ok_at = now
                    continue
                if not h.columns:
                    # projection unknown until the initial refresh ran
                    pending.add(sub_id)
                    continue
                try:
                    h.apply_columnar(v)
                except sqlite3.Error:
                    self.agent.metrics.counter(
                        "corro_subs_delta_fallbacks_total"
                    )
                    pending.add(sub_id)

    def _drain_round(
        self, pending: Set[str],
        pending_pks: Dict[str, Dict[str, Set[bytes]]],
    ) -> None:
        """Process one popped batch of candidate work."""
        for sub_id, table_pks in pending_pks.items():
            if sub_id in pending:
                continue  # a full refresh covers the candidates
            h = self._subs.get(sub_id)
            if h is None:
                continue
            # the delta path needs the projection (first refresh) and
            # loses to a full pass beyond DELTA_MAX_PKS candidates
            total = sum(len(p) for p in table_pks.values())
            if not h.columns or total > DELTA_MAX_PKS:
                pending.add(sub_id)
                continue
            try:
                h.delta(table_pks)
            except sqlite3.Error:
                # correct but expensive; counted so a systemic
                # cause (e.g. busy storms) is visible in metrics
                self.agent.metrics.counter(
                    "corro_subs_delta_fallbacks_total"
                )
                pending.add(sub_id)  # fall back to a full pass
        with self._lock:
            handles = [self._subs[i] for i in pending if i in self._subs]
        for h in handles:
            try:
                h.refresh()
            except sqlite3.Error:
                # the candidate set stays pending-free, so the refresh
                # is simply LOST until the next change touches the sub's
                # tables — count it (a systemic cause, e.g. busy storms,
                # must be visible next to the delta-fallback counter)
                self.agent.metrics.counter(
                    "corro_subs_refresh_failures_total"
                )
                logger.debug(
                    "full refresh failed for sub %s", h.id, exc_info=True
                )

    def idle(self) -> bool:
        """True when no candidate work is queued OR in flight — the
        condition tests must wait on before measuring delta cost.
        Raises if the worker died: neither a hang (flag stuck) nor a
        silent True (batch never processed) is an acceptable answer."""
        if self._worker_died:
            raise RuntimeError("subscription worker thread died")
        with self._lock:
            return not any(
                s.pending or s.pending_pks or s.waves or s.draining
                for s in self._shards
            )

    def _gc_idle_subs(self) -> None:
        """Drop subscriptions nobody has streamed from in SUB_GC_S
        (``public/pubsub.rs:131-227``: matchers with zero receivers are
        garbage-collected after 120 s; a later identical subscribe
        simply recreates the state from a fresh snapshot)."""
        now = time.time()
        with self._lock:
            dead = [
                h for h in self._subs.values()
                if not h._streams and now - h.last_receiver_at > self.SUB_GC_S
            ]
            for h in dead:
                self._subs.pop(h.id, None)
                self._by_sql.pop(h.sql, None)
                self._shards[h.shard].index.remove(h.id)
        for h in dead:
            h.close()
            try:
                os.unlink(h.db_path)
            except OSError:
                pass
        if dead:
            self.agent.metrics.counter("corro_subs_gcd_total", len(dead))
        self.agent.metrics.gauge("corro_subs_active", len(self._subs))

    # -- table-level updates (updates.rs parity) -------------------------

    def table_updates(self, table: str):
        """Iterator of {"change": [kind, pk_cells]} events for one table.

        The queue registers EAGERLY (at call time), not lazily at the
        first ``next()``: since group commit moved ``on_change`` fan-out
        onto the wbcast worker, a write committed between creating the
        stream and first consuming it is delivered asynchronously — with
        lazy registration that event raced the first ``next()`` and,
        losing, was dropped, leaving the consumer blocked forever (an
        intermittent test_table_updates_stream hang).  An iterator
        OBJECT (not a generator): a generator abandoned before its
        first ``next()`` never runs its ``finally``, which would leak
        the eagerly-registered queue — close() is explicit and
        GC-backed."""
        q: queue.Queue = queue.Queue(maxsize=4096)
        self._update_streams.setdefault(table, []).append(q)
        return _TableUpdateStream(self, table, q)

    def _notify_updates(self, table: str, changes: List) -> None:
        streams = self._update_streams.get(table)
        if not streams:
            return
        seen_pks = set()
        for ch in changes:
            if ch.pk in seen_pks:
                continue
            seen_pks.add(ch.pk)
            # cl parity: even causal length means the row is deleted
            kind = "delete" if ch.cl % 2 == 0 else "upsert"
            cells = jsonable_row(unpack_values(ch.pk))
            for q in list(streams):
                try:
                    q.put_nowait({"change": [kind, cells]})
                except queue.Full:
                    # backpressure contract (docs/pubsub.md): a slow
                    # consumer loses its OLDEST buffered event, never
                    # stalls the intake path, and the loss is counted
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        pass
                    try:
                        q.put_nowait({"change": [kind, cells]})
                    except queue.Full:
                        pass
                    self.agent.metrics.counter(
                        "corro_subs_updates_dropped_total", table=table
                    )



class _TableUpdateStream:
    """Blocking iterator over one table's update queue.

    Cleanup is explicit (``close``) and GC-backed (``__del__``): the
    queue registered eagerly in :meth:`SubsManager.table_updates`, so a
    consumer that errors out before its first ``next()`` must still
    unregister — a generator's ``finally`` never runs for a
    never-started generator."""

    def __init__(self, manager: "SubsManager", table: str,
                 q: "queue.Queue"):
        self._manager = manager
        self._table = table
        self._q = q
        self._closed = False

    def __iter__(self) -> "_TableUpdateStream":
        return self

    def __next__(self) -> dict:
        while True:
            try:
                return self._q.get(timeout=30.0)
            except queue.Empty:
                continue

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._manager._update_streams.get(
                self._table, []
            ).remove(self._q)
        except ValueError:
            pass

    def __del__(self) -> None:  # GC fallback for abandoned streams
        self.close()
