"""Lock observability.

Parity: the reference has no TSan — instead a bespoke ``LockRegistry``:
every counted-lock acquisition is registered with a label/state/started_at
(``corro-types/src/agent.rs:958-1181``), a watchdog logs locks held >10 s
(``setup.rs:186-230``), and ``corrosion locks`` surfaces it via admin.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass
class LockEntry:
    id: int
    label: str
    kind: str  # "write" | "apply" | "read"
    state: str  # "acquiring" | "locked"
    started_at: float


class LockRegistry:
    """Tracks in-flight lock acquisitions for the admin `locks` command."""

    def __init__(self):
        self._entries: Dict[int, LockEntry] = {}
        self._guard = threading.Lock()
        self._ids = itertools.count(1)
        self.slow_threshold_s = 10.0

    def begin(self, label: str, kind: str) -> int:
        lid = next(self._ids)
        with self._guard:
            self._entries[lid] = LockEntry(
                lid, label, kind, "acquiring", time.monotonic()
            )
        return lid

    def acquired(self, lid: int) -> None:
        with self._guard:
            e = self._entries.get(lid)
            if e:
                e.state = "locked"
                e.started_at = time.monotonic()

    def released(self, lid: int) -> None:
        with self._guard:
            self._entries.pop(lid, None)

    def snapshot(self) -> List[dict]:
        now = time.monotonic()
        with self._guard:
            return [
                {
                    "id": e.id,
                    "label": e.label,
                    "kind": e.kind,
                    "state": e.state,
                    "held_s": round(now - e.started_at, 3),
                }
                for e in self._entries.values()
            ]

    def slow(self) -> List[dict]:
        return [
            e for e in self.snapshot() if e["held_s"] > self.slow_threshold_s
        ]


class TrackedLock:
    """An RLock whose acquisitions appear in a LockRegistry."""

    def __init__(self, registry: LockRegistry, default_label: str = "storage"):
        self._lock = threading.RLock()
        self.registry = registry
        self.default_label = default_label
        self._local = threading.local()  # per-thread stack of entry ids

    def hold(self, label: str, kind: str = "write"):
        return _Hold(self, label, kind)

    # RLock interface (so it can drop in where threading.RLock was used)
    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        return self._lock.release()

    def __enter__(self):
        lid = self.registry.begin(self.default_label, "write")
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        stack.append(lid)
        self._lock.acquire()
        self.registry.acquired(lid)
        return self

    def __exit__(self, *exc):
        stack = getattr(self._local, "stack", [])
        if stack:
            self.registry.released(stack.pop())
        self._lock.release()
        return False


class _Hold:
    def __init__(self, lock: TrackedLock, label: str, kind: str):
        self.lock = lock
        self.label = label
        self.kind = kind
        self.lid: Optional[int] = None

    def __enter__(self):
        self.lid = self.lock.registry.begin(self.label, self.kind)
        self.lock.acquire()
        self.lock.registry.acquired(self.lid)
        return self

    def __exit__(self, *exc):
        self.lock.registry.released(self.lid)
        self.lock.release()
        return False
