"""Lock observability.

Parity: the reference has no TSan — instead a bespoke ``LockRegistry``:
every counted-lock acquisition is registered with a label/state/started_at
(``corro-types/src/agent.rs:958-1181``), a watchdog logs locks held >10 s
(``setup.rs:186-230``), and ``corrosion locks`` surfaces it via admin.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass
class LockEntry:
    id: int
    label: str
    kind: str  # "write" | "apply" | "read"
    state: str  # "acquiring" | "locked"
    started_at: float


class LockRegistry:
    """Tracks in-flight lock acquisitions for the admin `locks` command."""

    def __init__(self):
        self._entries: Dict[int, LockEntry] = {}
        self._guard = threading.Lock()
        self._ids = itertools.count(1)
        self.slow_threshold_s = 10.0

    def begin(self, label: str, kind: str) -> int:
        lid = next(self._ids)
        with self._guard:
            self._entries[lid] = LockEntry(
                lid, label, kind, "acquiring", time.monotonic()
            )
        return lid

    def acquired(self, lid: int) -> None:
        with self._guard:
            e = self._entries.get(lid)
            if e:
                e.state = "locked"
                e.started_at = time.monotonic()

    def released(self, lid: int) -> None:
        with self._guard:
            self._entries.pop(lid, None)

    def snapshot(self) -> List[dict]:
        now = time.monotonic()
        with self._guard:
            return [
                {
                    "id": e.id,
                    "label": e.label,
                    "kind": e.kind,
                    "state": e.state,
                    "held_s": round(now - e.started_at, 3),
                }
                for e in self._entries.values()
            ]

    def slow(self) -> List[dict]:
        return [
            e for e in self.snapshot() if e["held_s"] > self.slow_threshold_s
        ]


PRIO_HIGH, PRIO_NORMAL, PRIO_LOW = 0, 1, 2


class PriorityLock:
    """Reentrant mutex with 3 acquisition tiers (write-pool parity).

    The reference splits writes across three priority pools — high for
    applying replicated changes, normal for API writes, low for
    background maintenance (``agent.rs:614-765``,
    ``sqlite-pool/src/lib.rs``).  One sqlite RW connection can't run
    concurrent transactions, so the pools collapse to a SCHEDULING
    question: when the writer frees, the highest-priority waiter goes
    next (FIFO-fair within a tier via Condition wakeup order being
    irrelevant — every waiter re-checks).  Plain ``with lock:`` takes
    NORMAL; hot paths say ``with lock.prio(PRIO_HIGH, "apply"):``.

    Optionally registers acquisitions in a LockRegistry so the admin
    ``locks`` surface sees priority waits like any other.
    """

    def __init__(self, registry: Optional[LockRegistry] = None,
                 default_label: str = "storage"):
        self._cv = threading.Condition()
        self._owner: Optional[int] = None
        self._count = 0
        self._waiting = [0, 0, 0]
        self.registry = registry
        self.default_label = default_label
        self._local = threading.local()  # per-thread entry-id stack

    def acquire(self, priority: int = PRIO_NORMAL,
                timeout: Optional[float] = None) -> bool:
        """Acquire at ``priority``; with ``timeout`` give up after that
        many seconds and return False (best-effort readers — e.g. the
        metrics scrape — must degrade to stale data, not block behind
        a long writer)."""
        me = threading.get_ident()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            if self._owner == me:
                self._count += 1
                return True
            self._waiting[priority] += 1
            timed_out = False
            try:
                while self._owner is not None or any(
                    self._waiting[p] for p in range(priority)
                ):
                    remaining = (
                        None if deadline is None
                        else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        timed_out = True
                        return False
                    self._cv.wait(remaining)
                self._owner = me
                self._count = 1
                return True
            finally:
                self._waiting[priority] -= 1
                if timed_out:
                    # our _waiting slot may have gated lower tiers past
                    # a notify_all they consumed by re-waiting; now that
                    # the slot is gone, wake them so nobody sleeps on a
                    # free lock
                    self._cv.notify_all()

    def release(self) -> None:
        with self._cv:
            if self._owner != threading.get_ident():
                raise RuntimeError("release of un-owned PriorityLock")
            self._count -= 1
            if self._count == 0:
                self._owner = None
                self._cv.notify_all()

    def prio(self, priority: int, label: Optional[str] = None,
             kind: str = "write"):
        return _PrioHold(self, priority, label or self.default_label, kind)

    # plain `with lock:` == normal priority
    def __enter__(self):
        self._track_begin(self.default_label, "write")
        self.acquire(PRIO_NORMAL)
        self._track_acquired()
        return self

    def __exit__(self, *exc):
        self._track_released()
        self.release()
        return False

    # registry plumbing (no-ops when untracked)
    def _track_begin(self, label: str, kind: str) -> None:
        if self.registry is None:
            return
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        stack.append(self.registry.begin(label, kind))

    def _track_acquired(self) -> None:
        if self.registry is None:
            return
        stack = getattr(self._local, "stack", [])
        if stack:
            self.registry.acquired(stack[-1])

    def _track_released(self) -> None:
        if self.registry is None:
            return
        stack = getattr(self._local, "stack", [])
        if stack:
            self.registry.released(stack.pop())


class _PrioHold:
    def __init__(self, lock: PriorityLock, priority: int, label: str,
                 kind: str):
        self.lock = lock
        self.priority = priority
        self.label = label
        self.kind = kind

    def __enter__(self):
        self.lock._track_begin(self.label, self.kind)
        self.lock.acquire(self.priority)
        self.lock._track_acquired()
        return self

    def __exit__(self, *exc):
        self.lock._track_released()
        self.lock.release()
        return False

