"""Minimal distributed tracing with cross-node propagation.

Parity: the reference attaches a W3C ``traceparent`` to the sync
handshake (``crates/corro-types/src/sync.rs:32-67`` SyncTraceContextV1)
and re-parents the server's span on it (``api/peer.rs`` serve_sync /
parallel_sync).  This is the same propagation with a deliberately small
surface: spans log one structured line on end (tagged ``trace_id`` /
``span_id`` / duration) and land in a bounded in-memory ring for
introspection — no OTLP exporter exists in this image, so the log line
IS the export.
"""

from __future__ import annotations

import contextvars
import logging
import os
import re
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Optional

log = logging.getLogger("corrosion_tpu.trace")

_current: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "corro_current_span", default=None
)

# bounded export ring (admin/debug surface)
RECENT_MAX = 1024
_recent: deque = deque(maxlen=RECENT_MAX)


@dataclass
class Span:
    name: str
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    parent_id: Optional[str] = None
    start: float = field(default_factory=time.time)  # wall, for display
    start_mono: float = field(default_factory=time.monotonic)
    end: Optional[float] = None
    dur_ms: Optional[float] = None  # from the monotonic clock
    attrs: Dict[str, object] = field(default_factory=dict)

    @property
    def traceparent(self) -> str:
        """W3C trace-context header value (version 00, sampled)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self


_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def parse_traceparent(value: Optional[str]):
    """(trace_id, parent_span_id) from a W3C traceparent, or None.

    Strict hex validation: the string comes off the wire from a peer
    and ends up in log lines and the admin span ring — length checks
    alone would let an attacker inject arbitrary bytes there."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value)
    if m is None:
        return None
    return m.group(1), m.group(2)


class span:
    """Context manager: opens a Span, parents it on ``remote`` (a
    traceparent string) or on the task's current span, logs one line on
    exit.  Works in both sync and async code (no awaits inside)."""

    def __init__(self, name: str, remote: Optional[str] = None, **attrs):
        self.name = name
        self.remote = remote
        self.attrs = attrs
        self.span: Optional[Span] = None
        self._token = None

    def __enter__(self) -> Span:
        parent = _current.get()
        remote = parse_traceparent(self.remote)
        if remote is not None:
            trace_id, parent_id = remote
        elif parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = os.urandom(16).hex(), None
        self.span = Span(
            name=self.name,
            trace_id=trace_id,
            span_id=os.urandom(8).hex(),
            parent_id=parent_id,
            attrs=dict(self.attrs),
        )
        self._token = _current.set(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        s = self.span
        s.end = time.time()
        s.dur_ms = (time.monotonic() - s.start_mono) * 1000.0
        if exc is not None:
            s.attrs["error"] = repr(exc)
        _current.reset(self._token)
        _finish(s)


def _finish(s: Span) -> None:
    """The one span-finish sequence — ring append, export, log line —
    shared by live spans (``span().__exit__``) and post-hoc ones
    (:func:`record`), so the two can't drift."""
    _recent.append(s)
    _export(s)
    extras = " ".join(f"{k}={v}" for k, v in sorted(s.attrs.items()))
    log.info(
        "span %s trace_id=%s span_id=%s parent_id=%s dur_ms=%.1f %s",
        s.name, s.trace_id, s.span_id, s.parent_id or "-",
        s.dur_ms, extras,
    )


def current_traceparent() -> Optional[str]:
    s = _current.get()
    return s.traceparent if s is not None else None


def record(name: str, remote: Optional[str] = None,
           duration_ms: float = 0.0, **attrs) -> Optional[Span]:
    """Record an already-finished span after the fact.

    Hot paths that only decide to trace once the outcome is known (e.g.
    a broadcast apply that turns out to be a version's FIRST arrival)
    use this instead of wrapping every candidate in a live ``span()`` —
    the non-news duplicates would otherwise dominate the ring.  The
    span parents on ``remote`` (a traceparent) or the task's current
    span; ``duration_ms`` is caller-measured.  Returns the Span, or
    None when ``remote`` was given but unparseable (junk off the wire
    must not mint orphan traces)."""
    parsed = parse_traceparent(remote) if remote is not None else None
    if remote is not None and parsed is None:
        return None
    if parsed is not None:
        trace_id, parent_id = parsed
    else:
        cur = _current.get()
        if cur is not None:
            trace_id, parent_id = cur.trace_id, cur.span_id
        else:
            trace_id, parent_id = os.urandom(16).hex(), None
    now = time.time()
    s = Span(
        name=name,
        trace_id=trace_id,
        span_id=os.urandom(8).hex(),
        parent_id=parent_id,
        start=now - duration_ms / 1000.0,
        attrs=dict(attrs),
    )
    s.end = now
    s.dur_ms = duration_ms
    _finish(s)
    return s


# -- file export (the OTLP stand-in) ----------------------------------
#
# The reference ships spans to an OTLP endpoint (CLI main.rs tracing
# init); no OTel SDK exists in this image, so the configurable export
# is OTLP-flavored span records, one JSON object per line, consumable
# by a collector's file receiver or plain jq.

import json as _json
import threading as _threading

_sink_lock = _threading.Lock()
_sink = None  # open file object
_sink_gen = 0  # bumps on every (re)configure: the ownership token
_sink_path: Optional[str] = None
_sink_max_bytes = 0  # 0 = unbounded (legacy behavior)
_sink_bytes = 0  # bytes in the active file
_sink_rotated = False  # one rotation per configure generation
_sink_dead = False  # post-rotation reopen failed: sink gone, keep counting
_dropped_total = 0  # spans dropped post-rotation (process lifetime)

# default export bound: two ~64 MiB files (active + one rotation)
DEFAULT_EXPORT_MAX_BYTES = 64 * 1024 * 1024


def configure_export(path: Optional[str],
                     max_bytes: int = DEFAULT_EXPORT_MAX_BYTES
                     ) -> Optional[int]:
    """Append finished spans to ``path`` (None disables).  Process-wide,
    like the tracing runtime itself.  Returns an ownership token for
    :func:`disable_export_if` (None when disabling).

    The export is BOUNDED: once the active file exceeds ``max_bytes``
    it rotates ONCE to ``path + ".1"`` (overwriting a previous
    rotation); if the fresh file fills again, further spans are dropped
    and counted (:func:`export_dropped_total`, surfaced as
    ``corro_trace_spans_dropped_total``) — an append-forever spans file
    must not eat the disk out from under the database.  ``max_bytes=0``
    disables the bound."""
    global _sink, _sink_gen, _sink_path, _sink_max_bytes
    global _sink_bytes, _sink_rotated, _sink_dead
    with _sink_lock:
        _sink_gen += 1
        if _sink is not None:
            try:
                _sink.close()
            except OSError:
                pass
            _sink = None
        _sink_path = None
        _sink_rotated = False
        _sink_dead = False
        _sink_bytes = 0
        if path:
            _sink = open(path, "a", buffering=1)
            _sink_path = path
            _sink_max_bytes = max(0, int(max_bytes))
            try:
                _sink_bytes = os.path.getsize(path)
            except OSError:
                _sink_bytes = 0
            return _sink_gen
        return None


def export_dropped_total() -> int:
    """Spans dropped by the bounded export since process start (the
    sink — and therefore this counter — is process-wide)."""
    with _sink_lock:
        return _dropped_total


def export_token_active(token: Optional[int]) -> bool:
    """Whether ``token`` is the generation that opened the CURRENTLY
    active sink.  A superseded owner (another agent reconfigured the
    process-wide export after it) must stop claiming the drop total,
    or every past owner syncs the same delta into its own counter and
    the family sums to an n-owners-fold overcount."""
    if token is None:
        return False
    with _sink_lock:
        return _sink_gen == token


def _rotate_or_drop_locked(line_len: int) -> bool:
    """Under ``_sink_lock``: make room for one more line.  Returns True
    when the write may proceed (possibly into a freshly-rotated file),
    False when the span must drop."""
    global _sink, _sink_bytes, _sink_rotated, _dropped_total, _sink_dead
    if _sink_max_bytes <= 0 or _sink_bytes + line_len <= _sink_max_bytes:
        return True
    if _sink_rotated:
        _dropped_total += 1
        return False
    # single rotation: active file -> path.1 (replacing any previous
    # rotation), then a fresh active file.  Total on-disk footprint
    # stays <= 2 * max_bytes for the life of this sink.
    _sink_rotated = True
    try:
        _sink.close()
    except OSError:
        pass
    try:
        os.replace(_sink_path, _sink_path + ".1")
    except OSError:
        pass
    try:
        _sink = open(_sink_path, "w", buffering=1)
    except OSError:
        # the sink is DEAD, not disabled: every later span is a drop
        # and must keep counting (_export checks _sink_dead), or the
        # drop counter freezes while spans silently vanish
        _sink = None
        _sink_dead = True
        _dropped_total += 1
        return False
    _sink_bytes = 0
    return True


def disable_export_if(token: Optional[int]) -> None:
    """Disable the sink only if ``token`` is the one that opened the
    currently-active sink — in a multi-agent process, an agent must not
    kill a sink another still-running agent has since (re)opened.
    Check and close happen under one lock acquisition."""
    global _sink, _sink_gen, _sink_dead
    if token is None:
        return
    with _sink_lock:
        if _sink_gen != token:
            return
        if _sink is None and not _sink_dead:
            return
        _sink_gen += 1
        if _sink is not None:
            try:
                _sink.close()
            except OSError:
                pass
        _sink = None
        _sink_dead = False


def _export(s: Span) -> None:
    global _sink_bytes, _dropped_total
    with _sink_lock:
        if _sink is None:
            if _sink_dead:
                # configured sink whose post-rotation reopen failed:
                # these are DROPS and must keep counting — a frozen
                # counter reads as a healthy export while spans vanish
                _dropped_total += 1
            return
        rec = {
            "traceId": s.trace_id,
            "spanId": s.span_id,
            "parentSpanId": s.parent_id or "",
            "name": s.name,
            "startTimeUnixNano": int(s.start * 1e9),
            "endTimeUnixNano": int((s.end or s.start) * 1e9),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in sorted(s.attrs.items())
            ],
        }
        line = _json.dumps(rec) + "\n"
        if not _rotate_or_drop_locked(len(line)):
            return
        try:
            _sink.write(line)
            _sink_bytes += len(line)
        except OSError:
            pass


def recent_spans(limit: int = 100, trace_id: Optional[str] = None):
    """Most recent finished spans, newest last (admin surface).  A
    non-positive limit returns none — ``[-0:]`` would invert the bound
    and dump the whole ring.  ``trace_id`` filters to one trace BEFORE
    the limit applies, so a whole cross-node trace can be assembled
    from each node's ring without grepping the full dump."""
    if limit <= 0:
        return []
    spans = list(_recent)
    if trace_id is not None:
        spans = [s for s in spans if s.trace_id == trace_id]
    return spans[-limit:]
