"""Minimal distributed tracing with cross-node propagation.

Parity: the reference attaches a W3C ``traceparent`` to the sync
handshake (``crates/corro-types/src/sync.rs:32-67`` SyncTraceContextV1)
and re-parents the server's span on it (``api/peer.rs`` serve_sync /
parallel_sync).  This is the same propagation with a deliberately small
surface: spans log one structured line on end (tagged ``trace_id`` /
``span_id`` / duration) and land in a bounded in-memory ring for
introspection — no OTLP exporter exists in this image, so the log line
IS the export.
"""

from __future__ import annotations

import contextvars
import logging
import os
import re
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Optional

log = logging.getLogger("corrosion_tpu.trace")

_current: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "corro_current_span", default=None
)

# bounded export ring (admin/debug surface)
RECENT_MAX = 1024
_recent: deque = deque(maxlen=RECENT_MAX)


@dataclass
class Span:
    name: str
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    parent_id: Optional[str] = None
    start: float = field(default_factory=time.time)  # wall, for display
    start_mono: float = field(default_factory=time.monotonic)
    end: Optional[float] = None
    dur_ms: Optional[float] = None  # from the monotonic clock
    attrs: Dict[str, object] = field(default_factory=dict)

    @property
    def traceparent(self) -> str:
        """W3C trace-context header value (version 00, sampled)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self


_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def parse_traceparent(value: Optional[str]):
    """(trace_id, parent_span_id) from a W3C traceparent, or None.

    Strict hex validation: the string comes off the wire from a peer
    and ends up in log lines and the admin span ring — length checks
    alone would let an attacker inject arbitrary bytes there."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value)
    if m is None:
        return None
    return m.group(1), m.group(2)


class span:
    """Context manager: opens a Span, parents it on ``remote`` (a
    traceparent string) or on the task's current span, logs one line on
    exit.  Works in both sync and async code (no awaits inside)."""

    def __init__(self, name: str, remote: Optional[str] = None, **attrs):
        self.name = name
        self.remote = remote
        self.attrs = attrs
        self.span: Optional[Span] = None
        self._token = None

    def __enter__(self) -> Span:
        parent = _current.get()
        remote = parse_traceparent(self.remote)
        if remote is not None:
            trace_id, parent_id = remote
        elif parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = os.urandom(16).hex(), None
        self.span = Span(
            name=self.name,
            trace_id=trace_id,
            span_id=os.urandom(8).hex(),
            parent_id=parent_id,
            attrs=dict(self.attrs),
        )
        self._token = _current.set(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        s = self.span
        s.end = time.time()
        s.dur_ms = (time.monotonic() - s.start_mono) * 1000.0
        if exc is not None:
            s.attrs["error"] = repr(exc)
        _current.reset(self._token)
        _recent.append(s)
        _export(s)
        extras = " ".join(f"{k}={v}" for k, v in sorted(s.attrs.items()))
        log.info(
            "span %s trace_id=%s span_id=%s parent_id=%s dur_ms=%.1f %s",
            s.name, s.trace_id, s.span_id, s.parent_id or "-",
            s.dur_ms, extras,
        )


def current_traceparent() -> Optional[str]:
    s = _current.get()
    return s.traceparent if s is not None else None


# -- file export (the OTLP stand-in) ----------------------------------
#
# The reference ships spans to an OTLP endpoint (CLI main.rs tracing
# init); no OTel SDK exists in this image, so the configurable export
# is OTLP-flavored span records, one JSON object per line, consumable
# by a collector's file receiver or plain jq.

import json as _json
import threading as _threading

_sink_lock = _threading.Lock()
_sink = None  # open file object
_sink_gen = 0  # bumps on every (re)configure: the ownership token


def configure_export(path: Optional[str]) -> Optional[int]:
    """Append finished spans to ``path`` (None disables).  Process-wide,
    like the tracing runtime itself.  Returns an ownership token for
    :func:`disable_export_if` (None when disabling)."""
    global _sink, _sink_gen
    with _sink_lock:
        _sink_gen += 1
        if _sink is not None:
            try:
                _sink.close()
            except OSError:
                pass
            _sink = None
        if path:
            _sink = open(path, "a", buffering=1)
            return _sink_gen
        return None


def disable_export_if(token: Optional[int]) -> None:
    """Disable the sink only if ``token`` is the one that opened the
    currently-active sink — in a multi-agent process, an agent must not
    kill a sink another still-running agent has since (re)opened.
    Check and close happen under one lock acquisition."""
    global _sink, _sink_gen
    if token is None:
        return
    with _sink_lock:
        if _sink_gen != token or _sink is None:
            return
        _sink_gen += 1
        try:
            _sink.close()
        except OSError:
            pass
        _sink = None


def _export(s: Span) -> None:
    with _sink_lock:
        if _sink is None:
            return
        rec = {
            "traceId": s.trace_id,
            "spanId": s.span_id,
            "parentSpanId": s.parent_id or "",
            "name": s.name,
            "startTimeUnixNano": int(s.start * 1e9),
            "endTimeUnixNano": int((s.end or s.start) * 1e9),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in sorted(s.attrs.items())
            ],
        }
        try:
            _sink.write(_json.dumps(rec) + "\n")
        except OSError:
            pass


def recent_spans(limit: int = 100):
    """Most recent finished spans, newest last (admin surface).  A
    non-positive limit returns none — ``[-0:]`` would invert the bound
    and dump the whole ring."""
    if limit <= 0:
        return []
    return list(_recent)[-limit:]
