"""The host agent: a real, runnable distributed-SQLite node.

This half of the framework mirrors the reference's serving surface
(SURVEY.md §1 layers 1-12): a CRDT storage engine over stock sqlite3
(our own implementation of the cr-sqlite semantics — the reference
vendors a prebuilt C extension, ``crates/corro-types/crsqlite-*.so``),
version bookkeeping, gossip membership + dissemination, anti-entropy
sync, HTTP API, reactive subscriptions, and the CLI/devcluster tooling.

The TPU simulator (:mod:`corrosion_tpu.sim`) shares the same wire types
and merge semantics, which is what lets sim traces be diffed against real
agents at small N.
"""
