"""PostgreSQL SQLSTATE error codes + the sqlite→SQLSTATE mapping.

Parity: ``crates/corro-pg/src/sql_state.rs`` — the reference carries
the full PG error-code table and tags every ErrorResponse with the
right class.  This module is the same idea in two parts:

* ``SQLSTATE``: name → five-char code, covering every class (00-XX)
  and the condition names the wire actually emits (drivers switch on
  these — e.g. psycopg maps 23505 to ``UniqueViolation``, SQLAlchemy
  retries on 40001/40P01, ORMs surface 23502/23503 as field errors);
* :func:`sqlstate_for`: map a raised exception — usually a
  ``sqlite3.Error``, whose message text is the only classification
  sqlite offers — onto the PG code a real server would send for the
  same fault.

``PgError`` carries an explicit code through the session layer so
protocol-level faults (unknown portal, cancel, feature gaps) do not
collapse into a generic syntax error.
"""

from __future__ import annotations

import sqlite3

# name -> code, grouped by PG error class (Appendix A of the PG docs;
# the reference's sql_state.rs carries the same table as constants)
SQLSTATE = {
    # 00/01/02 — success, warning, no data
    "successful_completion": "00000",
    "warning": "01000",
    "no_data": "02000",
    # 03/08 — SQL-statement-not-yet-complete, connection exceptions
    "sql_statement_not_yet_complete": "03000",
    "connection_exception": "08000",
    "connection_does_not_exist": "08003",
    "connection_failure": "08006",
    "sqlclient_unable_to_establish_sqlconnection": "08001",
    "sqlserver_rejected_establishment_of_sqlconnection": "08004",
    "transaction_resolution_unknown": "08007",
    "protocol_violation": "08P01",
    # 0A — feature not supported
    "feature_not_supported": "0A000",
    # 0B/0F/0L/0P — invalid transaction initiation, locator, grantor
    "invalid_transaction_initiation": "0B000",
    "locator_exception": "0F000",
    "invalid_grantor": "0L000",
    "invalid_role_specification": "0P000",
    # 20/21 — case not found, cardinality violation
    "case_not_found": "20000",
    "cardinality_violation": "21000",
    # 22 — data exceptions
    "data_exception": "22000",
    "string_data_right_truncation": "22001",
    "null_value_no_indicator_parameter": "22002",
    "numeric_value_out_of_range": "22003",
    "null_value_not_allowed": "22004",
    "error_in_assignment": "22005",
    "invalid_datetime_format": "22007",
    "datetime_field_overflow": "22008",
    "invalid_time_zone_displacement_value": "22009",
    "escape_character_conflict": "2200B",
    "invalid_use_of_escape_character": "2200C",
    "invalid_escape_octet": "2200D",
    "zero_length_character_string": "2200F",
    "most_specific_type_mismatch": "2200G",
    "not_an_xml_document": "2200L",
    "invalid_xml_document": "2200M",
    "invalid_argument_for_logarithm": "2201E",
    "invalid_argument_for_ntile_function": "22014",
    "invalid_argument_for_nth_value_function": "22016",
    "invalid_argument_for_power_function": "2201F",
    "invalid_argument_for_width_bucket_function": "2201G",
    "invalid_row_count_in_limit_clause": "2201W",
    "invalid_row_count_in_result_offset_clause": "2201X",
    "character_not_in_repertoire": "22021",
    "indicator_overflow": "22022",
    "invalid_parameter_value": "22023",
    "unterminated_c_string": "22024",
    "invalid_escape_sequence": "22025",
    "string_data_length_mismatch": "22026",
    "trim_error": "22027",
    "array_subscript_error": "2202E",
    "floating_point_exception": "22P01",
    "invalid_text_representation": "22P02",
    "invalid_binary_representation": "22P03",
    "bad_copy_file_format": "22P04",
    "untranslatable_character": "22P05",
    "nonstandard_use_of_escape_character": "22P06",
    "division_by_zero": "22012",
    # 23 — integrity constraint violations
    "integrity_constraint_violation": "23000",
    "restrict_violation": "23001",
    "not_null_violation": "23502",
    "foreign_key_violation": "23503",
    "unique_violation": "23505",
    "check_violation": "23514",
    "exclusion_violation": "23P01",
    # 24/25 — invalid cursor/transaction state
    "invalid_cursor_state": "24000",
    "invalid_transaction_state": "25000",
    "active_sql_transaction": "25001",
    "branch_transaction_already_active": "25002",
    "inappropriate_access_mode_for_branch_transaction": "25003",
    "inappropriate_isolation_level_for_branch_transaction": "25004",
    "no_active_sql_transaction_for_branch_transaction": "25005",
    "read_only_sql_transaction": "25006",
    "schema_and_data_statement_mixing_not_supported": "25007",
    "held_cursor_requires_same_isolation_level": "25008",
    "no_active_sql_transaction": "25P01",
    "in_failed_sql_transaction": "25P02",
    "idle_in_transaction_session_timeout": "25P03",
    # 26/27/28 — invalid statement name, triggered data change, authz
    "invalid_sql_statement_name": "26000",
    "triggered_data_change_violation": "27000",
    "invalid_authorization_specification": "28000",
    "invalid_password": "28P01",
    # 2B/2D/2F — dependent objects, transaction termination, SQL routine
    "dependent_privilege_descriptors_still_exist": "2B000",
    "dependent_objects_still_exist": "2BP01",
    "invalid_transaction_termination": "2D000",
    "sql_routine_exception": "2F000",
    # 34 — invalid cursor name
    "invalid_cursor_name": "34000",
    # 38/39/3B/3D/3F — external routine, savepoint, catalog, schema
    "external_routine_exception": "38000",
    "external_routine_invocation_exception": "39000",
    "savepoint_exception": "3B000",
    "invalid_savepoint_specification": "3B001",
    "invalid_catalog_name": "3D000",
    "invalid_schema_name": "3F000",
    # 40 — transaction rollback
    "transaction_rollback": "40000",
    "transaction_integrity_constraint_violation": "40002",
    "serialization_failure": "40001",
    "statement_completion_unknown": "40003",
    "deadlock_detected": "40P01",
    # 42 — syntax error or access rule violation
    "syntax_error_or_access_rule_violation": "42000",
    "syntax_error": "42601",
    "insufficient_privilege": "42501",
    "cannot_coerce": "42846",
    "grouping_error": "42803",
    "windowing_error": "42P20",
    "invalid_recursion": "42P19",
    "invalid_foreign_key": "42830",
    "invalid_name": "42602",
    "name_too_long": "42622",
    "reserved_name": "42939",
    "datatype_mismatch": "42804",
    "indeterminate_datatype": "42P18",
    "collation_mismatch": "42P21",
    "indeterminate_collation": "42P22",
    "wrong_object_type": "42809",
    "undefined_column": "42703",
    "undefined_function": "42883",
    "undefined_table": "42P01",
    "undefined_parameter": "42P02",
    "undefined_object": "42704",
    "duplicate_column": "42701",
    "duplicate_cursor": "42P03",
    "duplicate_database": "42P04",
    "duplicate_function": "42723",
    "duplicate_prepared_statement": "42P05",
    "duplicate_schema": "42P06",
    "duplicate_table": "42P07",
    "duplicate_alias": "42712",
    "duplicate_object": "42710",
    "ambiguous_column": "42702",
    "ambiguous_function": "42725",
    "ambiguous_parameter": "42P08",
    "ambiguous_alias": "42P09",
    "invalid_column_reference": "42P10",
    "invalid_column_definition": "42611",
    "invalid_cursor_definition": "42P11",
    "invalid_database_definition": "42P12",
    "invalid_function_definition": "42P13",
    "invalid_prepared_statement_definition": "42P14",
    "invalid_schema_definition": "42P15",
    "invalid_table_definition": "42P16",
    "invalid_object_definition": "42P17",
    # 53/54/55/57/58 — resources, limits, object state, operator
    # intervention, system errors
    "insufficient_resources": "53000",
    "disk_full": "53100",
    "out_of_memory": "53200",
    "too_many_connections": "53300",
    "configuration_limit_exceeded": "53400",
    "program_limit_exceeded": "54000",
    "statement_too_complex": "54001",
    "too_many_columns": "54011",
    "too_many_arguments": "54023",
    "object_not_in_prerequisite_state": "55000",
    "object_in_use": "55006",
    "cant_change_runtime_param": "55P02",
    "lock_not_available": "55P03",
    "operator_intervention": "57000",
    "query_canceled": "57014",
    "admin_shutdown": "57P01",
    "crash_shutdown": "57P02",
    "cannot_connect_now": "57P03",
    "database_dropped": "57P04",
    "system_error": "58000",
    "io_error": "58030",
    "undefined_file": "58P01",
    "duplicate_file": "58P02",
    # F0/HV/P0/XX — config file, FDW, PL/pgSQL, internal
    "config_file_error": "F0000",
    "lock_file_exists": "F0001",
    "fdw_error": "HV000",
    "plpgsql_error": "P0000",
    "raise_exception": "P0001",
    "no_data_found": "P0002",
    "too_many_rows": "P0003",
    "assert_failure": "P0004",
    "internal_error": "XX000",
    "data_corrupted": "XX001",
    "index_corrupted": "XX002",
}


class PgError(Exception):
    """An error with an explicit SQLSTATE, raised by the session layer
    for conditions sqlite cannot name (cancelled queries, transaction
    misuse, unsupported features)."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


# sqlite message fragment -> SQLSTATE name (checked in order; first
# match wins — sqlite3.Error carries no machine-readable subcode for
# most of these, so the text IS the classification, exactly what the
# stdlib's own exception mapping does)
_SQLITE_PATTERNS = (
    ("no such table", "undefined_table"),
    ("no such column", "undefined_column"),
    ("no such function", "undefined_function"),
    ("no such index", "undefined_object"),
    ("no such savepoint", "invalid_savepoint_specification"),
    ("already exists", "duplicate_table"),
    ("duplicate column name", "duplicate_column"),
    ("ambiguous column name", "ambiguous_column"),
    ("unique constraint failed", "unique_violation"),
    ("not null constraint failed", "not_null_violation"),
    ("check constraint failed", "check_violation"),
    ("foreign key constraint failed", "foreign_key_violation"),
    ("datatype mismatch", "datatype_mismatch"),
    ("syntax error", "syntax_error"),
    ("unrecognized token", "syntax_error"),
    ("incomplete input", "syntax_error"),
    ("wrong number of arguments", "undefined_function"),
    ("too many terms in compound select", "statement_too_complex"),
    ("too many columns", "too_many_columns"),
    ("string or blob too big", "program_limit_exceeded"),
    ("database or disk is full", "disk_full"),
    ("out of memory", "out_of_memory"),
    ("interrupted", "query_canceled"),
    ("database is locked", "lock_not_available"),
    ("attempt to write a readonly database", "read_only_sql_transaction"),
    ("readonly database", "read_only_sql_transaction"),
    ("database disk image is malformed", "data_corrupted"),
)


def sqlstate_for(exc: BaseException) -> str:
    """The SQLSTATE a real PG server would send for this fault."""
    if isinstance(exc, PgError):
        return exc.code
    msg = str(exc).lower()
    if isinstance(exc, sqlite3.IntegrityError):
        for frag, name in _SQLITE_PATTERNS:
            if frag in msg:
                return SQLSTATE[name]
        return SQLSTATE["integrity_constraint_violation"]
    if isinstance(exc, sqlite3.Error):
        for frag, name in _SQLITE_PATTERNS:
            if frag in msg:
                return SQLSTATE[name]
        return SQLSTATE["internal_error"] if isinstance(
            exc, sqlite3.InternalError
        ) else SQLSTATE["syntax_error"]
    if isinstance(exc, (ValueError, TypeError)):
        return SQLSTATE["invalid_text_representation"]
    return SQLSTATE["internal_error"]
