"""Cluster flight recorder: HLC-stamped metric snapshots + the typed
event journal, in a bounded in-memory ring.

The convergence plane (PR 6) and the scenario matrix (PR 7) both
observe *end states*: a scrape is a point in time, and a failing matrix
cell says "did not converge" with no record of how the run evolved.
This module is the missing time axis — per-node history assembled into
one cluster timeline (``ClusterObserver.flight_timeline``):

* **snapshots** — on ``AgentConfig.flight_interval_s`` (default 1 s,
  wired like ``LoopHealthProbe``) the recorder captures counter DELTAS,
  current gauges, and windowed histogram quantiles from :class:`Metrics`
  in one registry-lock hold (``Metrics.snapshot_state``), HLC-stamped
  so cross-node alignment survives the clock-skew fault family (the
  HLC merges on every message receipt, pulling skewed nodes onto a
  shared axis the raw wall clock does not give);
* **events** — discrete protocol moments emitted at the seams that
  already exist in the runtime (sync session start/end, breaker and
  quarantine transitions, apply/write-group fallbacks, equivocation
  verdicts, crash/restart markers injected by
  ``devcluster.run_crash_schedule``), each a typed record from the
  :data:`EVENT_KINDS` registry — the doc-drift lint
  (``tests/test_telemetry.py``) keeps the registry and
  ``docs/telemetry.md`` in lockstep, like the metric series;
* **export** — optional on-disk jsonl (``[telemetry.flight] path``)
  with the spans-export discipline from ``tracing.py``: bounded file,
  ONE rotation to ``path.1``, further records dropped and counted
  (``corro_flight_export_dropped_total``);
* **crash dump** — an unhandled agent-task exception flushes the whole
  ring to ``<db dir>/flight_crash.jsonl`` (the agent's task supervisor
  calls :meth:`crash_dump`), so a dead loop ships its own post-mortem.

The ring itself is a ``deque(maxlen=ring_max)``: memory is bounded by
construction, and the admin ``flight dump`` / ``flight events``
commands read it live.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional

# -- the typed event registry ------------------------------------------
#
# Every kind the journal may carry, with its meaning.  Emission sites
# call Agent._flight_event(kind, ...); an unknown kind raises — the
# registry IS the schema, and the doc-drift lint keeps docs/telemetry.md
# carrying one row per kind (and no phantom rows).

EVENT_KINDS: Dict[str, str] = {
    "sync_client_start": "outbound sync session opened (peer, needs)",
    "sync_client_end": "outbound sync session finished (changes, bytes,"
                       " complete flag)",
    "sync_server_start": "inbound sync session accepted (peer)",
    "sync_server_end": "inbound sync session finished (needs served,"
                       " bytes)",
    "breaker_open": "per-peer circuit breaker opened (addr)",
    "breaker_close": "per-peer circuit breaker closed on half-open"
                     " success (addr)",
    "quarantine": "member quarantine transition (actor/addr, on,"
                  " reason=breaker|equivocation|expired)",
    "apply_group_fallback": "merged apply transaction aborted, fell back"
                            " to per-changeset applies",
    "write_group_fallback": "write routed to the per-transaction oracle"
                            " (reason=stmt|abort)",
    "equivocation": "hostile-changeset verdict (actor, kind=content|"
                    "span|quarantined)",
    "snap_serve": "served one whole-database snapshot to a"
                  " bootstrapping peer (peer, bytes)",
    "snap_install": "installed a served snapshot: digest verified,"
                    " identity rewritten, file atomically swapped in"
                    " (peer, bytes)",
    "snap_abort": "discarded a staged snapshot cleanly (reason="
                  "snap_digest|snap_stream|snap_offer|snap_prepare|"
                  "snap_stale); the previous database is untouched",
    "crash": "non-graceful stop injected by devcluster.run_crash_schedule",
    "restart": "respawn from the same node directory after an injected"
               " crash",
    "crash_dump": "the flight ring was flushed by the unhandled-"
                  "exception supervisor (reason)",
}


class FlightRecorder:
    """One agent's flight ring: snapshots + events, HLC-stamped."""

    def __init__(self, metrics, clock, interval: float = 1.0,
                 ring_max: int = 512,
                 export_path: Optional[str] = None,
                 export_max_bytes: int = 64 * 1024 * 1024,
                 crash_path: Optional[str] = None,
                 node: Optional[str] = None,
                 timebase=None):
        from corrosion_tpu.clock import SYSTEM_CLOCK

        self.metrics = metrics
        self.clock = clock
        # ``clock`` is the HLC (the merge axis); ``timebase`` is the
        # agent's injectable Clock — the wall half of every stamp and
        # the snapshot cadence, so a virtual-time campaign journals
        # deterministic timestamps
        self.timebase = timebase or SYSTEM_CLOCK
        self.interval = max(0.01, float(interval))
        self.node = node
        self._ring: deque = deque(maxlen=max(8, int(ring_max)))
        self._lock = threading.Lock()
        self._last_counters: Dict[str, float] = {}
        self.snapshots = 0
        self.events = 0
        self.crash_path = crash_path
        # jsonl export, spans-export discipline (tracing.py): bounded,
        # one rotation, then drops counted — but per-RECORDER state, not
        # process-global (each agent owns its own flight file)
        self._export_path = export_path
        self._export_max_bytes = max(0, int(export_max_bytes))
        self._export_bytes = 0
        self._export_rotated = False
        self._export_dead = False
        self.export_dropped = 0
        self._export_pending: List[str] = []
        # sink/rotation state lock, distinct from the ring lock: disk
        # writes must never block an event() on the loop (RLock: the
        # rotation paths drop-count while already holding it)
        self._io_lock = threading.RLock()
        self._sink = None
        if export_path:
            self._sink = open(export_path, "a", buffering=1)
            try:
                self._export_bytes = os.path.getsize(export_path)
            except OSError:
                self._export_bytes = 0

    # -- stamping ------------------------------------------------------

    def _stamp(self) -> tuple:
        """(hlc, wall) for one record: an HLC OBSERVATION (what
        new_timestamp would mint, without advancing the clock —
        telemetry must not mutate protocol clock state), the merge axis
        the cluster timeline sorts on."""
        return int(self.clock.observe_timestamp()), self.timebase.wall()

    # -- the event journal ---------------------------------------------

    def event(self, kind: str, /, **attrs) -> None:
        """Journal one typed event.  Thread-safe (seams fire from worker
        threads and the loop alike); unknown kinds raise — the registry
        is the schema and the doc lint depends on it being closed.
        ``kind`` is positional-only so an event may carry a ``kind``
        attribute of its own (an equivocation verdict's detection
        kind)."""
        if kind not in EVENT_KINDS:
            raise ValueError(f"unregistered flight event kind {kind!r}")
        hlc, wall = self._stamp()
        rec = {"t": "event", "kind": kind, "hlc": hlc, "wall": wall}
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            self._ring.append(rec)
            self.events += 1
        self._export(rec)

    # -- the snapshot loop (runs ON the loop, like LoopHealthProbe) ----

    async def run(self) -> None:
        import asyncio

        while True:
            await self.timebase.sleep(self.interval)
            # off-loop: the snapshot sorts every histogram window for
            # its quantiles — worker-thread work, not loop work (the
            # stall probe must never attribute a stall to its sibling)
            await asyncio.to_thread(self.snapshot_once)

    def snapshot_once(self) -> dict:
        """Capture one metric snapshot into the ring: counter deltas
        since the previous snapshot, current gauges, and windowed
        histogram p50/p99 — all from ONE registry-lock hold."""
        counters, gauges, quantiles = self.metrics.snapshot_state()
        hlc, wall = self._stamp()
        with self._lock:
            deltas = {
                k: round(v - self._last_counters.get(k, 0.0), 6)
                for k, v in counters.items()
                if v != self._last_counters.get(k, 0.0)
            }
            self._last_counters = counters
            rec = {
                "t": "snap", "hlc": hlc, "wall": wall,
                "counters_delta": deltas,
                "gauges": gauges,
                "quantiles": quantiles,
            }
            self._ring.append(rec)
            self.snapshots += 1
        self._export(rec)
        # the snapshot path runs off-loop (run()'s to_thread hop), so
        # it doubles as the export writer: events enqueued since the
        # last interval reach disk here
        self.flush_export()
        return rec

    # -- reading -------------------------------------------------------

    def entries(self, limit: int = 0, kind: Optional[str] = None
                ) -> List[dict]:
        """Ring contents oldest-first.  ``kind``: "snap"/"event" filter
        BEFORE the limit; non-positive limit = everything held."""
        with self._lock:
            out = list(self._ring)
        if kind is not None:
            out = [e for e in out if e["t"] == kind]
        if limit > 0:
            out = out[-limit:]
        return out

    def snapshot(self) -> dict:
        """Recorder state summary (admin surface)."""
        with self._lock:
            held = len(self._ring)
        return {
            "interval_s": self.interval,
            "ring_max": self._ring.maxlen,
            "held": held,
            "snapshots": self.snapshots,
            "events": self.events,
            "export_path": self._export_path,
            "export_dropped": self.export_dropped,
        }

    # -- jsonl export (the spans-export rotation discipline) -----------
    #
    # Records ENQUEUE here and reach disk in flush_export(), which runs
    # off the event loop (the snapshot worker thread, close(), and the
    # crash dump): events are journaled inline at async protocol seams,
    # and a slow disk must stall a worker, never the loop the recorder
    # exists to observe.

    EXPORT_PENDING_MAX = 4096  # unflushed lines; beyond = counted drops

    def _export(self, rec: dict) -> None:
        if self._export_path is None:
            return
        line = json.dumps(
            rec if self.node is None else dict(rec, node=self.node)
        ) + "\n"
        with self._lock:
            if len(self._export_pending) >= self.EXPORT_PENDING_MAX:
                drop = True
            else:
                self._export_pending.append(line)
                drop = False
        if drop:
            self._drop(1)

    def flush_export(self) -> None:
        """Write pending export lines to the sink — worker-thread work
        (called from the snapshot loop's to_thread hop, close(), and
        crash_dump(); safe to call anytime).  The ring lock is held
        only to SWAP the pending list out: disk writes and rotation
        happen under the separate io lock, so an event() on the loop
        never waits behind a slow disk."""
        with self._lock:
            pending, self._export_pending = self._export_pending, []
        if not pending or self._export_path is None:
            return
        with self._io_lock:
            if self._sink is None:
                # a dead sink keeps COUNTING drops (the tracing.py
                # lesson: a frozen counter reads as a healthy export
                # while records vanish)
                if self._export_dead:
                    self._drop(len(pending))
                return
            for line in pending:
                if not self._make_room_io_locked(len(line)):
                    continue
                try:
                    self._sink.write(line)
                    self._export_bytes += len(line)
                except OSError:
                    pass

    def _drop(self, n: int) -> None:
        # RLock: callers may already hold the io lock (rotation paths)
        with self._io_lock:
            self.export_dropped += n
        self.metrics.counter("corro_flight_export_dropped_total", n)

    def _make_room_io_locked(self, line_len: int) -> bool:
        """Under ``_io_lock``: room for one more line, rotating ONCE at the
        byte cap, dropping (counted) after that — bounded exactly like
        the spans export (on-disk footprint ≤ 2 × max_bytes)."""
        if (self._export_max_bytes <= 0
                or self._export_bytes + line_len <= self._export_max_bytes):
            return True
        if self._export_rotated:
            self._drop(1)
            return False
        self._export_rotated = True
        try:
            self._sink.close()
        except OSError:
            pass
        try:
            os.replace(self._export_path, self._export_path + ".1")
        except OSError:
            pass
        try:
            self._sink = open(self._export_path, "w", buffering=1)
        except OSError:
            self._sink = None
            self._export_dead = True
            self._drop(1)
            return False
        self._export_bytes = 0
        return True

    def close(self) -> None:
        self.flush_export()
        with self._io_lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    pass
                self._sink = None

    # -- crash dump ----------------------------------------------------

    def crash_dump(self, reason: str) -> Optional[str]:
        """Flush the whole ring to ``crash_path`` (one json line per
        record, newest ring state, overwriting a previous dump) — called
        by the agent's task supervisor on an unhandled exception so the
        history leading up to the death survives it.  Returns the path
        written, or None when no crash path was configured."""
        try:
            self.event("crash_dump", reason=reason)
        except ValueError:  # pragma: no cover - registry is closed
            pass
        self.flush_export()
        if not self.crash_path:
            return None
        entries = self.entries()
        try:
            with open(self.crash_path, "w") as f:
                for rec in entries:
                    f.write(json.dumps(rec) + "\n")
        except OSError:
            return None
        return self.crash_path
