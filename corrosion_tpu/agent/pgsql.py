"""PG→SQLite SQL translation over a real tokenizer.

The reference parses PG SQL with ``sqlparser`` and re-emits a SQLite
AST (``crates/corro-pg/src/lib.rs:545-``, ~6k lines).  SQLite's dialect
already overlaps PG's enough that a faithful token-level rewrite covers
the differences that matter on the wire; what this module guarantees —
and the old regex pass did not — is that every transformation is
TOKEN-AWARE: nothing ever rewrites inside string literals, quoted
identifiers, comments, or dollar-quoted bodies.

Lexed token kinds: ``str`` (standard ``'…'`` with doubled quotes),
``estr`` (``E'…'`` with backslash escapes), ``dollar`` (``$tag$…$tag$``
bodies), ``qident`` (``"…"``), ``param`` (``$N``), ``num``, ``word``,
``op`` (multi-char operators first: ``::``, ``<=``, ``>=``, ``<>``,
``!=``, ``||``), ``comment`` (``--`` and nested ``/* */``), ``ws``.

Translations applied:

* ``$N`` placeholders → ``?`` (param order returned for out-of-order /
  repeated references);
* ``::type`` / ``::type[]`` casts dropped (sqlite affinity governs);
* ``E'…'`` escape-strings and ``$tag$…$tag$`` dollar-quotes →
  standard quoted literals;
* ``now()`` / bare ``current_timestamp`` → ``datetime('now')``,
  ``current_date`` → ``date('now')``, ``current_time`` →
  ``time('now')``;
* ``ILIKE`` → ``LIKE`` (sqlite LIKE is already case-insensitive for
  ASCII);
* comments stripped (sqlite accepts them, but dropping them keeps the
  write-detection and catalog-routing heads honest).
"""

from __future__ import annotations

import re
from typing import List, Tuple

_OPS = ("::", "<=", ">=", "<>", "!=", "||", ":=")
_WORD_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
_NUM_RE = re.compile(r"(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?")
_DOLLAR_RE = re.compile(r"\$([A-Za-z_]\w*)?\$")


class PgSqlError(ValueError):
    pass


def tokenize(sql: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        # comments ---------------------------------------------------
        if ch == "-" and sql.startswith("--", i):
            j = sql.find("\n", i)
            j = n if j < 0 else j + 1
            out.append(("comment", sql[i:j]))
            i = j
            continue
        if ch == "/" and sql.startswith("/*", i):
            depth, j = 1, i + 2
            while j < n and depth:
                if sql.startswith("/*", j):
                    depth += 1
                    j += 2
                elif sql.startswith("*/", j):
                    depth -= 1
                    j += 2
                else:
                    j += 1
            out.append(("comment", sql[i:j]))
            i = j
            continue
        # strings ----------------------------------------------------
        if ch == "'" or (
            ch in "eE" and i + 1 < n and sql[i + 1] == "'"
        ):
            kind = "str" if ch == "'" else "estr"
            j = i + (1 if kind == "str" else 2)
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        j += 2
                        continue
                    break
                if kind == "estr" and sql[j] == "\\" and j + 1 < n:
                    j += 2
                    continue
                j += 1
            if j >= n:
                raise PgSqlError("unterminated string literal")
            out.append((kind, sql[i:j + 1]))
            i = j + 1
            continue
        if ch == '"':
            j = i + 1
            while j < n:
                if sql[j] == '"':
                    if j + 1 < n and sql[j + 1] == '"':
                        j += 2
                        continue
                    break
                j += 1
            if j >= n:
                raise PgSqlError("unterminated quoted identifier")
            out.append(("qident", sql[i:j + 1]))
            i = j + 1
            continue
        # dollar: $N param or $tag$…$tag$ quote ----------------------
        if ch == "$":
            if i + 1 < n and sql[i + 1].isdigit():
                j = i + 1
                while j < n and sql[j].isdigit():
                    j += 1
                out.append(("param", sql[i:j]))
                i = j
                continue
            m = _DOLLAR_RE.match(sql, i)
            if m:
                close = sql.find(m.group(0), m.end())
                if close < 0:
                    raise PgSqlError("unterminated dollar-quoted string")
                out.append(("dollar", sql[i:close + len(m.group(0))]))
                i = close + len(m.group(0))
                continue
            out.append(("op", "$"))
            i += 1
            continue
        # whitespace / words / numbers / operators -------------------
        if ch.isspace():
            j = i
            while j < n and sql[j].isspace():
                j += 1
            out.append(("ws", sql[i:j]))
            i = j
            continue
        m = _WORD_RE.match(sql, i)
        if m:
            out.append(("word", m.group(0)))
            i = m.end()
            continue
        m = _NUM_RE.match(sql, i)
        if m:
            out.append(("num", m.group(0)))
            i = m.end()
            continue
        for op in _OPS:
            if sql.startswith(op, i):
                out.append(("op", op))
                i += len(op)
                break
        else:
            out.append(("op", ch))
            i += 1
    return out


def _std_quote(body: str) -> str:
    return "'" + body.replace("'", "''") + "'"


_E_ESCAPES = {
    "n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
    "\\": "\\", "'": "'",
}


def _decode_estr(text: str) -> str:
    """E'…' body → plain string value (simple, hex, unicode and octal
    escapes per the PG escape-string rules)."""
    body = text[2:-1]
    out: List[str] = []
    i = 0
    while i < len(body):
        ch = body[i]
        if ch == "\\" and i + 1 < len(body):
            nxt = body[i + 1]
            if nxt in _E_ESCAPES:
                out.append(_E_ESCAPES[nxt])
                i += 2
                continue
            if nxt == "x":
                m = re.match(r"[0-9A-Fa-f]{1,2}", body[i + 2:])
                if m:
                    out.append(chr(int(m.group(0), 16)))
                    i += 2 + len(m.group(0))
                    continue
            if nxt in ("u", "U"):
                width = 4 if nxt == "u" else 8
                m = re.match(rf"[0-9A-Fa-f]{{{width}}}", body[i + 2:])
                if m:
                    out.append(chr(int(m.group(0), 16)))
                    i += 2 + width
                    continue
                raise PgSqlError(f"invalid \\{nxt} escape")
            m = re.match(r"[0-7]{1,3}", body[i + 1:])
            if m:
                out.append(chr(int(m.group(0), 8)))
                i += 1 + len(m.group(0))
                continue
            out.append(nxt)
            i += 2
            continue
        if ch == "'" and i + 1 < len(body) and body[i + 1] == "'":
            out.append("'")
            i += 2
            continue
        out.append(ch)
        i += 1
    return "".join(out)


_BARE_FUNC_WORDS = {
    "current_timestamp": "datetime('now')",
    "current_date": "date('now')",
    "current_time": "time('now')",
}
_CALL_FUNC_WORDS = {
    "now": "datetime('now')",
}


def translate_query(sql: str) -> Tuple[str, List[int]]:
    """PG SQL → SQLite SQL + $N parameter order (tokenizer pass)."""
    try:
        tokens = tokenize(sql)
    except PgSqlError:
        # ship the text through unchanged; sqlite reports the error
        return sql, []
    out: List[str] = []
    order: List[int] = []
    transform_tokens(tokens, out, order)
    return "".join(out), order


def transform_tokens(tokens: List[Tuple[str, str]], out: List[str],
                     order: List[int]) -> None:
    """The PG→SQLite token transforms over one token run, appending
    text to ``out`` and $N indices to ``order``.  Shared by the whole-
    string :func:`translate_query` and the AST emitter
    (``agent/pgparse.py``), which applies it per expression slice."""

    def next_code(k: int) -> int:
        """Index of the next non-ws/comment token after k, or -1."""
        for j in range(k + 1, len(tokens)):
            if tokens[j][0] not in ("ws", "comment"):
                return j
        return -1

    i = 0
    while i < len(tokens):
        kind, text = tokens[i]
        if kind == "param":
            order.append(int(text[1:]))
            out.append("?")
        elif kind == "op" and text == "::":
            # drop the cast — and the FULL PG type name: a word or
            # "qident" head, an optional second word (double PRECISION,
            # character VARYING), an optional (precision[,scale]) group,
            # an optional WITH/WITHOUT TIME ZONE tail, an optional []
            j = next_code(i)
            if j >= 0 and tokens[j][0] in ("word", "qident"):
                end = j
                k = next_code(end)
                # schema-qualified type names (pg_catalog.int4): hop
                # each .qualifier before the shape suffixes
                while (
                    k >= 0 and tokens[k][1] == "."
                    and (m := next_code(k)) >= 0
                    and tokens[m][0] in ("word", "qident")
                ):
                    end, k = m, next_code(m)
                if (
                    k >= 0 and tokens[k][0] == "word"
                    and tokens[k][1].lower() in ("precision", "varying")
                ):
                    end, k = k, next_code(k)
                if k >= 0 and tokens[k][1] == "(":
                    depth, m = 1, k
                    while depth and (m := next_code(m)) >= 0:
                        if tokens[m][1] == "(":
                            depth += 1
                        elif tokens[m][1] == ")":
                            depth -= 1
                    if depth == 0:
                        end, k = m, next_code(m)
                if (
                    k >= 0 and tokens[k][0] == "word"
                    and tokens[k][1].lower() in ("with", "without")
                    and (m := next_code(k)) >= 0
                    and tokens[m][1].lower() == "time"
                    and (m2 := next_code(m)) >= 0
                    and tokens[m2][1].lower() == "zone"
                ):
                    end, k = m2, next_code(m2)
                if (
                    k >= 0 and tokens[k][1] == "["
                    and (m := next_code(k)) >= 0 and tokens[m][1] == "]"
                ):
                    end = m
                i = end + 1
                continue
            out.append(text)
        elif kind == "estr":
            out.append(_std_quote(_decode_estr(text)))
        elif kind == "dollar":
            tag_end = text.index("$", 1) + 1
            out.append(_std_quote(text[tag_end:-tag_end]))
        elif kind == "comment":
            out.append(" ")
        elif kind == "word":
            low = text.lower()
            if low in _BARE_FUNC_WORDS:
                j = next_code(i)
                if j < 0 or tokens[j][1] != "(":
                    out.append(_BARE_FUNC_WORDS[low])
                else:
                    out.append(text)
            elif low in _CALL_FUNC_WORDS:
                j = next_code(i)
                k = next_code(j) if j >= 0 else -1
                if (
                    j >= 0 and tokens[j][1] == "("
                    and k >= 0 and tokens[k][1] == ")"
                ):
                    out.append(_CALL_FUNC_WORDS[low])
                    i = k + 1
                    continue
                out.append(text)
            elif low == "ilike":
                out.append("LIKE")
            else:
                out.append(text)
        else:
            out.append(text)
        i += 1


def split_statements(query: str) -> List[str]:
    """Split on top-level semicolons, string/comment/dollar-aware."""
    try:
        tokens = tokenize(query)
    except PgSqlError:
        return [query]
    parts: List[str] = []
    buf: List[str] = []
    for kind, text in tokens:
        if kind == "op" and text == ";":
            parts.append("".join(buf))
            buf = []
        elif kind == "comment":
            # dropped here so downstream first-word dispatch (write
            # detection, BEGIN/COMMIT handling) sees real SQL
            buf.append(" ")
        else:
            buf.append(text)
    if "".join(buf).strip():
        parts.append("".join(buf))
    return parts
