"""The `corrosion` command-line interface.

Parity: ``crates/corrosion/src/main.rs`` command set — ``agent``,
``query``, ``exec``, ``backup``, ``restore``, ``cluster members`` /
``membership-states``, ``sync generate`` / ``reconcile-gaps``, ``locks``,
``actor version``, ``subs list`` / ``info``, ``reload``, ``template``,
``consul sync``.

Run as ``python -m corrosion_tpu.cli <command>`` (or the ``corrosion-tpu``
entry point).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import signal
import sys
from typing import List, Optional


def _client(args):
    from corrosion_tpu.client import CorrosionApiClient

    host, _, port = args.api_addr.rpartition(":")
    return CorrosionApiClient((host or "127.0.0.1", int(port)), token=args.token)


def _admin(args):
    from corrosion_tpu.agent.admin import AdminClient

    return AdminClient(args.admin_path)


def cmd_agent(args) -> int:
    from corrosion_tpu.agent.config import load_config
    from corrosion_tpu.agent.runtime import Agent

    cfg = load_config(args.config)

    async def main():
        agent = Agent(cfg)
        await agent.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)

        def _do_reload():
            # blocking: config file I/O + a storage-lock acquire that
            # HIGH-tier apply traffic may delay — never on the loop
            fresh = load_config(args.config)
            if fresh.schema_sql:
                touched = agent.apply_schema_sql(fresh.schema_sql)
                print(f"reload: schema applied, touched={touched}",
                      flush=True)

        async def _reload_task():
            try:
                await asyncio.to_thread(_do_reload)
            except Exception as e:  # surfaced, never fatal to the agent
                print(f"reload failed: {e}", flush=True)

        def reload_schema():
            # SIGHUP re-reads the schema files and applies additions
            # (command/reload.rs + SIGHUP handling in the reference)
            loop.create_task(_reload_task())

        loop.add_signal_handler(signal.SIGHUP, reload_schema)
        # the banner is the readiness signal — every signal handler must
        # be registered BEFORE it, or a prompt operator's SIGHUP hits
        # the default action and kills the process
        print(
            f"agent {agent.actor_id.hex()} gossip={agent.gossip_addr} "
            f"api={agent.api_addr}",
            flush=True,
        )
        await stop.wait()
        await agent.stop()

    asyncio.run(main())
    return 0


def cmd_query(args) -> int:
    client = _client(args)
    stmt = [args.sql, args.param] if args.param else args.sql
    cols, rows = client.query(stmt)
    if args.columns:
        print("\t".join(cols))
    for row in rows:
        print("\t".join("" if v is None else str(v) for v in row))
    return 0


def cmd_exec(args) -> int:
    client = _client(args)
    stmt = [args.sql, args.param] if args.param else [args.sql]
    out = client.execute([stmt])
    print(json.dumps(out))
    return 0


def cmd_reload(args) -> int:
    client = _client(args)
    out = client.schema_from_paths(args.paths)
    print(json.dumps(out))
    return 0


def cmd_backup(args) -> int:
    from corrosion_tpu.agent.backup import backup

    backup(args.db, args.out)
    print(f"backed up {args.db} -> {args.out}")
    return 0


def cmd_restore(args) -> int:
    from corrosion_tpu.agent.backup import restore

    restore(args.backup, args.db)
    print(f"restored {args.backup} -> {args.db}")
    return 0


def cmd_admin(args, command: str, **kwargs) -> int:
    client = _admin(args)
    try:
        out = client.call(command, **kwargs)
        print(json.dumps(out, indent=2))
    finally:
        client.close()
    return 0


def cmd_rtt_dump(args) -> int:
    """Export this node's Members RTT-ring tier distribution as
    measured-topology JSON (``bench.py --frontier --topology
    measured_ring`` consumes it directly)."""
    client = _admin(args)
    try:
        kwargs = {}
        if args.tier_edges_ms:
            kwargs["tier_edges_ms"] = [
                float(e) for e in args.tier_edges_ms.split(",")
            ]
        # call() returns the unwrapped ``ok`` payload and raises on error
        doc = client.call("rtt_dump", **kwargs)
    finally:
        client.close()
    text = json.dumps(doc, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"wrote {args.out} ({doc['members_sampled']} members, "
              f"{doc['rtt_tiers']} tiers)")
    else:
        print(text)
    return 0


def cmd_template(args) -> int:
    from corrosion_tpu.tpl import render_loop, render_once

    host, _, port = args.api_addr.rpartition(":")
    addr = (host or "127.0.0.1", int(port))
    if args.once:
        render_once(addr, args.template, args.out, token=args.token)
    else:
        render_loop(addr, args.template, args.out, token=args.token)
    return 0


def cmd_consul_sync(args) -> int:
    from corrosion_tpu.consul import sync_loop

    host, _, port = args.api_addr.rpartition(":")
    sync_loop(
        (host or "127.0.0.1", int(port)),
        consul_addr=args.consul_addr,
        token=args.token,
        once=args.once,
    )
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="corrosion-tpu")
    p.add_argument("--api-addr", default="127.0.0.1:8080")
    p.add_argument("--admin-path", default="./admin.sock")
    p.add_argument("--token", default=None, help="API bearer token")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("agent", help="run the agent")
    sp.add_argument("--config", "-c", default=None)
    sp.set_defaults(fn=cmd_agent)

    sp = sub.add_parser("query", help="run a read-only SQL statement")
    sp.add_argument("sql")
    sp.add_argument("--param", action="append")
    sp.add_argument("--columns", action="store_true")
    sp.set_defaults(fn=cmd_query)

    sp = sub.add_parser("exec", help="execute a write statement")
    sp.add_argument("sql")
    sp.add_argument("--param", action="append")
    sp.set_defaults(fn=cmd_exec)

    sp = sub.add_parser("reload", help="apply schema files")
    sp.add_argument("paths", nargs="+")
    sp.set_defaults(fn=cmd_reload)

    sp = sub.add_parser("backup")
    sp.add_argument("db")
    sp.add_argument("out")
    sp.set_defaults(fn=cmd_backup)

    sp = sub.add_parser("restore")
    sp.add_argument("backup")
    sp.add_argument("db")
    sp.set_defaults(fn=cmd_restore)

    cluster = sub.add_parser("cluster").add_subparsers(dest="sub", required=True)
    sp = cluster.add_parser("members")
    sp.set_defaults(fn=lambda a: cmd_admin(a, "cluster_members"))
    sp = cluster.add_parser("membership-states")
    sp.set_defaults(fn=lambda a: cmd_admin(a, "cluster_members"))
    sp = cluster.add_parser(
        "rejoin", help="renew identity and re-announce to the cluster"
    )
    sp.set_defaults(fn=lambda a: cmd_admin(a, "cluster_rejoin"))
    sp = cluster.add_parser(
        "set-id", help="move this node to another cluster id"
    )
    sp.add_argument("cluster_id", type=int)
    sp.set_defaults(
        fn=lambda a: cmd_admin(a, "cluster_set_id", cluster_id=a.cluster_id)
    )

    rtt = sub.add_parser(
        "rtt", help="Members RTT-ring topology tools"
    ).add_subparsers(dest="sub", required=True)
    sp = rtt.add_parser(
        "dump",
        help="export the RTT tier distribution as measured-topology "
        "JSON (bench.py --frontier --topology measured_ring)",
    )
    sp.add_argument("--out", default=None,
                    help="write JSON here instead of stdout")
    sp.add_argument("--tier-edges-ms", default=None,
                    help="comma-separated tier edges in ms "
                    "(default: 6,12,24,48,96)")
    sp.set_defaults(fn=cmd_rtt_dump)

    syncp = sub.add_parser("sync").add_subparsers(dest="sub", required=True)
    sp = syncp.add_parser("generate")
    sp.set_defaults(fn=lambda a: cmd_admin(a, "sync_generate"))
    sp = syncp.add_parser("reconcile-gaps")
    sp.set_defaults(fn=lambda a: cmd_admin(a, "sync_reconcile_gaps"))
    sp = syncp.add_parser(
        "sessions",
        help="live sync sessions (both roles): peer, age, "
             "needs-remaining, bytes",
    )
    sp.set_defaults(fn=lambda a: cmd_admin(a, "sync_sessions"))

    flight = sub.add_parser(
        "flight", help="the flight recorder's bounded ring"
    ).add_subparsers(dest="sub", required=True)
    sp = flight.add_parser(
        "dump", help="recorder state + every held record (snapshots "
                     "and events), oldest first"
    )
    sp.add_argument("--limit", type=int, default=0,
                    help="trailing records only (0 = all held)")
    sp.set_defaults(fn=lambda a: cmd_admin(
        a, "flight_dump", limit=a.limit
    ))
    sp = flight.add_parser(
        "events", help="the typed event journal alone"
    )
    sp.add_argument("--limit", type=int, default=0)
    sp.set_defaults(fn=lambda a: cmd_admin(
        a, "flight_events", limit=a.limit
    ))

    sp = sub.add_parser("locks")
    sp.set_defaults(fn=lambda a: cmd_admin(a, "locks"))

    trace = sub.add_parser("trace").add_subparsers(dest="sub", required=True)
    sp = trace.add_parser("spans", help="recent finished spans")
    sp.add_argument("--limit", type=int, default=100)
    sp.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="only spans of this trace id (assemble one "
                         "cross-node trace from each node's ring)")
    sp.set_defaults(fn=lambda a: cmd_admin(
        a, "trace_spans", limit=a.limit,
        **({"trace": a.trace} if a.trace else {}),
    ))

    sp = sub.add_parser(
        "health",
        help="runtime health: loop stall probe, queue depths, the "
             "node's own convergence-lag measurement",
    )
    sp.set_defaults(fn=lambda a: cmd_admin(a, "health"))

    actor = sub.add_parser("actor").add_subparsers(dest="sub", required=True)
    sp = actor.add_parser("version")
    sp.add_argument("--actor", default=None)
    sp.set_defaults(
        fn=lambda a: cmd_admin(
            a, "actor_version", **({"actor": a.actor} if a.actor else {})
        )
    )

    subs = sub.add_parser("subs").add_subparsers(dest="sub", required=True)
    sp = subs.add_parser("list")
    sp.set_defaults(fn=lambda a: cmd_admin(a, "subs_list"))
    sp = subs.add_parser("info")
    sp.add_argument("id")
    sp.set_defaults(fn=lambda a: cmd_admin(a, "subs_info", id=a.id))

    sp = sub.add_parser("template", help="render a template from live queries")
    sp.add_argument("template")
    sp.add_argument("out")
    sp.add_argument("--once", action="store_true")
    sp.set_defaults(fn=cmd_template)

    consul = sub.add_parser("consul").add_subparsers(dest="sub", required=True)
    sp = consul.add_parser("sync")
    sp.add_argument("--consul-addr", default="127.0.0.1:8500")
    sp.add_argument("--once", action="store_true")
    sp.set_defaults(fn=cmd_consul_sync)

    # corrosion db lock <cmd> (main.rs:493-525): hold every sqlite file
    # lock while an external command runs against the frozen database
    db = sub.add_parser("db").add_subparsers(dest="sub", required=True)
    sp = db.add_parser("lock", help="run a command holding all DB locks")
    sp.add_argument("db_path")
    sp.add_argument("command",
                    help="argv-split and run without a shell (no pipes/"
                         "redirects)")
    sp.add_argument("--timeout", type=float, default=30.0)
    sp.set_defaults(fn=cmd_db_lock)

    # corrosion tls {ca,server,client} generate (main.rs:707-760)
    tls = sub.add_parser(
        "tls", help="generate a CA and signed server/client certs"
    ).add_subparsers(dest="sub", required=True)
    ca = tls.add_parser("ca").add_subparsers(dest="op", required=True)
    sp = ca.add_parser("generate")
    sp.add_argument("--dir", default=".")
    sp.add_argument("--days", type=int, default=3650)
    sp.set_defaults(fn=cmd_tls_ca)
    server = tls.add_parser("server").add_subparsers(dest="op", required=True)
    sp = server.add_parser("generate")
    sp.add_argument("names", nargs="+",
                    help="SANs: gossip IPs or DNS names")
    sp.add_argument("--dir", default=".")
    sp.add_argument("--ca-cert", default=None,
                    help="default: <dir>/ca.crt")
    sp.add_argument("--ca-key", default=None,
                    help="default: <dir>/ca.key")
    sp.add_argument("--days", type=int, default=365)
    sp.set_defaults(fn=cmd_tls_server)
    client = tls.add_parser("client").add_subparsers(dest="op", required=True)
    sp = client.add_parser("generate")
    sp.add_argument("--dir", default=".")
    sp.add_argument("--ca-cert", default=None,
                    help="default: <dir>/ca.crt")
    sp.add_argument("--ca-key", default=None,
                    help="default: <dir>/ca.key")
    sp.add_argument("--days", type=int, default=365)
    sp.set_defaults(fn=cmd_tls_client)

    return p


def cmd_db_lock(args) -> int:
    from corrosion_tpu.agent.dblock import run_locked

    return run_locked(args.db_path, args.command, timeout_s=args.timeout)


def _tls_generate(make_pair) -> int:
    """Run one cert-generation step with the dependency surfaced as an
    actionable message: ``agent/tls.py`` imports ``cryptography``
    lazily inside the generators, so on hosts without the package a
    bare ``corrosion-tpu tls ... generate`` used to die with a raw
    ModuleNotFoundError traceback instead of saying what to install.
    (Only cert GENERATION needs it — serving TLS from existing PEM
    files is pure stdlib ``ssl``.)"""
    try:
        cert, key = make_pair()
    except ImportError as e:
        print(
            "error: TLS certificate generation requires the "
            "'cryptography' package, which is not installed "
            f"({e}).\nInstall it with:  pip install cryptography\n"
            "(running an agent with EXISTING cert/key files needs "
            "only the stdlib)",
            file=sys.stderr,
        )
        return 1
    print(f"wrote {cert} and {key}")
    return 0


def cmd_tls_ca(args) -> int:
    from corrosion_tpu.agent.tls import generate_ca

    return _tls_generate(lambda: generate_ca(args.dir, days=args.days))


def cmd_tls_server(args) -> int:
    import os

    from corrosion_tpu.agent.tls import generate_server_cert

    return _tls_generate(lambda: generate_server_cert(
        args.dir,
        args.ca_cert or os.path.join(args.dir, "ca.crt"),
        args.ca_key or os.path.join(args.dir, "ca.key"),
        args.names, days=args.days,
    ))


def cmd_tls_client(args) -> int:
    import os

    from corrosion_tpu.agent.tls import generate_client_cert

    return _tls_generate(lambda: generate_client_cert(
        args.dir,
        args.ca_cert or os.path.join(args.dir, "ca.crt"),
        args.ca_key or os.path.join(args.dir, "ca.key"),
        days=args.days,
    ))


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except Exception as e:  # surfaced as a message, not a traceback
        from corrosion_tpu.client import ClientError

        if isinstance(e, (ClientError, OSError, RuntimeError, ValueError)):
            print(f"error: {e}", file=sys.stderr)
            return 1
        raise


if __name__ == "__main__":
    sys.exit(main())
